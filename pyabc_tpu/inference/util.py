"""Inference utilities: proposal, evaluation, weighting.

Reference parity: ``pyabc/inference_util.py::{create_simulate_function,
generate_valid_proposal, evaluate_proposal, create_prior_pdf,
create_transition_pdf, create_weight_function}``.

Two implementations of the same math live here:

1. **Host path** (`create_simulate_function`): a faithful scalar closure,
   exactly the reference's unit of distribution. It serves arbitrary Python
   models (SimpleModel, ScipyRV priors) and doubles as the *oracle* that the
   batched device kernel is property-tested against (SURVEY.md §7.3.5).

2. **Device path** (`DeviceContext`): the TPU inversion — one jitted XLA
   round kernel evaluates a whole batch of lanes: ancestor draw, model
   perturbation, transition perturbation (with in-kernel redraws-until-
   valid), simulation (`lax.switch` over models), distance, acceptance and
   the FULL importance weight, all fused. Per-generation state (epsilon,
   adaptive distance weights, fitted transitions, model probabilities) is
   passed as padded array arguments, so a whole ABC run compiles O(few)
   programs, not O(generations).

Importance weight (the SMC core, §3.5):
    w(theta, m) = model_prior(m) * prior_m(theta) * acc_weight
                  / ( [sum_anc p_{t-1}(anc) MPK(m|anc)] * K_m(theta) )
with K_m the transition density fitted on model-m particles of gen t-1.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.parameters import Parameter
from ..core.population import Particle
from ..core.random import round_key
from ..core.random_choice import fast_random_choice
from ..core.sumstat_spec import SumStatSpec
from ..model import JaxModel, Model


# ===========================================================================
# Host (scalar, reference-faithful) path
# ===========================================================================

def create_prior_pdf(model_prior_pmf, parameter_priors):
    def prior_pdf(m, theta):
        # pdf_host: the host closure must stay JAX-free — it runs inside
        # forked multiprocess workers where touching a JAX backend deadlocks
        return model_prior_pmf(m) * parameter_priors[m].pdf_host(theta)

    return prior_pdf


def create_transition_pdf(transitions, model_probabilities,
                          model_perturbation_kernel):
    """Joint proposal density of (m, theta) (reference create_transition_pdf)."""

    def transition_pdf(m, theta):
        model_factor = sum(
            p * model_perturbation_kernel.pmf(m, anc)
            for anc, p in model_probabilities.items()
        )
        import pandas as pd

        particle_factor = transitions[m].pdf(
            pd.Series(dict(theta))
        )
        return model_factor * float(particle_factor)

    return transition_pdf


def generate_valid_proposal(t, model_probabilities, model_perturbation_kernel,
                            transitions, model_prior_rvs, parameter_priors,
                            nr_samples_per_parameter: int = 1,
                            max_retries: int = 10000):
    """Draw (m, theta) with positive prior mass (reference
    generate_valid_proposal): ancestor model ~ p_{t-1}, perturb model, perturb
    parameters, retry until prior > 0."""
    if t == 0:
        m = model_prior_rvs()
        # rvs_host: numpy/scipy draw seeded from global np.random — workers
        # of the multiprocess samplers reseed np.random per fork; a JAX key
        # here would initialize an XLA backend after fork and deadlock
        theta = parameter_priors[m].rvs_host()
        return m, theta
    ms = np.asarray(list(model_probabilities.keys()))
    ps = np.asarray(list(model_probabilities.values()), np.float64)
    ps = ps / ps.sum()
    for _ in range(max_retries):
        m_anc = int(ms[fast_random_choice(ps)])
        m = model_perturbation_kernel.rvs(m_anc)
        if transitions[m].X is None:
            continue  # never-fitted model cannot propose
        theta_ser = transitions[m].rvs_single()
        theta = Parameter(dict(theta_ser))
        if parameter_priors[m].pdf_host(theta) > 0:
            return m, theta
    raise RuntimeError("could not generate a valid proposal")


def evaluate_proposal(m, theta, t, models, summary_statistics, distance_function,
                      eps, acceptor, x_0):
    """Simulate and accept-test one proposal (reference evaluate_proposal)."""
    model_result = models[m].accept(
        t, theta, summary_statistics, distance_function, eps, acceptor, x_0
    )
    return model_result


def create_weight_function(prior_pdf, transition_pdf,
                           nr_samples_per_parameter: int = 1):
    """w = prior * acc_weight / proposal (reference create_weight_function)."""

    def weight_function(m, theta, t, acceptance_weight: float):
        if t == 0:
            return float(acceptance_weight)
        fraction = prior_pdf(m, theta) / transition_pdf(m, theta)
        return float(acceptance_weight * fraction)

    return weight_function


def create_simulate_function(t, *, model_probabilities,
                             model_perturbation_kernel, transitions,
                             model_prior_rvs, model_prior_pmf,
                             parameter_priors, models,
                             summary_statistics, x_0, distance_function,
                             eps, acceptor,
                             evaluate: bool = True,
                             record_proposal_pd: bool = False
                             ) -> Callable[[], Particle]:
    """The reference's unit of distribution: a closure producing one Particle.

    With ``evaluate=False`` the particle is returned all-accepted without the
    accept test (calibration population, reference
    ``only_simulate_data_for_proposal``). With ``record_proposal_pd``, every
    particle carries the density of (m, theta) under the proposal it was
    drawn from (reference ``transition_pd_prev``) so record-keeping samplers
    can feed the AcceptanceRateScheme's importance reweighting.
    """
    prior_pdf = create_prior_pdf(model_prior_pmf, parameter_priors)
    transition_pdf = (
        create_transition_pdf(transitions, model_probabilities,
                              model_perturbation_kernel)
        if t > 0
        else None
    )

    def weight_function(m, theta, acceptance_weight):
        if t == 0 or transition_pdf is None:
            return float(acceptance_weight)
        return float(
            acceptance_weight * prior_pdf(m, theta) / transition_pdf(m, theta)
        )

    def proposal_pd(m, theta) -> float:
        if not record_proposal_pd:
            return float("nan")
        if t == 0 or transition_pdf is None:
            return float(prior_pdf(m, theta))
        return float(transition_pdf(m, theta))

    def simulate_one() -> Particle:
        m, theta = generate_valid_proposal(
            t, model_probabilities, model_perturbation_kernel, transitions,
            model_prior_rvs, parameter_priors,
        )
        if evaluate:
            result = evaluate_proposal(
                m, theta, t, models, summary_statistics, distance_function,
                eps, acceptor, x_0,
            )
            accepted = bool(result.accepted)
            weight = (
                weight_function(m, theta, result.weight) if accepted else 0.0
            )
            return Particle(
                m=m, parameter=theta, weight=weight,
                sum_stat=result.sum_stat, distance=float(result.distance),
                accepted=accepted, proposal_pd=proposal_pd(m, theta),
            )
        res = models[m].summary_statistics(t, theta, summary_statistics)
        d = distance_function(res.sum_stat, x_0, t, theta)
        return Particle(
            m=m, parameter=theta, weight=weight_function(m, theta, 1.0),
            sum_stat=res.sum_stat, distance=float(d), accepted=True,
            proposal_pd=proposal_pd(m, theta),
        )

    return simulate_one


# ===========================================================================
# Device (batched, jitted) path
# ===========================================================================

from ..utils import pow2_bucket as _pow2_bucket


def pad_transition_params(params: dict, n_cap: int, d_max: int) -> dict:
    """Zero-pad fitted transition params to static shapes.

    Zero weights on padded ancestors mean they are never resampled and
    contribute nothing to the mixture logpdf; zero-padded theta columns stay
    exactly zero through chol @ noise, so padded dims never perturb.
    """
    out = {}
    for k, v in params.items():
        v = np.asarray(v)
        if k in ("thetas", "thetas_c"):
            p = np.zeros((n_cap, d_max), v.dtype)
            p[: v.shape[0], : v.shape[1]] = v
        elif k in ("weights", "quad"):
            # padded ancestors carry weight 0, so a zero quad term is inert
            p = np.zeros((n_cap,), v.dtype)
            p[: v.shape[0]] = v
        elif k == "center":
            # padded dims center at 0, matching the zero-padded thetas
            p = np.zeros((d_max,), v.dtype)
            p[: v.shape[0]] = v
        elif k in ("chol", "prec"):
            p = np.zeros((d_max, d_max), v.dtype)
            p[: v.shape[0], : v.shape[1]] = v
        elif k in ("chols", "precs"):
            p = np.zeros((n_cap, d_max, d_max), v.dtype)
            p[: v.shape[0], : v.shape[1], : v.shape[2]] = v
        elif k == "logdets":
            # padded ancestors have weight 0; any finite logdet is inert
            p = np.zeros((n_cap,), v.dtype)
            p[: v.shape[0]] = v
        elif k == "logdet":
            p = v
        else:
            p = v
        out[k] = jnp.asarray(p)
    return out


@dataclass
class RoundResult:
    """Host-side copy of one device round (B lanes)."""

    ms: np.ndarray
    thetas: np.ndarray
    sumstats: np.ndarray
    distances: np.ndarray
    accepted: np.ndarray
    valid: np.ndarray
    log_weights: np.ndarray
    #: proposal log-density per lane (transition_pd_prev in log form)
    logqs: np.ndarray | None = None


class DeviceContext:
    """Builds & caches the jitted per-round generation kernels.

    One instance lives for the whole ABC run; kernels are traced per
    (batch_bucket, mode) where mode is 'prior' (generation 0 / calibration)
    or 'transition' (later generations). All per-generation quantities are
    array arguments.
    """

    N_REDRAWS = 4  # in-kernel proposal redraws against zero prior mass

    def __init__(self, *, models: Sequence[JaxModel], parameter_priors,
                 model_prior_logits, distance, acceptor, spec: SumStatSpec,
                 x_0_flat, transition_classes=None, transition_cls=None,
                 mesh=None):
        self.models = list(models)
        self.priors = list(parameter_priors)
        self.K = len(self.models)
        self.model_prior_logits = jnp.asarray(model_prior_logits, jnp.float32)
        self.distance = distance
        self.acceptor = acceptor
        self.spec = spec
        self.x0 = jnp.asarray(x_0_flat, jnp.float32)
        if transition_classes is None:
            if transition_cls is None:
                raise ValueError("transition_classes required")
            transition_classes = [transition_cls] * len(self.models)
        #: per-model transition class: its static device_rvs/device_logpdf
        #: are baked into that model's switch branch
        self.transition_classes = list(transition_classes)
        #: optional jax.sharding.Mesh with one axis: shard lanes over devices
        #: (the ICI replacement for the reference's Redis counters/queues —
        #: SURVEY.md §5.8; collectives are inserted by GSPMD)
        self.mesh = mesh
        self.d_max = max(m.space.dim for m in self.models)
        self._kernels: dict = {}
        #: the adopting run's SyncLedger (rebound by ABCSMC.run): the
        #: blocking fetches below must count into syncs_per_run (SYNC001)
        from ..observability import NULL_SYNC_LEDGER

        self.sync_ledger = NULL_SYNC_LEDGER

    def mesh_is_multihost(self) -> bool:
        """True when the mesh spans more than one process. Kernels that
        hand results back to the host replicate their outputs in this
        case (``out_shardings=NamedSharding(mesh, P())``) so EVERY
        process can device_get the full tree for the replicated
        persist/adaptation step — the all-gather over DCN at the chunk
        barrier is the reference's Redis result-queue drain."""
        return self.mesh is not None and len(
            {d.process_index for d in self.mesh.devices.flat}
        ) > 1

    # ------------------------------------------------------------------ build
    @staticmethod
    def _shard_lane_keys(keys, lane_sharding):
        """Lane-shard a vector of TYPED prng keys.

        Typed key arrays hide a trailing key-data dim (u32[B, 2] under a
        visible shape (B,)); newer jax/XLA versions validate sharding
        specs against the UNDERLYING rank, so a rank-1 spec on the typed
        array fails GSPMD validation ("tile assignment dimensions ...
        different than the input rank"). Constrain the raw key data with
        a rank-matched spec and re-wrap instead."""
        if lane_sharding is None:
            return keys
        from jax.sharding import NamedSharding, PartitionSpec as P

        data = jax.random.key_data(keys)
        spec = P(lane_sharding.spec[0], *([None] * (data.ndim - 1)))
        data = jax.lax.with_sharding_constraint(
            data, NamedSharding(lane_sharding.mesh, spec)
        )
        return jax.random.wrap_key_data(data)

    def _lane_prior(self, key, dyn):
        """One lane, generation 0: proposal from the prior."""
        km, kt, ksim, kacc = jax.random.split(key, 4)
        m = jax.random.categorical(km, self.model_prior_logits)
        theta, ss, logpri = self._switch_sim_prior(m, kt, ksim)
        d, accept, log_acc_w = self._accept_fn(
            kacc, ss, dyn["eps"], dyn["dist_params"], dyn["acc_params"]
        )
        return dict(
            m=m, theta=theta, sumstats=ss, distance=d,
            accepted=accept, valid=jnp.asarray(True),
            log_weight=log_acc_w,
            # proposal log-density (drawn from the prior): model prior x
            # parameter prior — the record's transition_pd_prev in log form
            logq=self.model_prior_logits[m] + logpri,
        )

    def _lane_calibration(self, key, dyn):
        """One lane, calibration: prior draw + simulate only (no accept test;
        the distance may itself still need this sample to initialize)."""
        km, kt, ksim = jax.random.split(key, 3)
        m = jax.random.categorical(km, self.model_prior_logits)
        theta, ss, logpri = self._switch_sim_prior(m, kt, ksim)
        return dict(
            m=m, theta=theta, sumstats=ss,
            distance=jnp.zeros(()), accepted=jnp.asarray(True),
            valid=jnp.asarray(True), log_weight=jnp.zeros(()),
            logq=self.model_prior_logits[m] + logpri,
        )

    def _switch_sim_prior(self, m, kt, ksim):
        def make_branch(i):
            model = self.models[i]
            prior = self.priors[i]

            def branch(kt, ksim):
                theta = prior.rvs_array(kt)
                logpri = prior.logpdf_array(theta)
                ss = self.spec.flatten(model.sim(ksim, theta))
                pad = self.d_max - theta.shape[0]
                theta = jnp.pad(theta, (0, pad)) if pad else theta
                return theta, ss, logpri

            return branch

        branches = [make_branch(i) for i in range(self.K)]
        if self.K == 1:
            return branches[0](kt, ksim)
        return jax.lax.switch(m, branches, kt, ksim)

    def _lane_transition(self, key, dyn):
        """One lane, generation t>0: ancestor -> MPK -> transition -> sim."""
        km1, km2, kt, ksim, kacc = jax.random.split(key, 5)
        # ancestor model from previous-generation probabilities
        m_anc = jax.random.categorical(km1, dyn["log_model_probs"])
        # model perturbation via the (host-masked) transition matrix
        m = jax.random.categorical(km2, jnp.log(dyn["mpk_matrix"][m_anc] + 1e-38))
        theta, logpri, logq, ss, valid = self._switch_propose_sim(
            m, kt, ksim, dyn
        )
        d, accept, log_acc_w = self._accept_fn(
            kacc, ss, dyn["eps"], dyn["dist_params"], dyn["acc_params"]
        )
        accept = accept & valid
        # log w = log model_prior + log prior - log model_factor - log K_m + acc
        log_w = (
            self.model_prior_logits[m]
            + logpri
            + log_acc_w
            - dyn["log_model_factor"][m]
            - logq
        )
        return dict(
            m=m, theta=theta, sumstats=ss, distance=d, accepted=accept,
            valid=valid, log_weight=jnp.where(valid, log_w, -jnp.inf),
            # full proposal log-density (model factor x particle kernel):
            # the record's transition_pd_prev in log form
            logq=dyn["log_model_factor"][m] + logq,
        )

    def _switch_propose_sim(self, m, kt, ksim, dyn):
        def make_branch(i):
            model = self.models[i]
            prior = self.priors[i]
            dim = model.space.dim
            trans_cls = self.transition_classes[i]

            def branch(kt, ksim, trans_params_all):
                params = trans_params_all[i]
                # redraw-until-valid, fixed unroll
                keys = jax.random.split(kt, DeviceContext.N_REDRAWS)
                theta = trans_cls.device_rvs(keys[0], params)[: self.d_max]
                logpri = prior.logpdf_array(theta[:dim])
                for r in range(1, DeviceContext.N_REDRAWS):
                    redraw = trans_cls.device_rvs(keys[r], params)[: self.d_max]
                    re_logpri = prior.logpdf_array(redraw[:dim])
                    take_new = ~jnp.isfinite(logpri)
                    theta = jnp.where(take_new, redraw, theta)
                    logpri = jnp.where(take_new, re_logpri, logpri)
                valid = jnp.isfinite(logpri)
                logq = trans_cls.device_logpdf(theta, params)
                theta_m = theta[:dim]
                ss = self.spec.flatten(model.sim(ksim, theta_m))
                pad = self.d_max - dim
                theta_out = jnp.pad(theta_m, (0, pad)) if pad else theta_m
                return theta_out, logpri, logq, ss, valid

            return branch

        branches = [make_branch(i) for i in range(self.K)]
        if self.K == 1:
            return branches[0](kt, ksim, dyn["trans_params"])
        return jax.lax.switch(m, branches, kt, ksim, dyn["trans_params"])

    def _accept_fn(self, key, ss, eps, dist_params, acc_params):
        acc_dev = self.acceptor.device_fn(self.distance.device_fn(self.spec))
        return acc_dev(key, ss, self.x0, eps, dist_params, acc_params)

    def round_kernel(self, B: int, mode: str):
        """The jitted round function for batch size B ('prior'/'transition')."""
        cache_key = (B, mode)
        if cache_key in self._kernels:
            return self._kernels[cache_key]

        lane = {
            "prior": self._lane_prior,
            "transition": self._lane_transition,
            "calibration": self._lane_calibration,
        }[mode]

        if self.mesh is None:
            def round_fn(key, dyn):
                keys = jax.random.split(key, B)
                return jax.vmap(lambda k: lane(k, dyn))(keys)

            fn = jax.jit(round_fn)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = self.mesh.axis_names[0]
            lane_sharding = NamedSharding(self.mesh, P(axis))

            def round_fn(key, dyn):
                keys = jax.random.split(key, B)
                keys = self._shard_lane_keys(keys, lane_sharding)
                out = jax.vmap(lambda k: lane(k, dyn))(keys)
                return jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(
                        x, lane_sharding
                    ),
                    out,
                )

            fn = jax.jit(round_fn)
        self._kernels[cache_key] = fn
        return fn

    # ---------------------------------------------------- fused generation
    def _generation_while(self, key, dyn, n_target, *, B, n_cap, rec_cap,
                          max_rounds, run_lanes, all_accept=False,
                          record_proposal=False, moment_cfg=None,
                          dfeat_cfg=None):
        """Traceable mask-and-refill loop for ONE generation.

        Proposes B-lane rounds until ``n_target`` acceptances (or the round
        budget), compacting accepted lanes into a fixed reservoir in
        proposal order — the deterministic slot-ordered trim happens by
        construction. Shared by the single-generation kernel and the
        multi-generation scan. Returns (n_acc, rounds, n_valid, res, rec),
        plus the moment block when ``moment_cfg`` is set.

        ``record_proposal`` extends the record ring with the proposal
        identity (m, theta) and its log-density under the generation's
        proposal (``logq``) — the AcceptanceRateScheme's record
        reweighting needs them (reference transition_pd_prev).

        ``moment_cfg = (C, cols_fn, x0_kernel, x0_cols)`` (sharded
        adaptive distances, ISSUE 12): accumulate the scale reduction's
        ``(MOMENT_ROWS, C)`` moment block IN-LOOP over the ring-eligible
        rows instead of reducing the ring afterwards — the ring's
        sum-stat rows stay dead, which keeps the lane program identical
        between the vmapped virtual-shard and per-device shard_map
        executions (the bit-identity contract; see ops/scale_reduce.py).

        ``dfeat_cfg = (C, row_fn, x0)`` (same contract): store each
        ACCEPTED row's distance-feature vector in the reservoir at
        accept time, so the post-generation distance recompute under the
        refit weights never re-reads the sum-stat rows.
        """
        if moment_cfg is not None:
            from ..ops.scale_reduce import (
                accumulate_moments,
                init_moments,
            )

            mom_C, mom_cols_fn, mom_x0_kernel, mom_x0_cols = moment_cfg
        d_max, S = self.d_max, self.spec.total_size
        res0 = {
            "m": jnp.zeros((n_cap,), jnp.int32),
            "theta": jnp.zeros((n_cap, d_max), jnp.float32),
            "sumstats": jnp.zeros((n_cap, S), jnp.float32),
            "distance": jnp.zeros((n_cap,), jnp.float32),
            "log_weight": jnp.full((n_cap,), -jnp.inf, jnp.float32),
            "slot": jnp.full((n_cap,), -1, jnp.int32),
        }
        if dfeat_cfg is not None:
            res0["dfeat"] = jnp.zeros((n_cap, dfeat_cfg[0]), jnp.float32)
        rec0 = {
            "sumstats": jnp.zeros((rec_cap, S), jnp.float32),
            "distance": jnp.zeros((rec_cap,), jnp.float32),
            "accepted": jnp.zeros((rec_cap,), bool),
            "valid": jnp.zeros((rec_cap,), bool),
        }
        if record_proposal:
            rec0["m"] = jnp.zeros((rec_cap,), jnp.int32)
            rec0["theta"] = jnp.zeros((rec_cap, d_max), jnp.float32)
            rec0["logq"] = jnp.zeros((rec_cap,), jnp.float32)
        state0 = (jnp.zeros((), jnp.int32),  # n_acc
                  jnp.zeros((), jnp.int32),  # round
                  jnp.zeros((), jnp.int32),  # n_valid (true model evals)
                  res0, rec0)
        if moment_cfg is not None:
            state0 = state0 + (init_moments(mom_C),)

        def cond(state):
            n_acc, r = state[0], state[1]
            return (n_acc < n_target) & (r < max_rounds)

        def body(state):
            n_acc, r, n_valid, res, rec = state[:5]
            out = run_lanes(jax.random.fold_in(key, r), dyn)
            acc = out["valid"] if all_accept else (
                out["accepted"] & out["valid"]
            )
            lanes = jnp.arange(B, dtype=jnp.int32)
            slots = r * B + lanes
            # compaction: lane i's accepted rank within this round
            rank = jnp.cumsum(acc.astype(jnp.int32)) - 1
            pos = n_acc + rank
            write_pos = jnp.where(acc & (pos < n_cap), pos, n_cap)
            res = {
                "m": res["m"].at[write_pos].set(
                    out["m"].astype(jnp.int32), mode="drop"),
                "theta": res["theta"].at[write_pos].set(
                    out["theta"], mode="drop"),
                "sumstats": res["sumstats"].at[write_pos].set(
                    out["sumstats"], mode="drop"),
                "distance": res["distance"].at[write_pos].set(
                    out["distance"], mode="drop"),
                "log_weight": res["log_weight"].at[write_pos].set(
                    jnp.where(all_accept, 0.0, out["log_weight"]),
                    mode="drop"),
                "slot": res["slot"].at[write_pos].set(
                    slots, mode="drop"),
            }
            if dfeat_cfg is not None:
                _dC, dfeat_row, dfeat_x0 = dfeat_cfg
                res["dfeat"] = state[3]["dfeat"].at[write_pos].set(
                    jax.vmap(lambda s: dfeat_row(s, dfeat_x0))(
                        out["sumstats"]),
                    mode="drop")
            # record ring: first rec_cap evaluations, in slot order
            rec_pos = jnp.where(out["valid"] & (slots < rec_cap),
                                slots, rec_cap)
            rec_next = {
                "sumstats": rec["sumstats"].at[rec_pos].set(
                    out["sumstats"], mode="drop"),
                "distance": rec["distance"].at[rec_pos].set(
                    out["distance"], mode="drop"),
                "accepted": rec["accepted"].at[rec_pos].set(
                    acc, mode="drop"),
                "valid": rec["valid"].at[rec_pos].set(
                    out["valid"], mode="drop"),
            }
            if record_proposal:
                rec_next["m"] = rec["m"].at[rec_pos].set(
                    out["m"].astype(jnp.int32), mode="drop")
                rec_next["theta"] = rec["theta"].at[rec_pos].set(
                    out["theta"], mode="drop")
                rec_next["logq"] = rec["logq"].at[rec_pos].set(
                    out["logq"], mode="drop")
            rec = rec_next
            nxt = (n_acc + jnp.sum(acc, dtype=jnp.int32), r + 1,
                   n_valid + jnp.sum(out["valid"], dtype=jnp.int32),
                   res, rec)
            if moment_cfg is not None:
                take = out["valid"] & (slots < rec_cap)
                cols = (out["sumstats"] if mom_cols_fn is None
                        else mom_cols_fn(out["sumstats"], mom_x0_kernel))
                nxt = nxt + (accumulate_moments(
                    state[5], cols, take, mom_x0_cols),)
            return nxt

        return jax.lax.while_loop(cond, body, state0)

    # ------------------------------------------ segmented early reject
    def segment_cfg(self, stochastic: bool = False) -> dict:
        """Build the segmented early-reject execution config (ISSUE 15):
        the uniform segment protocol of the model family, the flat-index
        emission map, and the distance's monotone prefix-bound closures.
        Raises with the blocking reason when the config cannot run the
        segmented engine — callers that want a soft fallback gate first
        (``ABCSMC._early_reject_incapable_reason``).

        ``stochastic`` selects the stochastic-acceptor retirement mode
        (ISSUE 17): the bound closures must then be an UPPER bound on
        the kernel's log-density (``device_bound_fn`` dicts carrying
        ``"upper": True``), and the engine retires against per-lane
        pre-committed acceptance thresholds. The direction check is a
        soundness gate in BOTH directions — a lower distance bound
        retired against log-density thresholds (or vice versa) would
        discard viable candidates."""
        from ..ops.segment import index_map_for, uniform_protocol_reason

        reason = uniform_protocol_reason(self.models)
        if reason is not None:
            raise ValueError(f"segmented execution unavailable: {reason}")
        bound = self.distance.device_bound_fn(self.spec)
        if bound is None:
            raise ValueError(
                "segmented execution unavailable: "
                f"{type(self.distance).__name__} has no monotone "
                "prefix bound (device_bound_fn)"
            )
        if bool(bound.get("upper", False)) != bool(stochastic):
            direction = "an upper log-density" if bound.get("upper") \
                else "a lower distance"
            need = ("a StochasticAcceptor" if bound.get("upper")
                    else "a UniformAcceptor")
            raise ValueError(
                "segmented execution unavailable: "
                f"{type(self.distance).__name__} provides {direction} "
                f"bound, which is only sound under {need}"
            )
        seg0 = self.models[0].segmented
        return {
            "n_segments": int(seg0.n_segments),
            "seg_size": int(seg0.seg_size),
            "index_map": jnp.asarray(index_map_for(seg0, self.spec)),
            "bound": bound,
            "use_hist": bool(getattr(self.acceptor,
                                     "use_complete_history", False)),
            "stochastic": bool(stochastic),
        }

    def _seg_propose(self, kind: str):
        """One lane's PROPOSAL phase (everything before the simulator),
        key-split-identical to ``_lane_prior`` / ``_lane_transition``
        with the simulation call replaced by the segment-chain ``init``
        — a proposal that later runs all its segments therefore consumes
        randomness exactly as the classic lane does, which is what makes
        the early-reject population bit-comparable to the unsegmented
        run."""
        segs = [m.segmented for m in self.models]

        if kind == "prior":
            def propose(key, dyn):
                km, kt, ksim, kacc = jax.random.split(key, 4)
                m = jax.random.categorical(km, self.model_prior_logits)

                def make_branch(i):
                    prior = self.priors[i]

                    def branch(kt, ksim):
                        theta = prior.rvs_array(kt)
                        logpri = prior.logpdf_array(theta)
                        carry = segs[i].init(ksim, theta)
                        pad = self.d_max - theta.shape[0]
                        theta = (jnp.pad(theta, (0, pad)) if pad
                                 else theta)
                        return theta, logpri, carry

                    return branch

                branches = [make_branch(i) for i in range(self.K)]
                if self.K == 1:
                    theta, logpri, carry = branches[0](kt, ksim)
                else:
                    theta, logpri, carry = jax.lax.switch(
                        m, branches, kt, ksim)
                return {
                    "m": m.astype(jnp.int32), "theta": theta,
                    "logpri": logpri, "logq": jnp.zeros(()),
                    "valid": jnp.asarray(True), "kacc": kacc,
                    "carry": carry,
                }

            return propose

        def propose(key, dyn):
            km1, km2, kt, ksim, kacc = jax.random.split(key, 5)
            m_anc = jax.random.categorical(km1, dyn["log_model_probs"])
            m = jax.random.categorical(
                km2, jnp.log(dyn["mpk_matrix"][m_anc] + 1e-38))

            def make_branch(i):
                prior = self.priors[i]
                dim = self.models[i].space.dim
                trans_cls = self.transition_classes[i]

                def branch(kt, ksim, trans_params_all):
                    params = trans_params_all[i]
                    keys = jax.random.split(kt, DeviceContext.N_REDRAWS)
                    theta = trans_cls.device_rvs(
                        keys[0], params)[: self.d_max]
                    logpri = prior.logpdf_array(theta[:dim])
                    for r in range(1, DeviceContext.N_REDRAWS):
                        redraw = trans_cls.device_rvs(
                            keys[r], params)[: self.d_max]
                        re_logpri = prior.logpdf_array(redraw[:dim])
                        take_new = ~jnp.isfinite(logpri)
                        theta = jnp.where(take_new, redraw, theta)
                        logpri = jnp.where(take_new, re_logpri, logpri)
                    valid = jnp.isfinite(logpri)
                    logq = trans_cls.device_logpdf(theta, params)
                    theta_m = theta[:dim]
                    carry = segs[i].init(ksim, theta_m)
                    pad = self.d_max - dim
                    theta_out = (jnp.pad(theta_m, (0, pad)) if pad
                                 else theta_m)
                    return theta_out, logpri, logq, valid, carry

                return branch

            branches = [make_branch(i) for i in range(self.K)]
            if self.K == 1:
                theta, logpri, logq, valid, carry = branches[0](
                    kt, ksim, dyn["trans_params"])
            else:
                theta, logpri, logq, valid, carry = jax.lax.switch(
                    m, branches, kt, ksim, dyn["trans_params"])
            return {
                "m": m.astype(jnp.int32), "theta": theta,
                "logpri": logpri, "logq": logq, "valid": valid,
                "kacc": kacc, "carry": carry,
            }

        return propose

    def _seg_step_fn(self):
        """Per-lane segment advance, switched over the model id. The
        step must be uniform in ``seg_idx`` (data, not control flow) —
        lanes sit at different segment indices inside one vmap."""
        segs = [m.segmented for m in self.models]
        if self.K == 1:
            return lambda m, carry, j: segs[0].step(carry, j)

        def step(m, carry, j):
            return jax.lax.switch(m, [s.step for s in segs], carry, j)

        return step

    def _generation_while_seg(self, key, dyn, n_target, *, B, n_cap,
                              rec_cap, max_rounds, kind, seg_cfg,
                              all_accept=False, record_proposal=False,
                              moment_cfg=None, dfeat_cfg=None,
                              B_total=None, lane_base=None):
        """Segment-inner proposal loop with mid-flight lane refill — the
        early-reject twin of :meth:`_generation_while` (ISSUE 15).

        Every lane holds ONE candidate at some segment progress; each
        sweep advances all live lanes one fixed-length segment and folds
        the emitted stats into the distance's monotone prefix bound.
        Between segments, lanes whose bound already exceeds the
        generation threshold are RETIRED (they are provably rejected —
        accepted lanes always run to completion, so only discardable
        work is skipped) and refilled with fresh proposals through the
        same rank/cumsum compaction the reservoir write uses.

        Key/slot discipline: proposals are materialized one ROUND BLOCK
        at a time — block ``r`` is ``vmap(propose)(split(fold_in(key,
        r), B))``, exactly the classic round's keys and proposal math at
        exactly the classic per-round cost — and refilling lanes GATHER
        their proposal by slot from the two live blocks (refills of one
        sweep span at most two rounds). Slots are handed out in lane
        order, every surviving candidate runs exactly ``n_segments``
        sweeps, so completions stay slot-ordered and the reservoir keeps
        the classic slot-ordered-by-construction invariant: the first
        ``n_target`` accepted slots are BIT-IDENTICAL to the classic
        path's. ``rounds``/``n_valid`` count resolved proposals, which
        can differ at the stop margin (classic resolves whole rounds);
        the record ring keeps COMPLETED evaluations only — both
        documented deviations, inert under the non-adaptive gate.

        Returns the classic 5-tuple plus a dict of early-reject
        accounting: lanes retired, productive segment steps, resolved
        proposals, and sweeps (occupancy = seg_steps / (B * sweeps)).

        Sharded composition (ISSUE 17): ``B_total``/``lane_base`` make
        this engine one SHARD's segment sweep — the round key splits
        into ``B_total`` global lane keys and this shard slices its
        contiguous ``B``-lane block at ``lane_base``, exactly the
        classic sharded ``run_lanes`` slice, so the lane-key reduction
        (global lane ``i`` keeps one key everywhere) is preserved and
        retire/refill stays strictly shard-local. ``moment_cfg`` /
        ``dfeat_cfg`` carry the PR 12 adaptive machinery: moments
        accumulate over ALL resolved lanes with per-COLUMN eligibility
        (retired lanes contribute their simulated prefix columns — the
        documented completed-only correction that removes the
        survivor bias of a ring-based refit), and accepted rows store
        their distance-feature vectors at completion. With
        ``moment_cfg`` the return inserts the moment block before the
        accounting dict. ``seg_cfg["stochastic"]`` switches retirement
        to per-lane log-density thresholds from each lane's
        PRE-COMMITTED acceptance draw (the acceptor's own
        ``uniform(kacc)``), making stochastic retirement exact: a lane
        retires only when its kernel-value upper bound proves the
        already-drawn accept test cannot pass.
        """
        from ..ops.segment import gather_lanes, select_lanes

        d_max, S = self.d_max, self.spec.total_size
        n_seg = int(seg_cfg["n_segments"])
        index_map = seg_cfg["index_map"]
        bound = seg_cfg["bound"]
        budget = max_rounds * B
        # backstop against a buggy protocol spinning the loop: every
        # sweep either advances a candidate or drains one
        hard_cap = (max_rounds + 2) * n_seg + 2

        propose = self._seg_propose(kind)
        step_fn = self._seg_step_fn()
        acc_dev = self.acceptor.device_fn(self.distance.device_fn(self.spec))
        eps = dyn["eps"]
        stoch_thr = bool(seg_cfg.get("stochastic", False))
        thr = (jnp.minimum(eps, dyn["acc_params"])
               if seg_cfg["use_hist"] else eps)
        dist_params = dyn["dist_params"]
        if moment_cfg is not None:
            from ..ops.scale_reduce import (
                accumulate_moments,
                init_moments,
            )

            mom_C, mom_cols_fn, _mom_x0_kernel, mom_x0_cols = moment_cfg
            if mom_cols_fn is not None:
                raise ValueError(
                    "segmented moment accumulation needs raw sum-stat "
                    "columns (prefix-accumulable); derived column "
                    "transforms read whole rows"
                )
        seg_size = int(seg_cfg["seg_size"])
        # stats accumulate SEGMENT-MAJOR as (B, n_seg, seg_size) via a
        # dense one-hot FMA — a per-lane scatter here costs more than a
        # whole segment of simulation on CPU backends. dense_pos is the
        # static permutation back to the spec's flat order, applied only
        # at completion; x0/weight rows per segment are pre-gathered.
        imap_np = np.asarray(seg_cfg["index_map"])
        dense_pos = np.empty(S, np.int32)
        for j in range(n_seg):
            dense_pos[imap_np[j]] = j * seg_size + np.arange(seg_size)
        dense_pos = jnp.asarray(dense_pos)
        x0_by_seg = self.x0[seg_cfg["index_map"]]
        # transformed-space bounds (ISSUE 20) precompute per-generation
        # operands — suffix-Gram null-space projectors of the fitted
        # linear transform — from the live distance params and the STATIC
        # emission map; classic bounds read the distance params directly
        bparams = (bound["prepare"](dist_params, imap_np)
                   if "prepare" in bound else dist_params)

        def propose_block(r):
            if B_total is None:
                keys = jax.random.split(jax.random.fold_in(key, r), B)
            else:
                # sharded: the round key still splits into the GLOBAL
                # lane keys; this shard slices its contiguous block —
                # the same lane-key reduction the classic sharded
                # run_lanes performs, so lane i is keyed identically on
                # every execution mode and width
                keys_all = jax.random.split(
                    jax.random.fold_in(key, r), B_total)
                keys = jax.lax.dynamic_slice_in_dim(keys_all, lane_base, B)
            return jax.vmap(lambda k: propose(k, dyn))(keys)

        res0 = {
            "m": jnp.zeros((n_cap,), jnp.int32),
            "theta": jnp.zeros((n_cap, d_max), jnp.float32),
            "sumstats": jnp.zeros((n_cap, S), jnp.float32),
            "distance": jnp.zeros((n_cap,), jnp.float32),
            "log_weight": jnp.full((n_cap,), -jnp.inf, jnp.float32),
            "slot": jnp.full((n_cap,), -1, jnp.int32),
        }
        if dfeat_cfg is not None:
            res0["dfeat"] = jnp.zeros((n_cap, dfeat_cfg[0]), jnp.float32)
        rec0 = {
            "sumstats": jnp.zeros((rec_cap, S), jnp.float32),
            "distance": jnp.zeros((rec_cap,), jnp.float32),
            "accepted": jnp.zeros((rec_cap,), bool),
            "valid": jnp.zeros((rec_cap,), bool),
        }
        if record_proposal:
            rec0["m"] = jnp.zeros((rec_cap,), jnp.int32)
            rec0["theta"] = jnp.zeros((rec_cap, d_max), jnp.float32)
            rec0["logq"] = jnp.zeros((rec_cap,), jnp.float32)

        blocks0 = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b]),
            propose_block(0), propose_block(1),
        )
        acc0_one = bound["init"]()
        lane0 = {
            # proposal fields start as block 0's rows; the first sweep's
            # refill re-selects the same rows, so nothing extra is paid
            **gather_lanes(blocks0, jnp.arange(B)),
            "seg_idx": jnp.zeros((B,), jnp.int32),
            "stats": jnp.zeros((B, n_seg, seg_size), jnp.float32),
            "bacc": jnp.broadcast_to(
                acc0_one, (B,) + acc0_one.shape).astype(jnp.float32),
            "slot": jnp.zeros((B,), jnp.int32),
            "active": jnp.zeros((B,), bool),
        }
        z32 = jnp.zeros((), jnp.int32)
        state0 = (z32, z32, z32,                  # n_acc, n_started, n_valid
                  z32, z32, z32, z32,             # retired, steps, resolved, sweeps
                  jnp.asarray(True),              # any_live
                  z32,                            # r_head
                  jnp.ones((B,), bool),           # alive
                  blocks0, res0, rec0, lane0)
        if moment_cfg is not None:
            state0 = state0 + (init_moments(mom_C),)

        def cond(state):
            n_acc, any_live, sweeps = state[0], state[7], state[6]
            return (n_acc < n_target) & any_live & (sweeps < hard_cap)

        def body(state):
            (n_acc, n_started, n_valid, retired, seg_steps, resolved,
             sweeps, _any_live, r_head, alive, blocks, res, rec,
             lane) = state[:14]
            mom = state[14] if moment_cfg is not None else None
            # ---- refill: resolved lanes take the next slots in lane
            # order (the same rank/cumsum compaction the reservoir
            # write uses), gathering their precomputed proposal rows
            need = alive & ~lane["active"]
            rank = jnp.cumsum(need.astype(jnp.int32)) - 1
            slot_new = n_started + jnp.where(need, rank, 0)
            can = need & (slot_new < budget)
            alive = alive & ~(need & ~can)
            off = jnp.clip(slot_new - r_head * B, 0, 2 * B - 1)
            fresh = gather_lanes(blocks, off)
            lane_new = {
                **fresh,
                "seg_idx": jnp.zeros((B,), jnp.int32),
                "stats": jnp.zeros((B, n_seg, seg_size), jnp.float32),
                "bacc": lane0["bacc"],
                "slot": slot_new.astype(jnp.int32),
                "active": jnp.ones((B,), bool),
            }
            lane = select_lanes(can, lane_new, lane)
            n_started = jnp.minimum(
                n_started + jnp.sum(need, dtype=jnp.int32), budget)
            # consume round blocks as the slot cursor crosses a round
            # boundary: one propose_block per B slots, the classic cost
            shift = n_started >= (r_head + 1) * B
            blocks = jax.lax.cond(
                shift,
                lambda bl: jax.tree.map(
                    lambda a, b: jnp.concatenate([a[B:], b]),
                    bl, propose_block(r_head + 2)),
                lambda bl: bl,
                blocks,
            )
            r_head = r_head + shift.astype(jnp.int32)
            # ---- one segment for every live lane
            stepmask = lane["active"]
            seg_i = jnp.minimum(lane["seg_idx"], n_seg - 1)
            idx_row = index_map[seg_i]
            carry2, vals = jax.vmap(step_fn)(
                lane["m"], lane["carry"], seg_i)
            lane["carry"] = select_lanes(stepmask, carry2, lane["carry"])
            # segment-major dense accumulation: each (lane, segment)
            # cell is written once, so the one-hot FMA equals a scatter
            # at pure vector-math cost
            oh = jax.nn.one_hot(seg_i, n_seg, dtype=jnp.float32)
            stats2 = lane["stats"] + oh[:, :, None] * vals[:, None, :]
            lane["stats"] = jnp.where(
                stepmask[:, None, None], stats2, lane["stats"])
            bacc2 = jax.vmap(
                lambda a, v, i: bound["step"](a, v, i, self.x0,
                                              bparams)
            )(lane["bacc"], vals, idx_row)
            lane["bacc"] = select_lanes(stepmask, bacc2, lane["bacc"])
            lane["seg_idx"] = lane["seg_idx"] + stepmask.astype(jnp.int32)
            seg_steps = seg_steps + jnp.sum(stepmask, dtype=jnp.int32)
            # ---- completions: the classic accept test on the fully
            # assembled stats (bit-identical inputs -> identical
            # verdict), gated so sweeps where no cohort survived to the
            # final segment — most sweeps in the heavy-retire regime —
            # skip the reservoir/ring writes entirely
            done = stepmask & (lane["seg_idx"] >= n_seg)
            # the flat-order stats of every lane: the moment fold reads
            # them each sweep anything resolves, so compute them once and
            # let the completion branch share the gather
            stats_all = (
                lane["stats"].reshape((B, n_seg * seg_size))[:, dense_pos]
                if moment_cfg is not None else None
            )

            def _complete(args):
                res_c, rec_c = args
                stats_flat = (
                    stats_all if stats_all is not None
                    else lane["stats"].reshape(
                        (B, n_seg * seg_size))[:, dense_pos]
                )
                d, accept, log_acc_w = jax.vmap(
                    lambda k, s: acc_dev(k, s, self.x0, eps,
                                         dist_params, dyn["acc_params"])
                )(lane["kacc"], stats_flat)
                if kind == "transition":
                    log_w = (
                        self.model_prior_logits[lane["m"]]
                        + lane["logpri"] + log_acc_w
                        - dyn["log_model_factor"][lane["m"]]
                        - lane["logq"]
                    )
                    logq_full = (dyn["log_model_factor"][lane["m"]]
                                 + lane["logq"])
                else:
                    log_w = log_acc_w
                    logq_full = (self.model_prior_logits[lane["m"]]
                                 + lane["logpri"])
                log_w = jnp.where(lane["valid"], log_w, -jnp.inf)
                acc = (done & lane["valid"] if all_accept
                       else done & accept & lane["valid"])
                rank_a = jnp.cumsum(acc.astype(jnp.int32)) - 1
                pos = n_acc + rank_a
                write_pos = jnp.where(acc & (pos < n_cap), pos, n_cap)
                res_c = {
                    "m": res_c["m"].at[write_pos].set(
                        lane["m"], mode="drop"),
                    "theta": res_c["theta"].at[write_pos].set(
                        lane["theta"], mode="drop"),
                    "sumstats": res_c["sumstats"].at[write_pos].set(
                        stats_flat, mode="drop"),
                    "distance": res_c["distance"].at[write_pos].set(
                        d, mode="drop"),
                    "log_weight": res_c["log_weight"].at[write_pos].set(
                        jnp.where(all_accept, 0.0, log_w), mode="drop"),
                    "slot": res_c["slot"].at[write_pos].set(
                        lane["slot"], mode="drop"),
                }
                if dfeat_cfg is not None:
                    # accepted lanes always run to completion, so their
                    # feature rows are exact — same contract as the
                    # classic sharded accept-time write
                    _dC, dfeat_row, dfeat_x0 = dfeat_cfg
                    res_c["dfeat"] = args[0]["dfeat"].at[write_pos].set(
                        jax.vmap(lambda s: dfeat_row(s, dfeat_x0))(
                            stats_flat),
                        mode="drop")
                # record ring: completed evaluations in slot order (the
                # documented deviation — retired lanes have no stats)
                rec_pos = jnp.where(
                    done & lane["valid"] & (lane["slot"] < rec_cap),
                    lane["slot"], rec_cap)
                rec_n = {
                    "sumstats": rec_c["sumstats"].at[rec_pos].set(
                        stats_flat, mode="drop"),
                    "distance": rec_c["distance"].at[rec_pos].set(
                        d, mode="drop"),
                    "accepted": rec_c["accepted"].at[rec_pos].set(
                        acc, mode="drop"),
                    "valid": rec_c["valid"].at[rec_pos].set(
                        done & lane["valid"], mode="drop"),
                }
                if record_proposal:
                    rec_n["m"] = rec_c["m"].at[rec_pos].set(
                        lane["m"], mode="drop")
                    rec_n["theta"] = rec_c["theta"].at[rec_pos].set(
                        lane["theta"], mode="drop")
                    rec_n["logq"] = rec_c["logq"].at[rec_pos].set(
                        logq_full, mode="drop")
                return res_c, rec_n, jnp.sum(acc, dtype=jnp.int32)

            res, rec, acc_inc = jax.lax.cond(
                jnp.any(done), _complete,
                lambda args: (args[0], args[1], jnp.zeros((), jnp.int32)),
                (res, rec),
            )
            # ---- retirement: provably rejected mid-trajectory (bound
            # sound + slack, so a surviving lane ALWAYS gets the exact
            # final test above; invalid draws are rejected at segment 1)
            if stoch_thr:
                # stochastic acceptance: each lane's accept draw u is
                # PRE-COMMITTED by its kacc key (the acceptor's device_fn
                # draws uniform(kacc)), so the per-lane log-density
                # threshold pdf_norm + T*log(u) is exact — the kernel's
                # upper bound falling below it proves the already-drawn
                # test "log(u) < (logv - pdf_norm)/T" cannot pass.
                # u == 0 gives thr = -inf (the lane is certainly
                # accepted and never retires); T = +inf (calibration)
                # likewise never retires.
                u_lane = jax.vmap(jax.random.uniform)(lane["kacc"])
                thr_lane = dyn["acc_params"] + eps * jnp.log(u_lane)
                exceeds = jax.vmap(
                    lambda a, tl: bound["exceeds"](a, tl, bparams)
                )(lane["bacc"], thr_lane)
            else:
                exceeds = jax.vmap(
                    lambda a: bound["exceeds"](a, thr, bparams)
                )(lane["bacc"])
            retire = stepmask & ~done & (exceeds | ~lane["valid"])
            resolved_now = done | retire
            if moment_cfg is not None:
                # ALL resolved lanes feed the scale moments: completed
                # lanes every column (identical to the classic take),
                # retired lanes the prefix columns they actually
                # simulated — per-column counts keep each statistic's
                # scale an average over every proposal that simulated
                # it, which is what removes the survivor bias of a
                # completed-only ring refit
                seg_done = (jnp.arange(n_seg, dtype=jnp.int32)[None, :]
                            < lane["seg_idx"][:, None])
                col_mask = jnp.broadcast_to(
                    seg_done[:, :, None], (B, n_seg, seg_size)
                ).reshape((B, n_seg * seg_size))[:, dense_pos]
                take_rows = (resolved_now & lane["valid"]
                             & (lane["slot"] < rec_cap))
                mom = accumulate_moments(
                    mom, stats_all, take_rows[:, None] & col_mask,
                    mom_x0_cols)
            lane["active"] = stepmask & ~resolved_now
            n_acc = n_acc + acc_inc
            n_valid = n_valid + jnp.sum(resolved_now & lane["valid"],
                                        dtype=jnp.int32)
            retired = retired + jnp.sum(retire, dtype=jnp.int32)
            resolved = resolved + jnp.sum(resolved_now, dtype=jnp.int32)
            any_live = jnp.any(lane["active"]) | (n_started < budget)
            nxt = (n_acc, n_started, n_valid, retired, seg_steps,
                   resolved, sweeps + 1, any_live, r_head, alive,
                   blocks, res, rec, lane)
            if moment_cfg is not None:
                nxt = nxt + (mom,)
            return nxt

        final = jax.lax.while_loop(cond, body, state0)
        (n_acc, n_started, n_valid, retired, seg_steps, resolved,
         sweeps, _live, _rh, _alive, _blocks, res, rec,
         _lane) = final[:14]
        rounds = (n_started + B - 1) // B
        segx = {"retired": retired, "seg_steps": seg_steps,
                "seg_resolved": resolved,
                # total lane-sweep slots: the occupancy denominator
                "seg_lane_slots": sweeps * B}
        if moment_cfg is not None:
            return n_acc, rounds, n_valid, res, rec, final[14], segx
        return n_acc, rounds, n_valid, res, rec, segx

    def generation_kernel(self, B: int, mode: str, n_cap: int, rec_cap: int,
                          max_rounds: int, record_proposal: bool = False):
        """One jitted program for a WHOLE generation: a ``lax.while_loop``
        keeps proposing B-lane rounds until n_cap acceptances (or the round
        budget), compacting accepted lanes into a fixed reservoir in
        proposal order — the deterministic slot-ordered trim happens by
        construction, and the host sees exactly ONE dispatch per generation
        (the TPU replacement for the reference's Redis counters/queues).

        A bounded record ring (rec_cap) keeps (sumstat, distance, accepted)
        of the first rec_cap evaluations for the adaptive components
        (reference ``max_nr_rejected`` cap).
        """
        cache_key = ("fused", B, mode, n_cap, rec_cap, max_rounds,
                     record_proposal)
        if cache_key in self._kernels:
            return self._kernels[cache_key]

        lane = {
            "prior": self._lane_prior,
            "transition": self._lane_transition,
            "calibration": self._lane_calibration,
        }[mode]
        d_max, S = self.d_max, self.spec.total_size
        all_accept = mode == "calibration"

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = self.mesh.axis_names[0]
            lane_sharding = NamedSharding(self.mesh, P(axis))
        else:
            lane_sharding = None

        def run_lanes(key, dyn):
            keys = jax.random.split(key, B)
            keys = self._shard_lane_keys(keys, lane_sharding)
            return jax.vmap(lambda k: lane(k, dyn))(keys)

        def generation_fn(key, dyn, n_target):
            n_acc, rounds, n_valid, res, rec = self._generation_while(
                key, dyn, n_target, B=B, n_cap=n_cap, rec_cap=rec_cap,
                max_rounds=max_rounds, run_lanes=run_lanes,
                all_accept=all_accept, record_proposal=record_proposal,
            )
            out = {"n_acc": n_acc, "rounds": rounds, "n_valid": n_valid,
                   **res,
                   "rec_" + "sumstats": rec["sumstats"],
                   "rec_distance": rec["distance"],
                   "rec_accepted": rec["accepted"],
                   "rec_valid": rec["valid"]}
            if record_proposal:
                out["rec_m"] = rec["m"]
                out["rec_theta"] = rec["theta"]
                out["rec_logq"] = rec["logq"]
            # adaptive-distance scale reduction IN the kernel: over a TPU
            # tunnel every extra host sync costs ~10x the reduction itself,
            # so the (S,) scale ships with the main fetch instead of a
            # second device round trip on the record ring
            reduce_fn = self.distance.device_record_reduce(self.spec)
            if reduce_fn is not None and rec_cap > 1:
                out["rec_scale"] = reduce_fn(
                    rec["sumstats"], rec["valid"], self.x0
                )
            return out

        if self.mesh_is_multihost():
            # multi-host: replicate outputs (an all-gather over DCN at the
            # generation barrier — the reference's result-queue drain) so
            # every host can device_get the full reservoir for the
            # replicated adaptation step
            from jax.sharding import NamedSharding, PartitionSpec as P

            fn = jax.jit(
                generation_fn,
                out_shardings=NamedSharding(self.mesh, P()),
            )
        else:
            fn = jax.jit(generation_fn)
        self._kernels[cache_key] = fn
        return fn

    def dispatch_generation(self, key, B: int, mode: str, dyn: dict, *,
                            n_cap: int, rec_cap: int, max_rounds: int,
                            n_target: int | None = None,
                            record_proposal: bool = False) -> dict:
        """Launch the fused generation kernel WITHOUT blocking: returns the
        dict of device arrays (jax dispatch is async — the host is free
        until someone calls device_get). This is the hook for
        cross-generation pipelining: persist/analyze generation t on the
        host while the device already runs generation t+1."""
        if n_target is None:
            n_target = n_cap
        return self.generation_kernel(
            B, mode, n_cap, rec_cap, max_rounds,
            record_proposal=record_proposal,
        )(key, dyn, jnp.asarray(min(n_target, n_cap), jnp.int32))

    # ----------------------------------------------------- fetch compaction
    def fetch_pack_kernel(self, *, n_keep: int, dtype_name: str,
                          keep_m: bool, ss_gens, g_keep: int | None = None,
                          merge_index=None):
        """Jitted device-side compaction of a multigen ``outs`` tree
        before the host fetch (``ops/pack.py`` holds the math): theta /
        distance / log_weight collapse into ONE narrowed-dtype row
        buffer, ``slot`` is elided (the reservoir is slot-ordered by
        construction), ``m`` ships as int8 only for K > 1, and sum stats
        ship only for the generations History persists. Over the TPU
        tunnel this cuts the per-chunk payload ~2.7x (32 -> 12 B per
        accepted row at d=4) AND collapses five transfers into one —
        both matter on a latency-floored link (BASELINE.md round 6).

        ``ss_gens``: static tuple of chunk-relative generations whose
        sum-stat rows to include, or ``"all"``.

        ``merge_index`` (sharded fused sampling): static row gather
        merging the shard-blocked per-device reservoirs into dense
        accepted order INSIDE this one program — the chunk-boundary
        all-gather of the sharded path rides the fetch it already pays,
        so the per-run sync budget is untouched.
        """
        ss_key = "all" if ss_gens == "all" else tuple(int(g) for g in ss_gens)
        merge_key = (None if merge_index is None
                     else (len(merge_index), int(merge_index[0])
                           if len(merge_index) else -1,
                           int(merge_index[-1]) if len(merge_index) else -1))
        cache_key = ("fetch_pack", n_keep, dtype_name, keep_m, ss_key,
                     g_keep, merge_key)
        if cache_key in self._kernels:
            return self._kernels[cache_key]

        from ..ops.pack import fetch_dtype_of, pack_outs

        dt = fetch_dtype_of(dtype_name)
        m_dtype = jnp.int8 if self.K <= 127 else jnp.int32
        midx = None if merge_index is None else np.asarray(
            merge_index, np.int32)

        def pack_fn(outs):
            return pack_outs(outs, n_keep=n_keep, dtype=dt, keep_m=keep_m,
                             ss_gens=ss_key, m_dtype=m_dtype, g_keep=g_keep,
                             merge_index=midx)

        if self.mesh_is_multihost() or (
                self.mesh is not None and midx is not None):
            # multi-host: keep the packed tree replicated like the outs it
            # compacts, so every host can device_get it. Sharded
            # single-host: replicating here makes the row merge an
            # explicit all-gather INSIDE the fetch program (one
            # collective per chunk) instead of n_devices host-side
            # per-shard copies at device_get time.
            from jax.sharding import NamedSharding, PartitionSpec as P

            fn = jax.jit(pack_fn, out_shardings=NamedSharding(self.mesh, P()))
        else:
            fn = jax.jit(pack_fn)
        self._kernels[cache_key] = fn
        return fn

    # ------------------------------------------- multi-generation device run
    def multigen_kernel(self, B: int, n_cap: int, rec_cap: int,
                        max_rounds: int, G: int, *, adaptive: bool,
                        eps_quantile: bool, eps_weighted: bool, alpha: float,
                        multiplier: float, trans_cls, fit_statics: tuple,
                        dims: tuple,
                        stochastic: bool = False,
                        temp_config: tuple | None = None,
                        temp_fixed: bool = False,
                        complete_history: bool = False,
                        sumstat_transform: bool = False,
                        sumstat_fit: tuple | None = None,
                        adaptive_n: tuple | None = None,
                        weight_sched: bool = False,
                        fold_sched_mode: bool = False,
                        first_gen_prior: bool = False,
                        fused_calibration: tuple | None = None,
                        refit_cadence: tuple | None = None,
                        health_config: tuple | None = None,
                        sharded: int | None = None,
                        segment_cfg: dict | None = None):
        """One jitted program for G WHOLE GENERATIONS (transition mode).

        The TPU-native endgame of the reference's per-generation scatter/
        gather: a ``lax.scan`` over generations where EVERYTHING the host
        used to do between generations happens on device — per-model
        transition refits (``MultivariateNormalTransition.device_fit``),
        model-probability updates with the never-fitted proposal mask,
        adaptive-distance reweighting + distance recompute, and the
        weighted-quantile epsilon update. One dispatch and ONE host sync
        per G generations; over a TPU tunnel (~0.1s per sync) this is the
        difference between ~7 and ~40+ generations per second at pop 1000.

        Multi-model: the carry holds K fitted-transition param sets, the
        model log-probabilities, and a per-model ``fitted`` mask (a model
        with fewer than dim+1 accepted particles cannot propose next
        generation — the host's NotEnoughParticles semantics); the model
        perturbation matrix is re-masked and renormalized on device each
        generation exactly as ``build_dyn_args`` does on the host.

        Early stop is a carried flag: a generation that misses ``n_target``
        within the round budget, hits ``min_eps``, or collapses below
        ``min_acc_rate`` marks the rest of the chunk skipped (lax.cond) and
        its outputs ``gen_ok=False`` for the host to discard.

        Noisy ABC (``stochastic=True``, single model only): the acceptor is
        a StochasticAcceptor and the epsilon a Temperature — the carry
        additionally holds (pdf_norm, max_found) and the TEMPERATURE, all
        updated on device each generation: pdf_norm via the reference
        ``pdf_norm_max_found`` recursion over accepted kernel values, and
        the temperature as the min over ``temp_config`` scheme twins
        (AcceptanceRateScheme with the reference's record reweighting by
        transition_pd/transition_pd_prev — the record ring keeps per-record
        theta + proposal log-density, and the new proposal density is
        evaluated against the JUST-REFIT transition — plus the
        ExpDecay/PolynomialDecay/FrielPettitt ladders), with monotone decay
        and the final-generation T=1 override (reference
        ``pyabc/epsilon/temperature.py::Temperature._set`` semantics).

        Refit cadence (``refit_cadence=(refit_every, drift_threshold)``,
        the amortized scale-path proposal engine): the per-generation
        transition refit — at pop 16384 a blocked 16k-row kNN plus 16k
        small Choleskys, the dominant device cost of the scale lane —
        runs only every ``refit_every`` generations OR when the
        acceptance-weighted mean/cov drift of the accepted population vs
        the FITTED one (``transition.util.device_proposal_drift``)
        crosses ``drift_threshold``. In between, generations sample and
        weigh against the carried factors directly — statistically exact
        (importance weights always use the proposal params actually
        sampled from), only proposal freshness is traded. A refit is
        FORCED when any model with accepted particles has no usable fit
        yet (first chunk after the in-kernel prior generation, model
        revival). The carry gains a generations-since-refit counter; the
        per-generation outputs gain ``refit``/``drift``/``rows_changed``
        so the host can mirror refit events into the observability
        subsystem — the amortization is measured, not assumed.

        Health guards (``health_config = (ess_floor, acc_floor,
        eps_stall_window, eps_stall_rtol)``, round 10): every generation
        computes an in-kernel health word (:mod:`pyabc_tpu.ops.health`)
        over values the step already holds — NaN/Inf in accepted
        theta/weights/distances, zero total weight, ESS below the floor,
        acceptance collapse, an epsilon-progress stall (carried
        ``(eps_prev, stall_count)`` recursion), and non-finite / zero-
        mass proposal params on BOTH the carry-input and just-refit side
        (a Cholesky that survived the jitter-escalation ladder
        non-finite). The word ships as one int32 per generation on the
        existing packed fetch — zero extra blocking syncs — and the host
        ``RunSupervisor`` maps nonzero words to recovery actions.
        """
        seg_token = (None if segment_cfg is None else
                     (segment_cfg["n_segments"], segment_cfg["seg_size"],
                      segment_cfg["use_hist"],
                      segment_cfg.get("stochastic", False)))
        cache_key = ("multigen", B, n_cap, rec_cap, max_rounds, G, adaptive,
                     eps_quantile, eps_weighted, alpha, multiplier,
                     trans_cls.__name__, fit_statics, dims,
                     stochastic, temp_config, temp_fixed, complete_history,
                     sumstat_transform, sumstat_fit, adaptive_n, weight_sched,
                     fold_sched_mode, first_gen_prior, fused_calibration,
                     refit_cadence, health_config, sharded, seg_token)
        if cache_key in self._kernels:
            return self._kernels[cache_key]
        if stochastic and self.K != 1:
            raise ValueError("stochastic fused chunks support K=1 only")
        if segment_cfg is not None and sumstat_transform and (
                sumstat_fit is None or adaptive):
            # a learned transform mixes entries across the prefix, so the
            # classic partial p-sum is unsound — ISSUE 20 supplies a
            # projector bound for DEVICE-FIT linear transforms
            # (non-adaptive: the adaptive scale refit needs transformed
            # rows the retirement-biased ring cannot supply). Everything
            # else stays gated by the caller
            # (ABCSMC._early_reject_incapable_reason); reaching here
            # means the gate was bypassed.
            raise ValueError(
                "segmented early reject serves learned summary "
                "statistics only under a device-fit plan with a "
                "non-adaptive linear transform (projector prefix "
                "bound); this config has no sound per-prefix bound"
            )
        if segment_cfg is not None and \
                bool(segment_cfg.get("stochastic", False)) != stochastic:
            raise ValueError(
                "segment_cfg stochastic mode does not match the kernel's "
                "acceptor configuration (build it via "
                "segment_cfg(stochastic=...))"
            )
        if sharded is not None:
            # the explicitly sharded variant: per-device lanes/reservoirs
            # with chunk-boundary-only row collectives (ISSUE 9 tentpole;
            # ISSUE 12 extended it to the adaptive mechanisms — adaptive
            # distances, stochastic acceptors, weight/pop schedules and
            # in-kernel adaptive n all ride the scalar-column collectives)
            if fused_calibration is not None:
                raise ValueError(
                    "sharded multigen cannot serve in-kernel "
                    "calibration — the caller must gate it onto the "
                    "GSPMD or host paths"
                )
            if sumstat_transform and (
                    sumstat_fit is None or adaptive
                    or dict(sumstat_fit).get("kind") != "linear"):
                raise ValueError(
                    "sharded multigen serves learned summary statistics "
                    "only under a LINEAR non-adaptive device-fit plan "
                    "(the boundary ridge fit rides the row gather); the "
                    "caller must gate other configs onto the host-refit "
                    "path"
                )
            if refit_cadence is None:
                raise ValueError(
                    "sharded multigen requires a refit cadence (the "
                    "chunk-boundary proposal refit is the cadence refit)"
                )
            fn = self._multigen_sharded(
                B, n_cap, rec_cap, max_rounds, G, n_shards=int(sharded),
                eps_quantile=eps_quantile, eps_weighted=eps_weighted,
                alpha=alpha, multiplier=multiplier, trans_cls=trans_cls,
                fit_statics=fit_statics, dims=dims,
                complete_history=complete_history,
                first_gen_prior=first_gen_prior,
                refit_cadence=refit_cadence, health_config=health_config,
                adaptive=adaptive, stochastic=stochastic,
                temp_config=temp_config, temp_fixed=temp_fixed,
                weight_sched=weight_sched,
                fold_sched_mode=fold_sched_mode, adaptive_n=adaptive_n,
                segment_cfg=segment_cfg,
                sumstat_transform=sumstat_transform,
                sumstat_fit=sumstat_fit,
            )
            self._kernels[cache_key] = fn
            return fn

        from ..ops.stats import normalize_log_weights, weighted_quantile

        lane = self._lane_transition
        S = self.spec.total_size
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = self.mesh.axis_names[0]
            lane_sharding = NamedSharding(self.mesh, P(axis))
        else:
            lane_sharding = None

        dist_fn = self.distance.device_fn(self.spec)
        weight_post = (
            self.distance.device_weight_update() if adaptive else None
        )
        scale_reduce = ss_fn = scale_impl = None
        seg_moment_cfg = seg_scale_finish = seg_mom_x0 = None
        if sumstat_transform:
            # the learned transform's device twin: applied to the fetched
            # rows under a device-fit plan, and composed with the scale
            # twin on the adaptive path below
            ss_fn = self.distance.sumstat.device_fn(self.spec)
        fit_plan = dict(sumstat_fit) if sumstat_fit is not None else None
        if adaptive and segment_cfg is not None:
            # unbiased adaptive refits under retirement (ISSUE 17): the
            # segmented engine's record ring keeps COMPLETED evaluations
            # only, so a ring-based refit would be survivor-biased.
            # Instead the engine folds the PR 12 moment blocks over ALL
            # resolved lanes (retired lanes contribute their simulated
            # prefix columns, per-column counts) and the refit finishes
            # from moments — the same machinery the sharded kernel uses.
            from ..ops.scale_reduce import scale_from_moments

            adapt_cfg = self.distance.device_sharded_reduce(self.spec)
            if (weight_post is None or adapt_cfg is None
                    or adapt_cfg["cols"] is not None):
                raise RuntimeError(
                    "adaptive segmented run needs a moment-expressible "
                    "scale over raw sum-stat columns "
                    "(distance.device_sharded_reduce)"
                )
            seg_scale_finish = scale_from_moments(adapt_cfg["name"])
            seg_mom_x0 = (self.x0 if adapt_cfg["x0_cols"] is None
                          else adapt_cfg["x0_cols"])
            seg_moment_cfg = (adapt_cfg["cols_dim"] or S,
                              adapt_cfg["cols"], self.x0, seg_mom_x0)
            # in-kernel calibration still reduces its complete prior
            # sample through the classic ring reduce (eps=+inf retires
            # nothing, so the sample has full rows)
            scale_reduce = self.distance.device_record_reduce(self.spec)
            if fused_calibration is not None and scale_reduce is None:
                raise RuntimeError(
                    "adaptive segmented calibration needs a device "
                    "record reduce (distance.device_record_reduce)"
                )
        elif adaptive and sumstat_transform:
            # the record ring holds RAW sumstats; the scale reduction runs
            # in the TRANSFORMED feature space of the (chunk-constant)
            # learned statistics, so compose the sumstat device twin with
            # the raw scale twin
            scale_impl = self.distance.device_scale_impl()
            if weight_post is None or scale_impl is None:
                raise RuntimeError(
                    "adaptive multigen run needs device scale + weight twins"
                )
        elif adaptive:
            scale_reduce = self.distance.device_record_reduce(self.spec)
            if weight_post is None or scale_reduce is None:
                raise RuntimeError(
                    "adaptive multigen run needs device scale + weight twins"
                )

        K = self.K

        def multigen_fn(root, t0, n_sched, g_limit, carry0, mpk_base,
                        eps_fixed, min_eps, min_acc_rate, dist_sched=None,
                        fold_sched=None):
            def run_lanes(key, dyn):
                keys = jax.random.split(key, B)
                keys = self._shard_lane_keys(keys, lane_sharding)
                return jax.vmap(lambda k: lane(k, dyn))(keys)

            def run_lanes_prior(key, dyn):
                # generation 0 inside the chunk (first_gen_prior):
                # proposals come straight from the prior; both lane
                # variants return identical output trees, so the
                # generation chooses per-t via lax.cond below
                keys = jax.random.split(key, B)
                keys = self._shard_lane_keys(keys, lane_sharding)
                return jax.vmap(
                    lambda k: self._lane_prior(k, dyn)
                )(keys)

            def gen_step(carry, g):
                carry_l = list(carry)
                (trans_params, log_model_probs, fitted, dist_w,
                 eps_carry, acc_state, stopped) = carry_l[:7]
                tail = carry_l[7:]
                n_carry = tail.pop(0) if adaptive_n is not None else None
                gens_since = (tail.pop(0) if refit_cadence is not None
                              else None)
                # (eps_prev, stall_count): the epsilon-stall recursion
                health_state = (tail.pop(0) if health_config is not None
                                else None)
                pdf_norm, max_found, daly_k = acc_state
                # g_limit (dynamic) caps the active generations so the LAST
                # chunk of a run reuses the same compiled G-kernel instead
                # of tracing a shorter scan (a ~20s compile per distinct G)
                stopped = stopped | (g >= g_limit)
                t = t0 + g
                # per-generation population target (constant schedules pass
                # a constant-filled array; ListPopulationSize varies it;
                # AdaptivePopulationSize carries the in-kernel bootstrap-CV
                # decision of the PREVIOUS generation)
                n_target = n_sched[g] if n_carry is None else n_carry
                gen_key = jax.random.fold_in(root, t + 1)  # generation_key
                if (stochastic and not temp_fixed) or eps_quantile:
                    eps_g = eps_carry
                else:
                    # deterministic schedule (ListEpsilon/ConstantEpsilon,
                    # or a ListTemperature ladder) precomputed by the host
                    eps_g = eps_fixed[g]
                # mask & renormalize the model-perturbation matrix like the
                # host build_dyn_args: never-fitted models cannot propose
                matrix = mpk_base * fitted[None, :].astype(jnp.float32)
                row_sums = matrix.sum(axis=1, keepdims=True)
                matrix = jnp.where(
                    row_sums > 0, matrix / jnp.where(row_sums > 0,
                                                     row_sums, 1.0), 0.0
                )
                probs = jnp.exp(log_model_probs)
                model_factor = probs @ matrix
                log_model_factor = jnp.where(
                    model_factor > 0,
                    jnp.log(jnp.maximum(model_factor, 1e-38)), -jnp.inf,
                )
                # per-generation USER weight schedules (PNormDistance
                # weights={t: ...} / AggregatedDistance sub-weight
                # schedules, non-adaptive): the host pre-resolves
                # device_params(t0+g) for every generation of the chunk
                # and ships them stacked on a leading G axis; the scan
                # indexes its generation's row. The dist_w carry slot is
                # untouched (it only matters for adaptive distances).
                if weight_sched:
                    dist_w_gen = jax.tree.map(lambda v: v[g], dist_sched)
                else:
                    dist_w_gen = dist_w
                # non-stochastic with use_complete_history: the pdf_norm
                # carry slot holds the running min of all past epsilons
                # (UniformAcceptor.device_fn reads it as acc_params)
                dyn = {
                    "eps": eps_g,
                    "dist_params": dist_w_gen,
                    "acc_params": (pdf_norm if stochastic or complete_history
                                   else ()),
                    "log_model_probs": log_model_probs,
                    "mpk_matrix": matrix,
                    "log_model_factor": log_model_factor,
                    "trans_params": trans_params,
                }

                def run_gen(_):
                    if segment_cfg is not None:
                        # ISSUE 15: the segment-inner early-reject loop
                        # replaces the round loop — between segments,
                        # provably-rejected lanes retire and refill so
                        # vector lanes spend cycles on viable candidates
                        def _seg(kind):
                            return self._generation_while_seg(
                                gen_key, dyn, n_target, B=B, n_cap=n_cap,
                                rec_cap=rec_cap, max_rounds=max_rounds,
                                kind=kind, seg_cfg=segment_cfg,
                                record_proposal=stochastic,
                                moment_cfg=seg_moment_cfg,
                            )

                        if not first_gen_prior:
                            return _seg("transition")
                        return jax.lax.cond(
                            t == 0,
                            lambda: _seg("prior"),
                            lambda: _seg("transition"),
                        )

                    def _with(lanes):
                        return self._generation_while(
                            gen_key, dyn, n_target, B=B, n_cap=n_cap,
                            rec_cap=rec_cap, max_rounds=max_rounds,
                            run_lanes=lanes, record_proposal=stochastic,
                        )

                    if not first_gen_prior:
                        return _with(run_lanes)
                    # a whole run in one dispatch chain: generation 0
                    # proposes from the PRIOR (the host used to run it
                    # through the single-generation kernel, paying an
                    # extra synchronous round trip per run)
                    return jax.lax.cond(
                        t == 0,
                        lambda: _with(run_lanes_prior),
                        lambda: _with(run_lanes),
                    )

                def skip_gen(_):
                    z32 = jnp.zeros((), jnp.int32)
                    res = {
                        "m": jnp.zeros((n_cap,), jnp.int32),
                        "theta": jnp.zeros((n_cap, self.d_max), jnp.float32),
                        "sumstats": jnp.zeros((n_cap, S), jnp.float32),
                        "distance": jnp.zeros((n_cap,), jnp.float32),
                        "log_weight": jnp.full((n_cap,), -jnp.inf,
                                               jnp.float32),
                        "slot": jnp.full((n_cap,), -1, jnp.int32),
                    }
                    rec = {
                        "sumstats": jnp.zeros((rec_cap, S), jnp.float32),
                        "distance": jnp.zeros((rec_cap,), jnp.float32),
                        "accepted": jnp.zeros((rec_cap,), bool),
                        "valid": jnp.zeros((rec_cap,), bool),
                    }
                    if stochastic:
                        rec["m"] = jnp.zeros((rec_cap,), jnp.int32)
                        rec["theta"] = jnp.zeros((rec_cap, self.d_max),
                                                 jnp.float32)
                        rec["logq"] = jnp.zeros((rec_cap,), jnp.float32)
                    if segment_cfg is not None:
                        segx_z = {
                            "retired": z32, "seg_steps": z32,
                            "seg_resolved": z32, "seg_lane_slots": z32,
                        }
                        if seg_moment_cfg is not None:
                            from ..ops.scale_reduce import init_moments

                            return (z32, z32, z32, res, rec,
                                    init_moments(seg_moment_cfg[0]), segx_z)
                        return z32, z32, z32, res, rec, segx_z
                    return z32, z32, z32, res, rec

                mom = None
                if segment_cfg is not None and seg_moment_cfg is not None:
                    (n_acc, rounds, n_valid, res, rec, mom,
                     segx) = jax.lax.cond(stopped, skip_gen, run_gen,
                                          None)
                elif segment_cfg is not None:
                    (n_acc, rounds, n_valid, res, rec,
                     segx) = jax.lax.cond(stopped, skip_gen, run_gen,
                                          None)
                else:
                    n_acc, rounds, n_valid, res, rec = jax.lax.cond(
                        stopped, skip_gen, run_gen, None
                    )
                    segx = None
                gen_ok = (n_acc >= jnp.minimum(n_target, n_cap)) & ~stopped
                k_mask = (
                    jnp.arange(n_cap) < jnp.minimum(n_acc, n_target)
                )
                w_norm = normalize_log_weights(res["log_weight"], k_mask)

                fit_now = None
                if fit_plan is not None:
                    # ISSUE 20 device-fit plan: refit the learned transform
                    # at the chunk's LAST ACTIVE generation from the
                    # accepted reservoir the step already holds — the
                    # boundary cadence the host refit used to pay a fetch
                    # for. ``need`` mirrors the host min-samples rule; a
                    # generation that missed it (or a mid-chunk
                    # generation) carries the old params forward.
                    from ..ops.fit import (keep_if_finite, mlp_fit_steps,
                                           ridge_fit)

                    fit_now = (
                        (g == g_limit - 1) & gen_ok
                        & (jnp.minimum(n_acc, n_target)
                           >= jnp.int32(fit_plan["need"]))
                    )
                    ssp_old = dist_w["ss"]
                    y_fit = res["theta"][:, : fit_plan["out_dim"]]
                    w_fit = jnp.where(k_mask, jnp.exp(w_norm), 0.0)
                    if fit_plan["kind"] == "linear":
                        def _fit_ss(_):
                            new = ridge_fit(
                                res["sumstats"], y_fit, w_fit, k_mask,
                                fit_plan["alpha"],
                            )
                            return keep_if_finite(new, ssp_old)[0]
                    else:
                        def _fit_ss(_):
                            new = mlp_fit_steps(
                                ssp_old, res["sumstats"], y_fit, w_fit,
                                k_mask, lr=fit_plan["lr"],
                                n_steps=fit_plan["n_steps"],
                            )
                            return keep_if_finite(new, ssp_old)[0]

                    ssp_next = jax.lax.cond(
                        fit_now, _fit_ss, lambda _: ssp_old, None
                    )
                else:
                    ssp_next = None

                if adaptive and sumstat_transform:
                    # host AdaptivePNormDistance.update order: transform
                    # refit FIRST, then the scale weights in the NEW
                    # transformed feature space
                    ssp = (ssp_next if ssp_next is not None
                           else dist_w["ss"])
                    rec_t = jax.vmap(lambda r: ss_fn(r, ssp))(rec["sumstats"])
                    scale = scale_impl(rec_t, rec["valid"],
                                       ss_fn(self.x0, ssp))
                    dist_w_next = {"w": weight_post(scale), "ss": ssp}
                elif adaptive and segment_cfg is not None:
                    # refit from the engine's resolved-lane moments —
                    # the record ring under retirement holds completed
                    # evaluations only and would bias the scale toward
                    # survivors
                    scale = seg_scale_finish(mom, seg_mom_x0)
                    dist_w_next = weight_post(scale)
                elif adaptive:
                    scale = scale_reduce(rec["sumstats"], rec["valid"],
                                         self.x0)
                    dist_w_next = weight_post(scale)
                elif ssp_next is not None:
                    dist_w_next = {"w": dist_w["w"], "ss": ssp_next}
                else:
                    dist_w_next = dist_w
                if adaptive:
                    # recompute accepted distances under the NEW weights
                    # before the epsilon update (host _recompute_distances
                    # semantics; history keeps the original values)
                    d_new = jax.vmap(
                        lambda s: dist_fn(s, self.x0, dist_w_next)
                    )(res["sumstats"])
                elif ssp_next is not None:
                    # at a boundary refit the epsilon quantile must be
                    # taken in the NEW feature space — the space the next
                    # chunk's accept test runs in (history keeps the
                    # acceptance-time values, like the adaptive path)
                    d_new = jax.lax.cond(
                        fit_now,
                        lambda: jax.vmap(
                            lambda s: dist_fn(s, self.x0, dist_w_next)
                        )(res["sumstats"]),
                        lambda: res["distance"],
                    )
                else:
                    d_new = res["distance"]

                if eps_quantile:
                    pts = jnp.where(k_mask, d_new, jnp.inf)
                    wts = (
                        jnp.where(k_mask, w_norm, 0.0) if eps_weighted
                        else k_mask.astype(jnp.float32)
                    )
                    eps_next = weighted_quantile(pts, wts, alpha) * multiplier
                else:
                    eps_next = eps_carry

                # per-model: probabilities, fitted mask, transition refits
                # (reference per-model masked refits + NotEnoughParticles:
                # a model needs > dim accepted particles to propose)
                m_arr = res["m"]
                model_probs_next = jnp.stack([
                    jnp.where((m_arr == m) & k_mask, w_norm, 0.0).sum()
                    for m in range(K)
                ])
                counts = jnp.stack([
                    (k_mask & (m_arr == m)).sum() for m in range(K)
                ])
                # host rule: MVN-style transitions fit from ANY non-empty
                # particle set (store_fit_params only rejects zero
                # particles; the single-particle degenerate covariance is
                # guarded inside device_fit like smart_cov) — a stricter
                # mask here would make model survival depend on chunk
                # boundaries. Transitions with a declared refit minimum
                # (LocalTransition: dim+1, where the host fit raises
                # NotEnoughParticles and the orchestrator reuses the
                # previous fit) carry the OLD params forward instead.
                min_count_of = getattr(
                    trans_cls, "device_refit_min_count", None
                )
                # GridSearchCV x ListPopulationSize: this generation's
                # host-built fold-id row (the fixed-seed rule over ITS n)
                fit_extra = (
                    {"folds": fold_sched[g]} if fold_sched_mode else {}
                )
                incremental = (
                    refit_cadence is not None
                    and hasattr(trans_cls, "device_fit_update")
                )

                def _refit_models(_):
                    """Per-model refits: per-class static fit config
                    (scaling + bandwidth selector for MVN; scaling + the
                    k_cap/k_fixed/k_fraction/selection neighbor rule for
                    LocalTransition; the scaling grid + fold spec for
                    GridSearchCV). Under cadence, transitions with an
                    incremental twin factorize only changed rows."""
                    trans_new = []
                    refit_ok = []
                    rows_changed = jnp.zeros((), jnp.int32)
                    for m in range(K):
                        w_m = jnp.where(m_arr == m, w_norm, 0.0)
                        if incremental:
                            fit_m, nch = trans_cls.device_fit_update(
                                res["theta"], w_m, trans_params[m],
                                dim=dims[m], **dict(fit_statics[m]),
                                **fit_extra,
                            )
                            rows_changed = rows_changed + nch
                        else:
                            fit_m = trans_cls.device_fit(
                                res["theta"], w_m,
                                dim=dims[m], **dict(fit_statics[m]),
                                **fit_extra,
                            )
                        if min_count_of is not None:
                            ok = counts[m] >= min_count_of(dims[m])
                            fit_m = jax.tree.map(
                                lambda new, old: jnp.where(ok, new, old),
                                fit_m, trans_params[m],
                            )
                        else:
                            ok = counts[m] > 0
                        refit_ok.append(ok)
                        trans_new.append(fit_m)
                    # a model below its refit minimum keeps proposing from
                    # the stale fit IF it ever had one (host semantics); a
                    # model that was never fitted stays masked out
                    fitted_new = jnp.stack(refit_ok) | (fitted
                                                        & (counts > 0))
                    return tuple(trans_new), fitted_new, rows_changed

                if refit_cadence is None:
                    trans_next, fitted_next, rows_changed = \
                        _refit_models(None)
                    drift = jnp.zeros((), jnp.float32)
                    refit_now = jnp.asarray(True)
                    gens_since_next = None
                else:
                    from ..transition.util import device_proposal_drift

                    refit_every_s, drift_thr = refit_cadence
                    # drift of the accepted population vs the population
                    # each alive model's carried proposal was FITTED on
                    drifts = []
                    for m in range(K):
                        vmask_m = (jnp.arange(self.d_max)
                                   < dims[m]).astype(jnp.float32)
                        w_m = jnp.where((m_arr == m) & k_mask, w_norm, 0.0)
                        d_m = device_proposal_drift(
                            trans_params[m]["thetas"],
                            trans_params[m]["weights"],
                            res["theta"], w_m, vmask_m,
                        )
                        drifts.append(jnp.where(
                            fitted[m] & (counts[m] > 0), d_m, 0.0))
                    drift = jnp.max(jnp.stack(drifts))
                    tick = gens_since + 1
                    refit_now = (
                        (tick >= refit_every_s)
                        | (drift > drift_thr)
                        # forced: a model with accepted particles but no
                        # usable fit (first chunk after the in-kernel
                        # prior generation, model revival) cannot wait
                        | jnp.any(~fitted & (counts > 0))
                        | ~jnp.any(fitted)
                    ) & ~stopped

                    def _skip_refit(_):
                        # stale params carried forward verbatim; a model
                        # that died this generation still unfits (same
                        # rule the refit branch applies)
                        return (trans_params, fitted & (counts > 0),
                                jnp.zeros((), jnp.int32))

                    trans_next, fitted_next, rows_changed = jax.lax.cond(
                        refit_now, _refit_models, _skip_refit, None
                    )
                    gens_since_next = jnp.where(
                        refit_now, 0, tick).astype(jnp.int32)
                log_model_probs_next = jnp.where(
                    model_probs_next > 0,
                    jnp.log(jnp.maximum(model_probs_next, 1e-38)), -jnp.inf,
                )
                acc_rate = n_acc / jnp.maximum(n_valid, 1)

                if stochastic:
                    (eps_next, acc_state_next, temp_extra
                     ) = self._stochastic_gen_update(
                        temp_config, trans_cls, trans_next, rec, res, k_mask,
                        w_norm, pdf_norm, max_found, daly_k, eps_carry,
                        acc_rate, t,
                    )
                    if temp_fixed:
                        # fixed ladder: next generation's temperature comes
                        # from the host-precomputed schedule, not a scheme
                        eps_next = eps_fixed[jnp.minimum(g + 1, G - 1)]
                else:
                    eps_min_next = (jnp.minimum(pdf_norm, eps_g)
                                    if complete_history else pdf_norm)
                    acc_state_next = (eps_min_next, max_found, daly_k)
                    temp_extra = {}

                stopped_next = (
                    stopped | ~gen_ok | (eps_g <= min_eps)
                    | (acc_rate < min_acc_rate)
                )
                if health_config is not None:
                    from ..ops.health import generation_health

                    ess_floor, acc_floor, stall_w, stall_rtol = \
                        health_config
                    eps_prev_c, stall_count_c = health_state
                    word, ess, eps_prev_n, stall_n = generation_health(
                        res=res, k_mask=k_mask, w_norm=w_norm,
                        d_new=d_new, n_acc=n_acc, n_target=n_target,
                        acc_rate=acc_rate, trans_params=trans_params,
                        trans_next=trans_next, fitted=fitted,
                        fitted_next=fitted_next, eps_g=eps_g,
                        eps_next=eps_next, eps_prev=eps_prev_c,
                        stall_count=stall_count_c, ess_floor=ess_floor,
                        acc_floor=acc_floor, stall_window=stall_w,
                        stall_rtol=stall_rtol,
                    )
                    # skipped generations are not evidence of anything:
                    # word 0, stall recursion frozen
                    word = jnp.where(stopped, jnp.int32(0), word)
                    health_state_next = (
                        jnp.where(stopped, eps_prev_c, eps_prev_n),
                        jnp.where(stopped, stall_count_c, stall_n),
                    )
                else:
                    word = ess = health_state_next = None
                if fit_plan is not None:
                    # the packed fetch ships TRANSFORMED C'-dim rows: the
                    # generation's ACCEPTANCE-time params (the carry
                    # input, not the boundary refit) transform the
                    # accepted rows so host-side population build /
                    # persist see exactly the feature space the accept
                    # test ran in — and the high-dim raw-S wire payload
                    # shrinks to O(n_params) per particle
                    ssp_used = dist_w["ss"]
                    res = {
                        **res,
                        "sumstats": jax.vmap(
                            lambda s: ss_fn(s, ssp_used)
                        )(res["sumstats"]),
                    }
                out = {
                    **res,
                    "eps_used": eps_g, "eps_next": eps_next,
                    "dist_w_next": dist_w_next, "n_acc": n_acc,
                    "rounds": rounds, "n_valid": n_valid, "gen_ok": gen_ok,
                    "model_probs": model_probs_next,
                    **temp_extra,
                }
                if segx is not None:
                    # early-reject accounting rides the packed fetch
                    # (four int32 per generation, zero extra syncs):
                    # the host mirrors them into the retired-lanes
                    # counter and the segment-occupancy gauge
                    out.update(segx)
                if refit_cadence is not None:
                    # refit events + drift + incremental-factorization
                    # occupancy ship with every generation: the host
                    # mirrors them into metrics/telemetry so the
                    # amortization is measured, not assumed
                    out["refit"] = refit_now
                    out["drift"] = drift
                    out["rows_changed"] = rows_changed
                if health_config is not None:
                    # one int32 + one f32 per generation on the existing
                    # packed fetch: health detection costs zero syncs
                    out["health"] = word
                    out["ess"] = ess
                if adaptive_n is not None:
                    # in-kernel AdaptivePopulationSize: the bootstrap-CV
                    # bisection runs on the JUST-REFIT kernels — exactly
                    # where the host's population_strategy.update sits in
                    # the per-generation loop. K>1 aggregates per-model
                    # CVs weighted by the new model probabilities
                    # (reference calc_cv: mw-weighted mean over the
                    # fitted transitions); works for any transition class
                    # with device_fit/device_logpdf twins (MVN,
                    # LocalTransition) via the generic helpers.
                    from ..transition.util import (
                        device_mean_cv as _cv_generic,
                        device_required_nr as _nr_generic,
                    )

                    target_cv, min_n, max_n, n_boot = adaptive_n
                    # bootstrap key OUTSIDE the proposal-round key space
                    # [0, max_rounds): fold_in(gen_key, r) seeds round r's
                    # lanes, so a tag below max_rounds would reuse a
                    # proposal stream for the CV resampling
                    boot_key = jax.random.fold_in(gen_key, max_rounds)
                    probs_sum = jnp.maximum(model_probs_next.sum(), 1e-38)

                    def cv_at(nn):
                        tot = jnp.zeros((), jnp.float32)
                        for m in range(K):
                            key_m = (boot_key if K == 1
                                     else jax.random.fold_in(boot_key, m))
                            cv_m = _cv_generic(
                                trans_cls, trans_next[m], key_m, nn,
                                dim=dims[m], n_bootstrap=n_boot,
                                **dict(fit_statics[m]),
                            )
                            # dead models (p=0, possibly never-fitted
                            # placeholder params whose CV is garbage)
                            # contribute nothing — reference calc_cv
                            # weighting zeroes them the same way
                            tot = tot + jnp.where(
                                model_probs_next[m] > 0,
                                model_probs_next[m] / probs_sum * cv_m,
                                0.0,
                            )
                        return tot

                    n_next = jax.lax.cond(
                        stopped_next,
                        lambda: n_target,
                        lambda: _nr_generic(
                            cv_at, target_cv=target_cv, min_n=min_n,
                            max_n=max_n,
                        ),
                    )
                    out["n_target"] = n_target
                    out["n_next"] = n_next
                new_carry = [trans_next, log_model_probs_next, fitted_next,
                             dist_w_next, eps_next, acc_state_next,
                             stopped_next]
                if adaptive_n is not None:
                    new_carry.append(n_next)
                if refit_cadence is not None:
                    new_carry.append(gens_since_next)
                if health_config is not None:
                    new_carry.append(health_state_next)
                return tuple(new_carry), out

            calib_info = None
            if fused_calibration is not None:
                # in-kernel CALIBRATION (reference _initialize_dist_eps_acc
                # semantics): a prior round at eps=+inf supplies the
                # calibration sample; adaptive distances take their
                # initial 1/scale weights from it and a from-sample
                # quantile epsilon takes eps_0 — all before generation 0,
                # so a fresh run needs NO host calibration round trip.
                # Runs only when this chunk starts the run (t0 == 0);
                # later chunks take the identity branch.
                n_cal, calib_w, calib_eps = fused_calibration

                def _calibrate():
                    carry = list(carry0)
                    dist_w0, eps_c0 = carry[3], carry[4]
                    dyn_cal = {
                        "eps": jnp.asarray(jnp.inf, jnp.float32),
                        "dist_params": dist_w0,
                        "acc_params": (),
                    }
                    c_acc, _r, _v, cres, _crec = self._generation_while(
                        jax.random.fold_in(root, 0), dyn_cal,
                        jnp.asarray(n_cal, jnp.int32), B=B, n_cap=n_cap,
                        rec_cap=rec_cap, max_rounds=max_rounds,
                        run_lanes=run_lanes_prior, record_proposal=False,
                    )
                    mask = jnp.arange(n_cap) < jnp.minimum(c_acc, n_cal)
                    w0 = dist_w0
                    if calib_w:
                        scale = scale_reduce(cres["sumstats"], mask, self.x0)
                        w0 = weight_post(scale)
                    eps0 = eps_c0
                    if calib_eps:
                        d0 = jax.vmap(
                            lambda s: dist_fn(s, self.x0, w0)
                        )(cres["sumstats"])
                        eps0 = weighted_quantile(
                            jnp.where(mask, d0, jnp.inf),
                            mask.astype(jnp.float32), alpha,
                        ) * multiplier
                    carry[3], carry[4] = w0, eps0
                    return tuple(carry), {"w0": w0, "eps0": eps0}

                def _skip_calib():
                    return carry0, {"w0": carry0[3], "eps0": carry0[4]}

                carry_start, calib_info = jax.lax.cond(
                    t0 == 0, _calibrate, _skip_calib
                )
            else:
                carry_start = carry0
            final_carry, outs = jax.lax.scan(
                gen_step, carry_start, jnp.arange(G)
            )
            # the final carry is returned ON DEVICE so the host can chain
            # the next chunk's dispatch directly off it — chunk k+1 starts
            # computing while chunk k's outputs are still in flight to the
            # host (cross-chunk pipelining; the carried `stopped` flag
            # propagates in-device stops into speculative chunks)
            ret = {"outs": outs, "carry": final_carry}
            if calib_info is not None:
                ret["calib"] = calib_info
            return ret

        if self.mesh_is_multihost():
            # multi-host: replicate the per-generation outputs (one
            # all-gather over DCN at the CHUNK barrier — G generations per
            # cross-host sync instead of one) so every host can device_get
            # the reservoirs for the replicated persist/adaptation step;
            # the carry stays device-resident for chunk chaining
            from jax.sharding import NamedSharding, PartitionSpec as P

            shardings = {"outs": NamedSharding(self.mesh, P()),
                         "carry": None}
            if fused_calibration is not None:
                shardings["calib"] = NamedSharding(self.mesh, P())
            fn = jax.jit(multigen_fn, out_shardings=shardings)
        else:
            fn = jax.jit(multigen_fn)
        self._kernels[cache_key] = fn
        return fn

    # ------------------------------------------- sharded multigen (ISSUE 9)
    def _multigen_sharded(self, B: int, n_cap: int, rec_cap: int,
                          max_rounds: int, G: int, *, n_shards: int,
                          eps_quantile: bool, eps_weighted: bool,
                          alpha: float, multiplier: float, trans_cls,
                          fit_statics: tuple, dims: tuple,
                          complete_history: bool, first_gen_prior: bool,
                          refit_cadence: tuple,
                          health_config: tuple | None,
                          adaptive: bool = False,
                          stochastic: bool = False,
                          temp_config: tuple | None = None,
                          temp_fixed: bool = False,
                          weight_sched: bool = False,
                          fold_sched_mode: bool = False,
                          adaptive_n: tuple | None = None,
                          segment_cfg: dict | None = None,
                          sumstat_transform: bool = False,
                          sumstat_fit: tuple | None = None):
        """The sharded fused chunk: population axis split over the mesh
        with chunk-boundary-only ROW collectives.

        Layout (the *lane-key reduction*): the generation key still
        splits into B lane keys exactly as on one device; shard ``d``
        owns the contiguous lane block ``[d*B_loc, (d+1)*B_loc)`` and
        compacts ITS accepted lanes into ITS reservoir shard of
        ``n_cap / n_shards`` rows, targeting its quota of the
        generation's population (``ops/shard.py``). Acceptance is
        therefore selected per shard in local slot order — the same
        proposals, keyed identically, reduced shard-blocked instead of
        globally. The reduction is a pure function of ``n_shards``, not
        of the physical device count: without a mesh the identical code
        runs vmapped over virtual shards on one device, which is the
        bit-level parity reference the sharded tests compare against.

        Cross-shard traffic per GENERATION is scalar columns only —
        distances, log-weights, model ids, per-shard counters (a few
        bytes per row) — from which every shard computes the identical
        replicated adaptation: weight normalization, the weighted-
        quantile epsilon, model probabilities, stopping flags and the
        health word. Theta rows cross shards exactly ONCE per chunk:
        the cadence refit (forced to the chunk boundary by the caller's
        ``refit_cadence``; sampling against the carried proposal in
        between is statistically exact, PR-3 semantics) all-gathers the
        accepted theta block and fits the next chunk's proposal
        replicated on every device. Sum stats and the packed fetch rows
        merge in ``fetch_pack_kernel`` via the static ``merge_index``
        gather — one all-gather riding the fetch the run already pays,
        so ``syncs_per_run`` is untouched and the dispatch engine's
        speculation/rollback machinery works unchanged (the carry is
        replicated and chains device-to-device exactly as before).

        Width-independence (round 15, mesh-aware serving): the mesh
        width ``w`` only has to DIVIDE ``n_shards`` — device ``d`` then
        runs the ``v = n_shards / w`` virtual shards ``[d*v, (d+1)*v)``
        vmapped inside the shard_map (the hybrid execution), and the
        per-generation collectives become reshape-then-all_gather. The
        reduction stays a pure function of ``n_shards``, so a
        checkpoint taken at any width resumes BIT-identical at any
        other width (including w=1 and the no-mesh virtual path) —
        which is what lets the serving scheduler re-place a preempted
        or device-loss-orphaned tenant on whatever sub-mesh is free.

        Adaptive mechanisms (ISSUE 12 — the capability-gate kill): the
        record ring stays SHARD-LOCAL; adaptive distances refit via the
        pass-decomposed scale reduction of ``ops/scale_reduce.py``
        (per-shard partial moments, an all-gather of scalar-per-stat
        columns, a replicated combine — no new host fetch), stochastic
        acceptors gather the ring's SCALAR columns (kernel value,
        proposal log-density old/new, validity) and run the identical
        replicated ``_stochastic_gen_update`` every device already
        computes, per-generation population schedules ride dynamic
        shard quotas with the packed-fetch merge gather re-indexed per
        generation (``ops/shard.py::merge_index_dyn``), and user weight
        schedules / CV fold tables resolve per generation on the
        replicated column exactly as in the unsharded kernel.

        Segmented early reject (ISSUE 17): with ``segment_cfg`` each
        shard runs the retire/refill engine over ITS lane-key block —
        ``_generation_while_seg`` slices its proposal keys out of the
        GLOBAL round split (``B_total``/``lane_base``), so the lane-key
        reduction and the slot-ordered reservoir survive unchanged and
        retire/refill never crosses devices. The adaptive moment blocks
        now accumulate over ALL resolved lanes (retired lanes feed their
        simulated prefix columns, per-column counts) and per-shard
        retire counters ride the existing packed fetch — the collective
        set, and therefore ``syncs_per_run``, is identical to the
        non-segmented sharded schedule.
        """
        from jax.sharding import PartitionSpec as P

        from ..ops.shard import shard_mask, shard_quota
        from ..ops.stats import normalize_log_weights, weighted_quantile

        if B % n_shards or n_cap % n_shards:
            raise ValueError(
                f"sharded multigen needs n_shards | B and n_shards | "
                f"n_cap (got B={B}, n_cap={n_cap}, n_shards={n_shards})"
            )
        B_loc = B // n_shards
        cap_loc = n_cap // n_shards
        S = self.spec.total_size
        d_max = self.d_max
        K = self.K
        refit_every_s, _drift_thr = refit_cadence
        use_mesh = self.mesh is not None
        dist_fn = self.distance.device_fn(self.spec)
        # ISSUE 20: learned-sumstat device-fit plan (LINEAR, non-adaptive
        # — the multigen_kernel gate enforced it). The boundary ridge fit
        # consumes the SAME gathered rows the cadence refit pays for, so
        # the per-chunk collective set is unchanged.
        fit_plan = dict(sumstat_fit) if sumstat_fit is not None else None
        ss_fn = (self.distance.sumstat.device_fn(self.spec)
                 if sumstat_transform else None)
        weight_post = (
            self.distance.device_weight_update() if adaptive else None
        )
        adapt_cfg = (
            self.distance.device_sharded_reduce(self.spec)
            if adaptive else None
        )
        if adaptive and (weight_post is None or adapt_cfg is None):
            raise RuntimeError(
                "adaptive sharded run needs a moment-expressible device "
                "scale reduction + weight twin "
                "(distance.device_sharded_reduce)"
            )
        if adaptive:
            from ..ops.scale_reduce import (
                combine_moments,
                scale_from_moments,
            )

            scale_finish = scale_from_moments(adapt_cfg["name"])
            mom_x0_cols = (self.x0 if adapt_cfg["x0_cols"] is None
                           else adapt_cfg["x0_cols"])
            moment_cfg = (adapt_cfg["cols_dim"] or S,
                          adapt_cfg["cols"], self.x0, mom_x0_cols)
            dfeat = self.distance.device_sharded_dfeat(self.spec)
            dfeat_combine = dfeat["combine"]
            dfeat_cfg = (dfeat["dim"], dfeat["row"], self.x0)
        else:
            moment_cfg = None
            dfeat_cfg = dfeat_combine = None
        record_proposal = stochastic
        # the AcceptanceRateScheme is the one temperature scheme that
        # reads the record ring; without it the ring's scalar columns
        # never need to cross shards
        need_rec_cols = stochastic and temp_config is not None and any(
            sch[0] == "acceptance_rate" for sch in temp_config[0]
        )
        v_loc = 1
        if use_mesh:
            mesh_devs = list(self.mesh.devices.flat)
            w_mesh = len(mesh_devs)
            if n_shards % w_mesh:
                raise ValueError(
                    f"mesh has {w_mesh} devices but the kernel was "
                    f"requested with n_shards={n_shards}: the mesh "
                    f"width must divide the shard count"
                )
            v_loc = n_shards // w_mesh
            axis = self.mesh.axis_names[0]

        def local_generation(shard_idx, gen_key, dyn, n_target, use_prior,
                             stopped):
            """One shard's whole generation: its lane-key block, its
            reservoir, its quota. No collectives in here."""
            quota_loc = (n_target // n_shards
                         + (shard_idx < n_target % n_shards))

            def _run_with(lane):
                def run_lanes(key, dyn_):
                    keys_all = jax.random.split(key, B)
                    keys = jax.lax.dynamic_slice_in_dim(
                        keys_all, shard_idx * B_loc, B_loc
                    )
                    return jax.vmap(lambda k: lane(k, dyn_))(keys)

                out = self._generation_while(
                    gen_key, dyn, quota_loc, B=B_loc, n_cap=cap_loc,
                    rec_cap=rec_cap, max_rounds=max_rounds,
                    run_lanes=run_lanes, record_proposal=record_proposal,
                    moment_cfg=moment_cfg, dfeat_cfg=dfeat_cfg,
                )
                if moment_cfg is None:
                    out = out + (jnp.zeros((0,), jnp.float32),)
                return out

            def _seg(kind):
                # this shard's segment sweep: the engine slices its
                # B_loc lane keys out of the GLOBAL round split, so
                # retire/refill stays strictly shard-local while lane i
                # keeps the identical key at every width
                out = self._generation_while_seg(
                    gen_key, dyn, quota_loc, B=B_loc, n_cap=cap_loc,
                    rec_cap=rec_cap, max_rounds=max_rounds, kind=kind,
                    seg_cfg=segment_cfg,
                    record_proposal=record_proposal,
                    moment_cfg=moment_cfg, dfeat_cfg=dfeat_cfg,
                    B_total=B, lane_base=shard_idx * B_loc,
                )
                if moment_cfg is None:
                    # insert the mom placeholder before the accounting
                    return out[:5] + (jnp.zeros((0,), jnp.float32),
                                      out[5])
                return out

            def run_gen(_):
                if segment_cfg is not None:
                    if not first_gen_prior:
                        return _seg("transition")
                    return jax.lax.cond(
                        use_prior,
                        lambda: _seg("prior"),
                        lambda: _seg("transition"),
                    )
                if not first_gen_prior:
                    return _run_with(self._lane_transition)
                return jax.lax.cond(
                    use_prior,
                    lambda: _run_with(self._lane_prior),
                    lambda: _run_with(self._lane_transition),
                )

            def skip_gen(_):
                z32 = jnp.zeros((), jnp.int32)
                res = {
                    "m": jnp.zeros((cap_loc,), jnp.int32),
                    "theta": jnp.zeros((cap_loc, d_max), jnp.float32),
                    "sumstats": jnp.zeros((cap_loc, S), jnp.float32),
                    "distance": jnp.zeros((cap_loc,), jnp.float32),
                    "log_weight": jnp.full((cap_loc,), -jnp.inf,
                                           jnp.float32),
                    "slot": jnp.full((cap_loc,), -1, jnp.int32),
                }
                if dfeat_cfg is not None:
                    res["dfeat"] = jnp.zeros((cap_loc, dfeat_cfg[0]),
                                             jnp.float32)
                rec = {
                    "sumstats": jnp.zeros((rec_cap, S), jnp.float32),
                    "distance": jnp.zeros((rec_cap,), jnp.float32),
                    "accepted": jnp.zeros((rec_cap,), bool),
                    "valid": jnp.zeros((rec_cap,), bool),
                }
                if record_proposal:
                    rec["m"] = jnp.zeros((rec_cap,), jnp.int32)
                    rec["theta"] = jnp.zeros((rec_cap, d_max),
                                             jnp.float32)
                    rec["logq"] = jnp.zeros((rec_cap,), jnp.float32)
                if moment_cfg is None:
                    mom = jnp.zeros((0,), jnp.float32)
                else:
                    from ..ops.scale_reduce import init_moments

                    mom = init_moments(moment_cfg[0])
                if segment_cfg is not None:
                    return z32, z32, z32, res, rec, mom, {
                        "retired": z32, "seg_steps": z32,
                        "seg_resolved": z32, "seg_lane_slots": z32,
                    }
                return z32, z32, z32, res, rec, mom

            if segment_cfg is not None:
                (n_acc_l, rounds_l, n_valid_l, res_l, rec_l, mom_l,
                 segx_l) = jax.lax.cond(stopped, skip_gen, run_gen, None)
            else:
                (n_acc_l, rounds_l, n_valid_l, res_l, rec_l,
                 mom_l) = jax.lax.cond(stopped, skip_gen, run_gen, None)
            # local accepted-theta finiteness: the one health input that
            # must be reduced across shards instead of recomputed from
            # the gathered scalar columns
            mask_loc = jnp.arange(cap_loc) < jnp.minimum(
                n_acc_l, quota_loc)
            theta_bad_l = ~jnp.all(jnp.isfinite(
                jnp.where(mask_loc[:, None], res_l["theta"], 0.0)))
            ret = (n_acc_l, rounds_l, n_valid_l, res_l, rec_l, mom_l,
                   theta_bad_l)
            if segment_cfg is not None:
                ret = ret + (segx_l,)
            return ret

        # the two executions of the SAME shard program: without a mesh
        # the shards are a vmapped leading axis on one device and the
        # "collectives" are reshapes; on a mesh of ANY divisor width
        # (including the full width, v_loc == 1) each device vmaps its
        # block of v = n_shards/w virtual shards and the collectives
        # compose reshape + all_gather — bit-level the same reduction.
        # The full-width mesh deliberately keeps the SINGLETON vmap
        # instead of running the shard body unbatched: XLA compiles the
        # batched and unbatched lane programs with different elementwise
        # fusion/contraction choices, and the resulting ULP differences
        # in the simulated statistics broke mesh == virtual bit-identity
        # for multi-stat models and uneven quotas (a latent round-13
        # defect, found and fixed in round 16) — vmapping everywhere
        # keeps every width in the same codegen class (measured:
        # tests/test_sharded.py parity suite).
        class _VirtualShards:
            @staticmethod
            def run_local(gen_key, dyn, n_target, use_prior, stopped):
                return jax.vmap(
                    local_generation,
                    in_axes=(0, None, None, None, None, None),
                )(jnp.arange(n_shards), gen_key, dyn, n_target, use_prior,
                  stopped)

            @staticmethod
            def rows(x):
                return x.reshape((n_shards * x.shape[1],) + x.shape[2:])

            @staticmethod
            def stack(x):
                return x

            @staticmethod
            def map_local(fn):
                # per-shard local computation over the vmapped shard axis
                return jax.vmap(fn)

        class _HybridShards:
            """w devices × v_loc virtual shards per device: device ``d``
            owns global shards ``[d*v_loc, (d+1)*v_loc)``, so flattening
            its local virtual axis and tiling the all_gather reproduces
            the global shard-blocked order exactly."""

            @staticmethod
            def run_local(gen_key, dyn, n_target, use_prior, stopped):
                dev = jax.lax.axis_index(axis)
                idx = dev * v_loc + jnp.arange(v_loc)
                return jax.vmap(
                    local_generation,
                    in_axes=(0, None, None, None, None, None),
                )(idx, gen_key, dyn, n_target, use_prior, stopped)

            @staticmethod
            def rows(x):
                flat = x.reshape((v_loc * x.shape[1],) + x.shape[2:])
                return jax.lax.all_gather(flat, axis, tiled=True)

            @staticmethod
            def stack(x):
                return jax.lax.all_gather(x, axis, tiled=True)

            @staticmethod
            def map_local(fn):
                # per-virtual-shard computation over the device's block
                return jax.vmap(fn)

        def make_gen_step(A, root, t0, n_sched, g_limit, mpk_base,
                          eps_fixed, min_eps, min_acc_rate,
                          dist_sched=None, fold_sched=None):
            def gen_step(carry, g):
                carry_l = list(carry)
                (trans_params, log_model_probs, fitted, dist_w,
                 eps_carry, acc_state, stopped) = carry_l[:7]
                tail = carry_l[7:]
                n_carry = tail.pop(0) if adaptive_n is not None else None
                gens_since = tail.pop(0)
                health_state = (tail.pop(0) if health_config is not None
                                else None)
                pdf_norm, max_found, daly_k = acc_state
                stopped = stopped | (g >= g_limit)
                t = t0 + g
                # per-generation population target: schedules vary it,
                # in-kernel adaptive n carries the previous generation's
                # bootstrap-CV decision — both feed DYNAMIC shard quotas
                n_target = n_sched[g] if n_carry is None else n_carry
                gen_key = jax.random.fold_in(root, t + 1)
                if (stochastic and not temp_fixed) or eps_quantile:
                    eps_g = eps_carry
                else:
                    eps_g = eps_fixed[g]
                # mask & renormalize the model-perturbation matrix —
                # replicated math, identical to the unsharded kernel
                matrix = mpk_base * fitted[None, :].astype(jnp.float32)
                row_sums = matrix.sum(axis=1, keepdims=True)
                matrix = jnp.where(
                    row_sums > 0,
                    matrix / jnp.where(row_sums > 0, row_sums, 1.0), 0.0,
                )
                probs = jnp.exp(log_model_probs)
                model_factor = probs @ matrix
                log_model_factor = jnp.where(
                    model_factor > 0,
                    jnp.log(jnp.maximum(model_factor, 1e-38)), -jnp.inf,
                )
                # per-generation USER weight schedules resolve on the
                # replicated column exactly as in the unsharded kernel
                if weight_sched:
                    dist_w_gen = jax.tree.map(lambda v: v[g], dist_sched)
                else:
                    dist_w_gen = dist_w
                dyn = {
                    "eps": eps_g,
                    "dist_params": dist_w_gen,
                    "acc_params": (pdf_norm if stochastic or complete_history
                                   else ()),
                    "log_model_probs": log_model_probs,
                    "mpk_matrix": matrix,
                    "log_model_factor": log_model_factor,
                    "trans_params": trans_params,
                }
                use_prior = (t == 0) if first_gen_prior \
                    else jnp.asarray(False)
                loc = A.run_local(gen_key, dyn, n_target, use_prior,
                                  stopped)
                (n_acc_l, rounds_l, n_valid_l, res_l, rec_l, mom_l,
                 theta_bad_l) = loc[:7]
                segx_l = loc[7] if segment_cfg is not None else None
                # ---- per-generation scalar-column collectives only
                d_col = A.rows(res_l["distance"])
                lw_col = A.rows(res_l["log_weight"])
                m_col = A.rows(res_l["m"])
                nacc_sh = A.stack(n_acc_l)
                rounds_sh = A.stack(rounds_l)
                nvalid_sh = A.stack(n_valid_l)
                theta_bad = jnp.any(A.stack(theta_bad_l))
                quota_sh = shard_quota(n_target, n_shards)
                n_acc = jnp.sum(nacc_sh)
                n_valid = jnp.sum(nvalid_sh)
                rounds = jnp.max(rounds_sh)
                # a sharded generation is complete when EVERY shard met
                # its quota within the round budget (per-shard budgets —
                # the documented deviation from the global-budget
                # single-device reduction)
                gen_ok = jnp.all(
                    nacc_sh >= jnp.minimum(quota_sh, cap_loc)
                ) & ~stopped
                k_mask = shard_mask(nacc_sh, quota_sh, n_shards, cap_loc)
                w_norm = normalize_log_weights(lw_col, k_mask)
                if adaptive:
                    # adaptive-distance scale refit with the record ring
                    # SHARD-LOCAL: each shard accumulated its moment
                    # block IN-LOOP (ops/scale_reduce.py — the ring's
                    # sum-stat rows stay dead, which is what keeps the
                    # lane program bit-stable across execution modes);
                    # the only cross-shard traffic is this all-gather of
                    # scalar-per-stat moment columns + the replicated
                    # combine/finisher every shard computes identically
                    mom_glob = combine_moments(A.stack(mom_l))
                    scale = scale_finish(mom_glob, mom_x0_cols)
                    dist_w_next = weight_post(scale)
                    # recompute accepted distances under the NEW weights
                    # before the epsilon update (host _recompute_distances
                    # semantics; History keeps the original values). The
                    # recompute reads the reservoir's in-lane DISTANCE
                    # FEATURE rows (|x - x0|^p per stat / sub-distance
                    # values — stored at accept time), NOT the sum-stat
                    # rows: a post-loop re-evaluation of the distance on
                    # the sum stats makes XLA re-materialize the
                    # simulation chain differently between the vmapped
                    # virtual-shard and per-device programs, breaking the
                    # bit-identity contract (measured; see
                    # device_sharded_dfeat).
                    d_new = A.rows(A.map_local(
                        lambda f: jax.vmap(
                            lambda r: dfeat_combine(r, dist_w_next)
                        )(f)
                    )(res_l["dfeat"]))
                    # the feature rows are internal to the recompute:
                    # they must not leak into the chunk outputs
                    res_l = {k: v for k, v in res_l.items()
                             if k != "dfeat"}
                elif fit_plan is not None:
                    # boundary refit of the learned transform: gather the
                    # raw sum-stat + theta rows INSIDE the cond (the same
                    # pattern the cadence refit uses — fit_now fires at
                    # the chunk's last active generation only, so this
                    # rides the boundary the run already pays) and
                    # recompute the accepted distances in the NEW feature
                    # space for the epsilon quantile, from the gathered
                    # replicated rows so every width computes the
                    # identical column
                    from ..ops.fit import keep_if_finite, ridge_fit

                    fit_now = (
                        (g == g_limit - 1) & gen_ok
                        & (jnp.minimum(n_acc, n_target)
                           >= jnp.int32(fit_plan["need"]))
                    )
                    ssp_old = dist_w["ss"]
                    res_raw = res_l

                    def _fit_ss(_):
                        ss_glob = A.rows(res_raw["sumstats"])
                        th_glob = A.rows(
                            res_raw["theta"])[:, : fit_plan["out_dim"]]
                        w_fit = jnp.where(k_mask, jnp.exp(w_norm), 0.0)
                        ssp_n = ridge_fit(ss_glob, th_glob, w_fit,
                                          k_mask, fit_plan["alpha"])
                        ssp_n, fit_ok = keep_if_finite(ssp_n, ssp_old)
                        dw = {"w": dist_w["w"], "ss": ssp_n}
                        d_n = jax.vmap(
                            lambda s: dist_fn(s, self.x0, dw)
                        )(ss_glob)
                        # a rejected fit keeps the acceptance-time
                        # distance column verbatim — recomputing under
                        # the OLD params over gathered rows could differ
                        # in the last bit from the shard-local
                        # acceptance pass
                        return ssp_n, jnp.where(fit_ok, d_n, d_col)

                    ssp_next, d_new = jax.lax.cond(
                        fit_now, _fit_ss,
                        lambda _: (ssp_old, d_col), None,
                    )
                    dist_w_next = {"w": dist_w["w"], "ss": ssp_next}
                else:
                    dist_w_next = dist_w
                    d_new = d_col
                if fit_plan is not None:
                    # the fetch ships TRANSFORMED C'-dim rows under the
                    # generation's ACCEPTANCE-time params — shard-local
                    # math, no new collectives (the row merge happens in
                    # fetch_pack_kernel exactly as before, just over C'
                    # columns instead of S)
                    ssp_used = dist_w["ss"]
                    res_l = {
                        **res_l,
                        "sumstats": A.map_local(
                            lambda rows: jax.vmap(
                                lambda s: ss_fn(s, ssp_used)
                            )(rows)
                        )(res_l["sumstats"]),
                    }
                if eps_quantile:
                    pts = jnp.where(k_mask, d_new, jnp.inf)
                    wts = (
                        jnp.where(k_mask, w_norm, 0.0) if eps_weighted
                        else k_mask.astype(jnp.float32)
                    )
                    eps_next = weighted_quantile(pts, wts,
                                                 alpha) * multiplier
                else:
                    eps_next = eps_carry
                model_probs_next = jnp.stack([
                    jnp.where((m_col == m) & k_mask, w_norm, 0.0).sum()
                    for m in range(K)
                ])
                counts = jnp.stack([
                    (k_mask & (m_col == m)).sum() for m in range(K)
                ])
                min_count_of = getattr(
                    trans_cls, "device_refit_min_count", None
                )
                tick = gens_since + 1
                # the cadence refit IS the chunk-boundary merge point:
                # between refits every shard proposes from the carried
                # replicated params (statistically exact); a refit
                # all-gathers the theta block once and fits replicated
                refit_now = (
                    (tick >= refit_every_s)
                    | jnp.any(~fitted & (counts > 0))
                    | ~jnp.any(fitted)
                ) & ~stopped
                # GridSearchCV x ListPopulationSize: this generation's
                # host-built fold-id row (the fixed-seed rule over ITS n)
                fit_extra = (
                    {"folds": fold_sched[g]} if fold_sched_mode else {}
                )

                def _refit_models(_):
                    theta_glob = A.rows(res_l["theta"])
                    trans_new = []
                    refit_ok = []
                    for m in range(K):
                        w_m = jnp.where(m_col == m, w_norm, 0.0)
                        fit_m = trans_cls.device_fit(
                            theta_glob, w_m, dim=dims[m],
                            **dict(fit_statics[m]), **fit_extra,
                        )
                        if min_count_of is not None:
                            ok = counts[m] >= min_count_of(dims[m])
                            fit_m = jax.tree.map(
                                lambda new, old: jnp.where(ok, new, old),
                                fit_m, trans_params[m],
                            )
                        else:
                            ok = counts[m] > 0
                        refit_ok.append(ok)
                        trans_new.append(fit_m)
                    fitted_new = jnp.stack(refit_ok) | (fitted
                                                        & (counts > 0))
                    return tuple(trans_new), fitted_new

                def _skip_refit(_):
                    return trans_params, fitted & (counts > 0)

                trans_next, fitted_next = jax.lax.cond(
                    refit_now, _refit_models, _skip_refit, None
                )
                gens_since_next = jnp.where(
                    refit_now, 0, tick).astype(jnp.int32)
                log_model_probs_next = jnp.where(
                    model_probs_next > 0,
                    jnp.log(jnp.maximum(model_probs_next, 1e-38)),
                    -jnp.inf,
                )
                acc_rate = n_acc / jnp.maximum(n_valid, 1)
                if stochastic:
                    # the temperature/pdf-norm recursions are replicated
                    # scalar adaptation over the gathered columns; the
                    # AcceptanceRateScheme's record reweighting reads the
                    # ring's SCALAR columns only — proposal log-densities
                    # (old, and new against the just-refit transition,
                    # evaluated shard-locally), kernel values, validity
                    rec_cols = {
                        "logq": A.rows(rec_l["logq"]),
                        "valid": A.rows(rec_l["valid"]),
                        "distance": A.rows(rec_l["distance"]),
                    } if need_rec_cols else {
                        "logq": jnp.zeros((1,), jnp.float32),
                        "valid": jnp.zeros((1,), bool),
                        "distance": jnp.zeros((1,), jnp.float32),
                    }
                    if need_rec_cols:
                        rec_cols["logq_new"] = A.rows(A.map_local(
                            lambda th: jax.vmap(
                                lambda x: trans_cls.device_logpdf(
                                    x, trans_next[0])
                            )(th)
                        )(rec_l["theta"]))
                    (eps_next, acc_state_next, temp_extra
                     ) = self._stochastic_gen_update(
                        temp_config, trans_cls, trans_next, rec_cols,
                        {"distance": d_col}, k_mask, w_norm, pdf_norm,
                        max_found, daly_k, eps_carry, acc_rate, t,
                    )
                    if temp_fixed:
                        eps_next = eps_fixed[jnp.minimum(g + 1, G - 1)]
                else:
                    eps_min_next = (jnp.minimum(pdf_norm, eps_g)
                                    if complete_history else pdf_norm)
                    acc_state_next = (eps_min_next, max_found, daly_k)
                    temp_extra = {}
                stopped_next = (
                    stopped | ~gen_ok | (eps_g <= min_eps)
                    | (acc_rate < min_acc_rate)
                )
                if health_config is not None:
                    from ..ops.health import (
                        BIT_EPS_NONFINITE,
                        BIT_PSD_FAIL,
                        _bit,
                        eps_stall_update,
                        params_unhealthy,
                        population_bits_cols,
                    )

                    ess_floor, acc_floor, stall_w, stall_rtol = \
                        health_config
                    eps_prev_c, stall_count_c = health_state
                    word, ess = population_bits_cols(
                        theta_bad=theta_bad, k_mask=k_mask,
                        w_norm=w_norm, d_new=d_new, n_acc=n_acc,
                        ess_floor=ess_floor, n_target=n_target,
                        acc_rate=acc_rate, acc_floor=acc_floor,
                    )
                    psd_bad = params_unhealthy(trans_params, fitted) \
                        | params_unhealthy(trans_next, fitted_next)
                    word = word | _bit(psd_bad, BIT_PSD_FAIL)
                    eps_bad = (~jnp.isfinite(eps_g)
                               | ~jnp.isfinite(eps_next))
                    word = word | _bit(eps_bad, BIT_EPS_NONFINITE)
                    stall_bit, stall_n = eps_stall_update(
                        eps_prev_c, eps_g, stall_count_c,
                        window=stall_w, rtol=stall_rtol,
                    )
                    word = word | stall_bit
                    word = jnp.where(stopped, jnp.int32(0), word)
                    health_state_next = (
                        jnp.where(stopped, eps_prev_c, eps_g),
                        jnp.where(stopped, stall_count_c, stall_n),
                    )
                else:
                    word = ess = health_state_next = None
                out = {
                    **res_l,
                    "eps_used": eps_g, "eps_next": eps_next,
                    "dist_w_next": dist_w_next, "n_acc": n_acc,
                    "rounds": rounds, "n_valid": n_valid,
                    "gen_ok": gen_ok, "model_probs": model_probs_next,
                    "refit": refit_now,
                    "drift": jnp.zeros((), jnp.float32),
                    "rows_changed": jnp.zeros((), jnp.int32),
                    # per-shard accounting for the mesh observability
                    # gauges (imbalance = how unevenly the mesh worked)
                    "n_acc_shard": nacc_sh, "rounds_shard": rounds_sh,
                    **temp_extra,
                }
                if segx_l is not None:
                    # early-reject accounting, globally AND per shard:
                    # the per-shard int32 columns ride the packed fetch
                    # exactly like n_acc_shard — the retire-imbalance
                    # gauge costs zero extra syncs
                    retired_sh = A.stack(segx_l["retired"])
                    steps_sh = A.stack(segx_l["seg_steps"])
                    slots_sh = A.stack(segx_l["seg_lane_slots"])
                    out.update({
                        "retired": jnp.sum(retired_sh),
                        "seg_steps": jnp.sum(steps_sh),
                        "seg_resolved": jnp.sum(
                            A.stack(segx_l["seg_resolved"])),
                        "seg_lane_slots": jnp.sum(slots_sh),
                        "retired_shard": retired_sh,
                        "seg_steps_shard": steps_sh,
                        "seg_lane_slots_shard": slots_sh,
                    })
                if health_config is not None:
                    out["health"] = word
                    out["ess"] = ess
                if adaptive_n is not None:
                    # in-kernel AdaptivePopulationSize: the bootstrap-CV
                    # bisection is replicated math over the just-refit
                    # kernels — every shard computes the identical next
                    # target, which feeds the next generation's dynamic
                    # quotas (same key discipline as the unsharded twin)
                    from ..transition.util import (
                        device_mean_cv as _cv_generic,
                        device_required_nr as _nr_generic,
                    )

                    target_cv, min_n, max_n, n_boot = adaptive_n
                    boot_key = jax.random.fold_in(gen_key, max_rounds)
                    probs_sum = jnp.maximum(model_probs_next.sum(), 1e-38)

                    def cv_at(nn):
                        tot = jnp.zeros((), jnp.float32)
                        for m in range(K):
                            key_m = (boot_key if K == 1
                                     else jax.random.fold_in(boot_key, m))
                            cv_m = _cv_generic(
                                trans_cls, trans_next[m], key_m, nn,
                                dim=dims[m], n_bootstrap=n_boot,
                                **dict(fit_statics[m]),
                            )
                            tot = tot + jnp.where(
                                model_probs_next[m] > 0,
                                model_probs_next[m] / probs_sum * cv_m,
                                0.0,
                            )
                        return tot

                    n_next = jax.lax.cond(
                        stopped_next,
                        lambda: n_target,
                        lambda: _nr_generic(
                            cv_at, target_cv=target_cv, min_n=min_n,
                            max_n=max_n,
                        ),
                    )
                    out["n_target"] = n_target
                    out["n_next"] = n_next
                new_carry = [trans_next, log_model_probs_next,
                             fitted_next, dist_w_next, eps_next,
                             acc_state_next, stopped_next]
                if adaptive_n is not None:
                    new_carry.append(n_next)
                new_carry.append(gens_since_next)
                if health_config is not None:
                    new_carry.append(health_state_next)
                return tuple(new_carry), out

            return gen_step

        ROW_LOCAL = ("m", "theta", "sumstats", "distance", "log_weight",
                     "slot")

        def _chunk_body(A, root, t0, n_sched, g_limit, carry0, mpk_base,
                        eps_fixed, min_eps, min_acc_rate, dist_sched,
                        fold_sched):
            step = make_gen_step(A, root, t0, n_sched, g_limit, mpk_base,
                                 eps_fixed, min_eps, min_acc_rate,
                                 dist_sched=dist_sched,
                                 fold_sched=fold_sched)
            final_carry, outs = jax.lax.scan(step, carry0, jnp.arange(G))
            rows = {k: outs.pop(k) for k in ROW_LOCAL}
            return rows, outs, final_carry

        # schedule tables are replicated chunk inputs; shard_map needs a
        # leaf in every argument slot, so inactive schedules ride as a
        # zero scalar placeholder
        def _sched_or_zero(sched):
            return sched if sched is not None else jnp.zeros((),
                                                             jnp.float32)

        if use_mesh:
            from jax.experimental.shard_map import shard_map

            Sh = _HybridShards

            def inner(root_data, t0, n_sched, g_limit, carry0, mpk_base,
                      eps_fixed, min_eps, min_acc_rate, dist_sched,
                      fold_sched):
                root_k = jax.random.wrap_key_data(root_data)
                rows, repl, carry = _chunk_body(
                    Sh, root_k, t0, n_sched, g_limit, carry0, mpk_base,
                    eps_fixed, min_eps, min_acc_rate, dist_sched,
                    fold_sched)
                # flatten each device's virtual-shard axis (singleton
                # on a full-width mesh) so the sharded out_spec
                # concatenates device blocks into the same
                # (G, n_cap, ...) global layout every width produces
                rows = {
                    k: x.reshape((G, v_loc * cap_loc) + x.shape[3:])
                    for k, x in rows.items()
                }
                return rows, repl, carry

            inner_sharded = shard_map(
                inner, mesh=self.mesh, in_specs=(P(),) * 11,
                # rows: scan axis G unsharded, reservoir axis sharded;
                # everything else (per-generation scalars, the carry the
                # next chunk chains off) replicated
                out_specs=(P(None, axis), P(), P()),
                check_rep=False,
            )

            def multigen_fn(root, t0, n_sched, g_limit, carry0, mpk_base,
                            eps_fixed, min_eps, min_acc_rate,
                            dist_sched=None, fold_sched=None):
                rows, repl, carry = inner_sharded(
                    jax.random.key_data(root), t0, n_sched, g_limit,
                    carry0, mpk_base, eps_fixed, min_eps, min_acc_rate,
                    _sched_or_zero(dist_sched),
                    _sched_or_zero(fold_sched),
                )
                return {"outs": {**rows, **repl}, "carry": carry}
        else:
            def multigen_fn(root, t0, n_sched, g_limit, carry0, mpk_base,
                            eps_fixed, min_eps, min_acc_rate,
                            dist_sched=None, fold_sched=None):
                rows, repl, carry = _chunk_body(
                    _VirtualShards, root, t0, n_sched, g_limit, carry0,
                    mpk_base, eps_fixed, min_eps, min_acc_rate,
                    _sched_or_zero(dist_sched),
                    _sched_or_zero(fold_sched),
                )
                # virtual shards: ys rows are (G, n_shards, cap_loc, ...)
                # — flatten the shard blocks into the same global layout
                # the mesh run produces
                rows = {
                    k: v.reshape((G, n_cap) + v.shape[3:])
                    for k, v in rows.items()
                }
                return {"outs": {**rows, **repl}, "carry": carry}

        return jax.jit(multigen_fn)

    def _stochastic_gen_update(self, temp_config, trans_cls, trans_next,
                               rec, res, k_mask, w_norm, pdf_norm, max_found,
                               daly_k, temp, acc_rate, t):
        """Traceable per-generation noisy-ABC adaptation (K=1).

        Twin of the host pair ``StochasticAcceptor._update_norm`` (pdf_norm
        via the pdf_norm_max_found recursion over accepted kernel values)
        and ``Temperature._set`` (min over scheme proposals, monotone decay,
        final-generation T=1). The AcceptanceRateScheme twin carries the
        reference record reweighting: each record in the ring was drawn
        with proposal log-density ``rec['logq']``; its density under the
        NEXT generation's proposal is evaluated against the just-refit
        transition params — weights transition_pd / transition_pd_prev
        (SURVEY.md §2.2 Temperature row).

        DalyScheme's contraction state k rides the carry as ``daly_k``
        (host twin: ``DalyScheme._k``); EssScheme bisects the relative-ESS
        condition over the accepted set like the host scheme.

        Returns (eps_next, (pdf_norm_next, max_found_next, daly_k_next),
        extra_outputs).
        """
        import jax
        import jax.numpy as jnp

        schemes, max_np, pdf_max_s, lin_scale, *rest = temp_config
        pdf_scaled = rest[0] if rest else None
        # pdf_norm update from ACCEPTED kernel values (host semantics:
        # acceptor.update reads the weighted accepted distances)
        v_acc = res["distance"]
        logv_acc = (jnp.log(jnp.maximum(v_acc, 1e-30)) if lin_scale
                    else v_acc)
        mx = jnp.max(jnp.where(k_mask, logv_acc, -jnp.inf))
        max_found_next = jnp.maximum(max_found, mx)
        if pdf_max_s is not None:
            pdf_norm_next = jnp.full((), pdf_max_s, jnp.float32)
        else:
            # the scaled carry never exceeds max_found, so taking the max
            # with it reproduces the host's prev_pdf_norm recursion for
            # both the plain and the ScaledPDFNorm method
            pdf_norm_next = jnp.maximum(pdf_norm, max_found_next)
        if pdf_scaled is not None:
            # ScaledPDFNorm twin: cap the norm at the alpha-quantile of the
            # accepted kernel values plus log(factor) (host uses
            # np.quantile's linear interpolation — replicated exactly)
            factor, q_alpha = pdf_scaled
            svals = jnp.sort(jnp.where(k_mask, logv_acc, jnp.inf))
            n_accd = jnp.maximum(k_mask.sum(), 1)
            pos = q_alpha * (n_accd - 1).astype(jnp.float32)
            lo_i = jnp.floor(pos).astype(jnp.int32)
            hi_i = jnp.ceil(pos).astype(jnp.int32)
            frac = pos - lo_i.astype(jnp.float32)
            quant = svals[lo_i] * (1.0 - frac) + svals[hi_i] * frac
            pdf_norm_next = jnp.minimum(
                pdf_norm_next, quant + jnp.log(factor))

        t_next = (t + 1).astype(jnp.float32)
        daly_k_next = daly_k
        if not schemes:
            # fixed ladder (ListTemperature): only the pdf-norm recursion is
            # scheme-free state; the caller substitutes the ladder value
            extra = {"pdf_norm_next": pdf_norm_next,
                     "max_found_next": max_found_next,
                     "daly_k_next": daly_k_next}
            return (temp, (pdf_norm_next, max_found_next, daly_k_next),
                    extra)
        proposals = []
        for sch in schemes:
            if sch[0] == "acceptance_rate":
                target = sch[1]
                # record reweighting to the NEXT proposal (reference
                # transition_pd / transition_pd_prev). The sharded kernel
                # evaluates the new proposal density SHARD-LOCALLY and
                # ships it as a gathered scalar column ("logq_new") —
                # theta rows never cross shards for it.
                logq_new = rec.get("logq_new")
                if logq_new is None:
                    logq_new = jax.vmap(
                        lambda th: trans_cls.device_logpdf(
                            th, trans_next[0])
                    )(rec["theta"])
                lw = jnp.clip(logq_new - rec["logq"], -60.0, 60.0)
                rv = rec["valid"]
                w_rec = jnp.where(rv, jnp.exp(lw), 0.0)
                w_sum = w_rec.sum()
                w_unif = rv.astype(jnp.float32) / jnp.maximum(
                    rv.sum(), 1).astype(jnp.float32)
                w_rec = jnp.where(w_sum > 0,
                                  w_rec / jnp.maximum(w_sum, 1e-38), w_unif)
                v_rec = rec["distance"]
                logv_rec = (jnp.log(jnp.maximum(v_rec, 1e-30)) if lin_scale
                            else v_rec)
                diff = logv_rec - pdf_norm_next

                def rate_at(T_):
                    return jnp.sum(
                        w_rec * jnp.minimum(1.0, jnp.exp(diff / T_)))

                def bisect_body(_, lohi):
                    lo, hi = lohi
                    mid = 0.5 * (lo + hi)
                    ok = rate_at(10.0 ** mid) >= target
                    return (jnp.where(ok, lo, mid), jnp.where(ok, mid, hi))

                lo, hi = jax.lax.fori_loop(
                    0, 60, bisect_body,
                    (jnp.zeros(()), jnp.full((), 12.0)))
                prop = jnp.where(rate_at(1.0) >= target, 1.0, 10.0 ** hi)
            elif sch[0] == "exp_decay_fixed_iter":
                t_to_go = max_np - t_next
                prop = jnp.where(
                    t_to_go <= 1.0, 1.0,
                    temp ** ((t_to_go - 1.0) / jnp.maximum(t_to_go, 1.0)))
            elif sch[0] == "poly_decay_fixed_iter":
                exponent = sch[1]
                t_to_go = max_np - t_next
                frac = (t_to_go - 1.0) / jnp.maximum(t_to_go, 1.0)
                prop = jnp.where(t_to_go <= 1.0, 1.0,
                                 1.0 + (temp - 1.0) * frac ** exponent)
            elif sch[0] == "exp_decay_fixed_ratio":
                a0, min_r, max_r = sch[1:]
                a_eff = jnp.where(
                    acc_rate < min_r, jnp.sqrt(a0),
                    jnp.where(acc_rate > max_r, a0 ** 2, a0))
                prop = jnp.maximum(1.0, a_eff * temp)
            elif sch[0] == "friel_pettitt":
                beta = ((t_next + 1.0) / max_np) ** 2
                prop = 1.0 / jnp.maximum(beta, 1e-12)
            elif sch[0] == "daly":
                # stateful contraction (host DalyScheme._k) rides the chunk
                # carry as daly_k; on acceptance collapse SHRINK the step so
                # temperature cools more slowly while acceptance recovers
                alpha, min_r = sch[1:]
                daly_k_next = jnp.where(
                    acc_rate < min_r,
                    alpha * daly_k,
                    alpha * jnp.minimum(daly_k, temp),
                )
                prop = jnp.maximum(1.0, temp - daly_k_next)
            elif sch[0] == "ess":
                # T s.t. relative ESS of the tempering reweight factors
                # (beta_new - beta_old) * v over the ACCEPTED set hits the
                # target (host EssScheme; bisection on log10 T)
                target = sch[1]
                w_acc = jnp.where(k_mask, w_norm, 0.0)
                w_acc = w_acc / jnp.maximum(w_acc.sum(), 1e-38)
                beta_old = 1.0 / temp
                n_accd = jnp.maximum(k_mask.sum(), 1).astype(jnp.float32)

                def rel_ess(T_):
                    lw = (1.0 / T_ - beta_old) * logv_acc
                    lw = lw - jnp.max(jnp.where(k_mask, lw, -jnp.inf))
                    ww = w_acc * jnp.where(k_mask, jnp.exp(lw), 0.0)
                    s = ww.sum()
                    wn = ww / jnp.maximum(s, 1e-38)
                    ess = 1.0 / jnp.maximum((wn ** 2).sum(), 1e-38) / n_accd
                    return jnp.where(s > 0, ess, 0.0)

                def ess_bisect(_, lohi):
                    lo, hi = lohi
                    mid = 0.5 * (lo + hi)
                    ok = rel_ess(10.0 ** mid) >= target
                    return (jnp.where(ok, lo, mid), jnp.where(ok, mid, hi))

                lo, hi = jax.lax.fori_loop(
                    0, 60, ess_bisect,
                    (jnp.zeros(()), jnp.full((), 12.0)))
                prop = jnp.where(rel_ess(1.0) >= target, 1.0, 10.0 ** hi)
            else:  # pragma: no cover - guarded by _fused_chunk_capable
                raise ValueError(f"unsupported device scheme: {sch[0]}")
            proposals.append(jnp.asarray(prop, jnp.float32))

        props = jnp.stack(proposals)
        props = jnp.where(jnp.isfinite(props), props, jnp.inf)
        temp_next = jnp.min(props)
        temp_next = jnp.where(jnp.isfinite(temp_next), temp_next, temp)
        # monotone decay + T >= 1 + final-generation exact sampling
        temp_next = jnp.maximum(jnp.minimum(temp_next, temp), 1.0)
        if max_np > 0:
            temp_next = jnp.where(t_next >= max_np - 1, 1.0, temp_next)
        extra = {"pdf_norm_next": pdf_norm_next,
                 "max_found_next": max_found_next,
                 "daly_k_next": daly_k_next}
        return (temp_next, (pdf_norm_next, max_found_next, daly_k_next),
                extra)

    def run_generation(self, key, B: int, mode: str, dyn: dict, *,
                       n_cap: int, rec_cap: int, max_rounds: int,
                       n_target: int | None = None) -> dict:
        out = self.dispatch_generation(
            key, B, mode, dyn, n_cap=n_cap, rec_cap=rec_cap,
            max_rounds=max_rounds, n_target=n_target,
        )
        host = jax.device_get(out)
        self.sync_ledger.record("generation_fetch")
        return host

    # ------------------------------------------------------------- dispatch
    def run_round(self, key, B: int, mode: str, dyn: dict) -> RoundResult:
        out = self.round_kernel(B, mode)(key, dyn)
        out = jax.device_get(out)
        self.sync_ledger.record("round_fetch")
        return RoundResult(
            ms=np.asarray(out["m"], np.int32),
            thetas=np.asarray(out["theta"], np.float64),
            sumstats=np.asarray(out["sumstats"], np.float64),
            distances=np.asarray(out["distance"], np.float64),
            accepted=np.asarray(out["accepted"], bool),
            valid=np.asarray(out["valid"], bool),
            log_weights=np.asarray(out["log_weight"], np.float64),
            logqs=(np.asarray(out["logq"], np.float64)
                   if "logq" in out else None),
        )

    # ---------------------------------------------------- per-generation args
    def build_dyn_args(self, *, t: int, eps_value: float,
                       model_probabilities: dict | None = None,
                       transitions: Sequence | None = None,
                       model_perturbation_kernel=None) -> tuple[str, dict]:
        """(mode, dynamic-args pytree) for generation t."""
        dist_params = self.distance.device_params(t)
        acc_params = self.acceptor.device_params(t)
        dyn = {
            "eps": jnp.asarray(eps_value, jnp.float32),
            "dist_params": dist_params,
            "acc_params": acc_params,
        }
        if t == 0 or transitions is None:
            return "prior", dyn

        probs = np.zeros(self.K)
        for m, p in model_probabilities.items():
            probs[int(m)] = p
        fitted = np.asarray(
            [tr.X is not None for tr in transitions], bool
        )
        matrix = np.asarray(
            jax.device_get(model_perturbation_kernel.device_params()),
            np.float64,
        )
        self.sync_ledger.record("kernel_params_fetch", matrix.nbytes)
        # never-fitted models cannot propose: mask & renormalize rows
        matrix = matrix * fitted[None, :]
        row_sums = matrix.sum(axis=1, keepdims=True)
        matrix = np.where(row_sums > 0, matrix / np.where(row_sums > 0,
                                                          row_sums, 1.0), 0.0)
        # log model_factor[m] = log sum_anc p(anc) matrix[anc, m]
        model_factor = probs @ matrix
        with np.errstate(divide="ignore"):
            log_model_factor = np.log(model_factor)
            log_model_probs = np.log(probs)

        n_cap = _pow2_bucket(
            max(len(tr.X) for tr in transitions if tr.X is not None)
        )
        trans_params = []
        for tr in transitions:
            if tr.X is not None:
                raw = jax.tree.map(np.asarray, tr.device_params())
            else:
                # placeholder params; masked out of the MPK matrix above
                ref = next(x for x in transitions if x.X is not None)
                raw = jax.tree.map(
                    lambda v: np.zeros_like(np.asarray(v)),
                    ref.device_params(),
                )
            trans_params.append(
                pad_transition_params(raw, n_cap, self.d_max)
            )

        dyn.update(
            log_model_probs=jnp.asarray(log_model_probs, jnp.float32),
            mpk_matrix=jnp.asarray(matrix, jnp.float32),
            log_model_factor=jnp.asarray(log_model_factor, jnp.float32),
            trans_params=tuple(trans_params),
        )
        return "transition", dyn
