"""One async dispatch engine — double-buffered speculative chunk dispatch.

Until round 12 ``inference/smc.py`` carried THREE overlapping loops that
each re-implemented the same carry/fetch/stopping machinery: the
per-generation pipelined loop, the fused-chunk loop with its threaded
fetch pipeline, and the async-drain tail (a near-verbatim copy of the
fused processing loop running on a background thread). The round-5..11
instrumentation (SyncLedger, device-busy pseudo-thread, gap attribution)
proved the residual dual-basis gap — ~143.7k accepted-particles/sec
pipeline-full vs ~45.6k strict wall clock — is host orchestration plus
the ~0.1 s/sync tunnel floor, not device compute. This module is the fix:
ONE event-driven engine that every device dispatch and fetch flows
through (abc-lint DISP001 makes that structural — direct
``multigen_kernel`` / ``fetch_pack_kernel`` / ``round_kernel`` calls
outside this module are findings).

:class:`DispatchEngine` is a small state machine::

    FILL ──► PROCESS ──► FILL            (steady state: device never idles)
      │         │
      │         ├──► RECOVER ──► FILL    (in-kernel health failure:
      │         │                         rollback + redispatch, PR 5/6)
      │         ├──► BOUNDARY ──► FILL   (sumstat-refit host boundary)
      │         └──► STOPPED             (stopping rule hit: speculative
      │                                   overrun rolled back, unpersisted)
      └──► DRAIN ──► DONE                (schedule exhausted: the same
                                          step body on a background thread)

- **Double-buffered speculation**: chunk k+1 is dispatched off chunk k's
  still-on-device final carry while chunk k's packed fetch is in flight —
  the device never waits for host turnaround. Up to ``depth`` chunks keep
  their ``device_get`` running on fetch threads (concurrent fetches
  pipeline over the tunnel: 4x512KB measured 1.26 s sequential, 0.18 s
  concurrent).
- **Stop rollback**: stopping rules and epsilon/temperature adaptation
  are evaluated from the already-landed packed fetch — no extra blocking
  syncs. A speculative chunk that overruns a stopping-rule hit is
  DISCARDED unpersisted (processing is strictly in order and the History
  writer only ever sees generations below the stop), counted into
  ``pyabc_tpu_speculative_rollbacks_total`` — a speculative run's History
  is bit-identical to a non-speculative run's.
- **Sync budget**: the engine owns the per-run sync budget
  (``syncs_per_run <= chunks + O(1)``) asserted through
  :meth:`~pyabc_tpu.observability.sync.SyncLedger.budget_report` and
  exported as the ``pyabc_tpu_syncs_per_run`` gauge; the bench
  ``dispatch`` lane regression-guards it.
- **Engine states, not re-implementations**: the PR-6 health-word
  supervision (RECOVER), PR-5 mid-chunk checkpointing, and the
  ``drain_async`` handoff (DRAIN runs the SAME ``_step`` body on the
  drain thread) are states of this one loop.

The per-generation PIPELINED path (host-adaptive configs that cannot
chain generations on device) routes through :func:`run_pipelined` /
:func:`dispatch_speculative_round` below — same module, same rollback
accounting, so the whole dispatch surface lives behind one door.
"""
from __future__ import annotations

import logging

import numpy as np

from ..observability import fire_span_ship_hooks, register_dispatch_source
from ..observability.metrics import (
    MESH_BUSY_MAX_GAUGE,
    MESH_DEVICES_GAUGE,
    MESH_IMBALANCE_GAUGE,
    MESH_ROW_COLLECTIVES_TOTAL,
    MESH_SCALE_BYTES_GAUGE,
    SIM_RETIRE_IMBALANCE_GAUGE,
    SPECULATIVE_ROLLBACKS_TOTAL,
    SYNCS_PER_RUN_GAUGE,
)

logger = logging.getLogger("ABC.Dispatch")

#: O(1) allowance of the per-run sync budget: blocking round trips that
#: are per-RUN, not per-chunk — host calibration collect, an unfused
#: generation-0 collect + adaptive records/scale fetches, a checkpoint
#: restore, the final boundary build. Everything else must amortize into
#: the per-chunk term or the budget report flips to not-ok.
SYNC_BUDGET_O1 = 8

#: engine states (strings — they ride snapshots/telemetry as-is)
FILL = "fill"
PROCESS = "process"
RECOVER = "recover"
BOUNDARY = "boundary"
DRAIN = "drain"
STOPPED = "stopped"
DONE = "done"


class DispatchEngine:
    """Event-driven double-buffered chunk dispatch for the fused path.

    Owns every device round trip of a fused run: the multigen kernel
    build, chunk dispatch (speculative, chained carry-to-carry on
    device), the packed fetch pipeline, in-order processing, health
    rollback/redispatch, mid-chunk checkpoints, the drain-async handoff
    and the per-run sync budget. The ``owner`` (ABCSMC) supplies the
    STATISTICAL half as hooks: per-chunk host schedules
    (``chunk_host_args``), carry construction (``rebuild_carry``),
    generation limits (``g_limit``), chunk processing / host mirroring
    (``_process_chunk``) and recovery-carry selection.
    """

    def __init__(self, owner, ctx, *, shapes, kernel_kwargs, g_limit,
                 chunk_host_args, rebuild_carry, stop, n_of,
                 sumstat_refit=False, adaptive=False, stochastic=False,
                 temp_fixed=False, eps_quantile=False, adaptive_n=False,
                 n_keep=None, shard_merge=None, mesh_shards=None,
                 mesh_scale_bytes=0):
        from concurrent.futures import ThreadPoolExecutor

        self.owner = owner
        self.ctx = ctx
        self.g_limit = g_limit
        self.chunk_host_args = chunk_host_args
        self.rebuild_carry = rebuild_carry
        self.stop = dict(stop)
        self.n_of = n_of
        self.sumstat_refit = bool(sumstat_refit)
        self.adaptive = bool(adaptive)
        self.stochastic = bool(stochastic)
        self.temp_fixed = bool(temp_fixed)
        self.eps_quantile = bool(eps_quantile)
        self.adaptive_n = bool(adaptive_n)
        self.n_keep = n_keep
        #: sharded fused sampling: the static row gather merging the
        #: shard-blocked per-device reservoirs inside the packed fetch
        #: (ops/shard.py::merge_index), or the string "dynamic" when the
        #: kernel re-indexes the merge per generation (population
        #: schedules / in-kernel adaptive n), and the mesh width for the
        #: observability gauges. None/None on unsharded runs.
        self.shard_merge = shard_merge
        self.mesh_shards = int(mesh_shards) if mesh_shards else None
        #: per-generation cross-shard payload of the adaptive scale
        #: reduction + stochastic record-column gathers (config-derived;
        #: 0 for non-adaptive configs) — the round-16 collectives made
        #: visible in the gap accounting instead of assumed free
        self.mesh_scale_bytes = int(mesh_scale_bytes)
        #: cross-shard ROW collectives so far (packed-fetch merge
        #: gathers + in-kernel cadence-refit theta all-gathers)
        self.row_collectives = 0
        #: per-shard accounting of the last processed chunk (rounds and
        #: accepted rows per device, imbalance ratio) — surfaced in
        #: snapshot()["mesh"] and the pyabc_tpu_mesh_* gauges
        self._mesh_stats = None
        self._clock = owner._clock
        # sumstat_refit mode can't speculate: each next chunk's carry
        # needs the host predictor refit on the previous chunk's last
        # population (depth 1, sync)
        self.depth = 1 if sumstat_refit else max(
            1, int(owner.fetch_pipeline_depth)
        )
        self._executor = (ThreadPoolExecutor(max_workers=self.depth)
                          if self.depth > 1 else None)
        self._probe_pool = (ThreadPoolExecutor(max_workers=1)
                            if owner.compute_probe else None)
        # the boundary sumstat refit feeds a host KDE fit — keep its wire
        # format at full precision; every other config narrows (the
        # device carry chain is f32 either way, so acceptances / epsilon
        # trail / refits are bit-identical across fetch dtypes)
        self.fetch_dtype = "float32" if sumstat_refit else owner.fetch_dtype
        B, n_cap, rec_cap, max_rounds, G = shapes
        self.G = int(G)
        self._n_cap = int(n_cap)
        # does this run PAY the multigen trace/compile? A context
        # adopted from a same-shape donor (bench back-to-backs, the
        # serving layer's shape-keyed kernel cache) already holds the
        # jitted program for these shapes — its first dispatch is
        # cache-hit cheap, and the first-dispatch span below is marked
        # compile=False so the serving tests can assert a repeat-shape
        # tenant compiles NOTHING
        self._fresh_compile = not any(
            isinstance(k, tuple) and len(k) >= 6 and k[0] == "multigen"
            and k[1:6] == (B, n_cap, rec_cap, max_rounds, G)
            for k in getattr(ctx, "_kernels", {})
        )
        # the ONE multigen-kernel build of the run (DISP001: kernel
        # construction and invocation both live in this module)
        with owner.tracer.span("kernel.build", G=int(G), B=int(B),
                               n_cap=int(n_cap)):
            self.kern = ctx.multigen_kernel(
                B, n_cap, rec_cap, max_rounds, G, **kernel_kwargs
            )
        # even at depth 1 (sync fetch) the NEXT chunk must be dispatched
        # before fetching the current one — both for the speculative
        # overlap and because the step loop drains `while pending`
        self.refill_target = max(self.depth, 2)
        # ---- engine state
        self.state = FILL
        self.pending: list = []   # ((handle, r5_bytes), t, g, carry_ref)
        self.tail = None          # newest dispatched chunk (carry chain)
        self.t = 0
        self.sims_total = 0
        self.chunk_index = 0
        self.chunks_dispatched = 0
        self.chunks_processed = 0
        self.speculative_rollbacks = 0
        self.good_carry = None    # (t, carry) newest known-healthy boundary
        self.drained_async = False
        self._t_chunk0 = self._clock.now()
        # weakly registered with the process-wide observability snapshot
        # (``/api/observability`` "dispatch" block, broker status /
        # ``abc-manager``) — a collected engine silently drops out
        register_dispatch_source(self)

    # --------------------------------------------------------------- public
    def run(self, t0: int, carry0, sims_total: int):
        """Drive the state machine to DONE (or hand the tail to the
        DRAIN thread). Returns the owner's History either way — on a
        drain handoff it is incomplete until ``owner.drain_join()``."""
        owner = self.owner
        self.t = t0
        self.sims_total = int(sims_total)
        g0 = self.g_limit(t0)
        self.good_carry = (t0, carry0)
        owner._final_ck_state = None
        self._t_chunk0 = self._clock.now()
        # the FIRST dispatch triggers the multigen kernel's trace/compile
        # (the dominant dark block on fresh runs, per the first coverage
        # traces) — span it separately so compile time is attributed;
        # `compile` marks whether this run actually paid the trace (see
        # the _fresh_compile probe in __init__)
        with owner.tracer.span("dispatch", first=True, t_first=int(t0),
                               compile=self._fresh_compile):
            res = self._dispatch_chunk(carry0, t0, g0)
        self.pending = [(self._submit(res, t0, g0), t0, g0, res["carry"])]
        self.tail = (res, t0, g0)
        try:
            while self.pending:
                dispatch_s = self._refill()
                if self._maybe_drain_handoff():
                    return owner.history
                outcome = self._process_next(dispatch_s)
                if not self._after_process(outcome):
                    break
        finally:
            # on a drain-async handoff the drain thread owns the pools
            if not self.drained_async:
                self._shutdown_pools()
        self._complete()
        return owner.history

    def snapshot(self) -> dict:
        """JSON-ready engine state for the observability snapshot."""
        snap = {
            "state": self.state,
            "t": int(self.t),
            "in_flight": len(self.pending),
            "depth": int(self.depth),
            "chunks_dispatched": int(self.chunks_dispatched),
            "chunks_processed": int(self.chunks_processed),
            "speculative_rollbacks": int(self.speculative_rollbacks),
            "sync_budget": self.sync_budget_report(),
        }
        fallbacks = getattr(self.owner, "_capability_fallbacks", None)
        if fallbacks:
            # why this run is NOT on a requested fast path — the reason
            # strings, not just a counter (/api/observability reads this)
            snap["capability_fallbacks"] = [dict(f) for f in fallbacks]
        if self.mesh_shards:
            snap["mesh"] = {
                "devices": int(self.mesh_shards),
                "sharded": True,
                "row_collectives_total": int(self.row_collectives),
                "scale_reduction_bytes_per_gen": int(
                    self.mesh_scale_bytes),
                **(self._mesh_stats or {}),
            }
        ss = getattr(self.owner.distance_function, "sumstat", None)
        if ss is not None:
            # learned-summaries state of THIS run (/api/observability
            # reads it per dispatch engine, next to the global/tenant
            # registry gauges): which mode serves the transform and the
            # C -> C' reduction the packed fetch ships
            plan = getattr(self.owner, "_sumstat_device_plan", None)
            dim_raw = int(getattr(self.owner.spec, "total_size", 0) or 0)
            block = {
                "mode": "device" if plan is not None else "host",
                "transform": type(ss).__name__,
                "dim_raw": dim_raw,
            }
            if plan is not None:
                block["kind"] = str(plan["kind"])
                block["dim_reduced"] = int(plan["out_dim"])
            snap["sumstat"] = block
        return snap

    def _note_mesh_stats(self, fetched, g_done: int) -> None:
        """Per-device busy/imbalance accounting from the chunk's
        ``rounds_shard`` / ``n_acc_shard`` outputs (sharded runs ship
        them on the packed fetch — zero extra syncs). Imbalance = max
        over shards of rounds worked / mean — the number the mesh lane
        records so uneven acceptance across shards is measured, not
        assumed."""
        if "rounds_shard" not in fetched or g_done <= 0:
            return
        rounds = np.asarray(fetched["rounds_shard"])[:g_done]
        n_acc = np.asarray(fetched["n_acc_shard"])[:g_done]
        per_dev_rounds = rounds.sum(axis=0).astype(float)
        mean = float(per_dev_rounds.mean())
        imbalance = (float(per_dev_rounds.max()) / mean
                     if mean > 0 else 1.0)
        busy_max = (float(per_dev_rounds.max()) / float(
            per_dev_rounds.sum()) if per_dev_rounds.sum() > 0
            else 1.0 / max(self.mesh_shards or 1, 1))
        # cross-shard ROW collectives of this chunk: one packed-fetch
        # merge gather + one theta all-gather per in-kernel cadence
        # refit — counted from the chunk's own refit flags so the gap
        # accounting sees what actually crossed the mesh
        chunk_row_colls = 1
        if "refit" in fetched:
            chunk_row_colls += int(
                np.asarray(fetched["refit"])[:g_done].sum())
        self.row_collectives += chunk_row_colls
        self._mesh_stats = {
            "rounds_per_device": [int(r) for r in per_dev_rounds],
            "accepted_per_device": [int(a) for a in n_acc.sum(axis=0)],
            "imbalance": round(imbalance, 4),
            "busy_max_frac": round(busy_max, 4),
        }
        # composed sharded+segmented chunks (ISSUE 17) ship per-shard
        # retire columns on the same packed fetch: retire imbalance =
        # how unevenly the early-reject bound fired across lane blocks
        retire_imb = None
        if "retired_shard" in fetched:
            per_dev_ret = np.asarray(
                fetched["retired_shard"])[:g_done].sum(axis=0).astype(float)
            rmean = float(per_dev_ret.mean())
            retire_imb = (float(per_dev_ret.max()) / rmean
                          if rmean > 0 else 1.0)
            self._mesh_stats["retired_per_device"] = [
                int(r) for r in per_dev_ret]
            self._mesh_stats["retire_imbalance"] = round(retire_imb, 4)
        from ..observability import global_metrics

        for reg in (self.owner.metrics, global_metrics()):
            reg.counter(
                MESH_ROW_COLLECTIVES_TOTAL,
                "cross-shard row collectives (packed-fetch merge "
                "gathers + cadence-refit theta all-gathers) of sharded "
                "runs",
            ).inc(chunk_row_colls)
            reg.gauge(
                MESH_SCALE_BYTES_GAUGE,
                "per-generation cross-shard payload of the adaptive "
                "scale reduction + stochastic record-column gathers",
            ).set(float(self.mesh_scale_bytes))
            reg.gauge(
                MESH_DEVICES_GAUGE,
                "devices of the mesh the sharded multigen kernel runs on",
            ).set(float(self.mesh_shards))
            reg.gauge(
                MESH_IMBALANCE_GAUGE,
                "per-shard proposal-round imbalance of the last chunk "
                "(max/mean; 1.0 = perfectly balanced)",
            ).set(imbalance)
            reg.gauge(
                MESH_BUSY_MAX_GAUGE,
                "busiest shard's share of mesh proposal rounds in the "
                "last chunk",
            ).set(busy_max)
            if retire_imb is not None:
                reg.gauge(
                    SIM_RETIRE_IMBALANCE_GAUGE,
                    "per-shard early-reject imbalance of the last chunk "
                    "(max/mean of lanes retired; 1.0 = evenly spread)",
                ).set(retire_imb)

    def sync_budget_report(self) -> dict:
        """The per-run sync budget, asserted through the SyncLedger:
        ``syncs_per_run <= chunks + O(1)`` — each PROCESSED chunk pays
        exactly one packed fetch; compute probes (one per DISPATCHED
        chunk, opt-in) and checkpoint fetches (one per
        ``checkpoint_every`` processed chunks) are declared per-chunk
        terms, everything else must fit the O(1) allowance."""
        owner = self.owner
        per_chunk_allowance = self.chunks_processed
        if owner.compute_probe:
            per_chunk_allowance += self.chunks_dispatched
        if owner._checkpoint is not None and not self.sumstat_refit:
            per_chunk_allowance += (
                self.chunks_processed // max(owner.checkpoint_every, 1) + 1
            )
        return owner.sync_ledger.budget_report(
            chunks=self.chunks_processed,
            allowed=per_chunk_allowance + SYNC_BUDGET_O1,
        )

    # ----------------------------------------------------- dispatch / fetch
    def _dispatch_chunk(self, carry, t_at: int, g_limit: int):
        """Enqueue one chunk (async). ``carry`` is either the host-built
        initial carry or the PREVIOUS chunk's on-device final carry —
        chaining device-to-device lets chunk k+1 compute while chunk
        k's outputs are still being fetched/persisted."""
        import jax.numpy as jnp

        # resilience fault site (round 10): numeric CORRUPTION of the
        # dispatched chunk's input carry — silent NaN/cov/weight poison
        # that never raises, exactly what the in-kernel health word
        # exists to catch. The clean carry ref stays untouched (rollback
        # reuses it); the poison is traceable jnp ops riding the normal
        # dispatch, no sync.
        from ..resilience.faults import maybe_corrupt

        kind = maybe_corrupt("device.carry", t=int(t_at))
        if kind is not None:
            from ..ops.health import poison_carry

            logger.warning(
                "injected carry corruption %r at t=%d", kind, t_at
            )
            carry = poison_carry(carry, kind)
        host = self.chunk_host_args(t_at, g_limit)
        self.chunks_dispatched += 1
        return self.kern(
            self.owner._root_key, jnp.asarray(t_at, jnp.int32),
            jnp.asarray(host["n_sched"]),
            jnp.asarray(g_limit, jnp.int32), carry,
            jnp.asarray(
                self.owner.model_perturbation_kernel.device_params()),
            jnp.asarray(host["eps_fixed"]),
            jnp.asarray(self.stop["minimum_epsilon"], jnp.float32),
            jnp.asarray(self.stop["min_acceptance_rate"], jnp.float32),
            host["dist_sched"],
            host["fold_sched"],
        )

    def _fetch_tree(self, res_i, t_at: int, g_lim: int):
        """Device-side fetch compaction (ops/pack.py): theta / distance /
        log_weight collapse into ONE narrowed-dtype row buffer sliced to
        the scheduled population, slot is elided (the reservoir is
        slot-ordered by construction), m ships only for K > 1, and
        per-particle sum stats — the dominant payload when retained
        (~70%) — ship only for generations History persists
        (sumstat-refit mode additionally needs the chunk's FINAL
        generation for the boundary refit)."""
        import jax

        owner = self.owner
        outs = res_i["outs"]
        ss_wanted = [
            (self.sumstat_refit and g == g_lim - 1)
            or owner.history.wants_sum_stats(t_at + g)
            for g in range(g_lim)
        ]
        ss_gens = ("all" if all(ss_wanted)
                   else tuple(g for g in range(g_lim) if ss_wanted[g]))
        tree = self.ctx.fetch_pack_kernel(
            n_keep=self.n_keep, dtype_name=self.fetch_dtype,
            keep_m=owner.K > 1, ss_gens=ss_gens, g_keep=int(g_lim),
            # "dynamic" = the HOST merges per generation (population
            # schedules / adaptive n); the kernel ships the full
            # shard-blocked reservoir untouched
            merge_index=(None if isinstance(self.shard_merge, str)
                         else self.shard_merge),
        )(outs)
        if "calib" in res_i and t_at == 0:
            # the run-starting chunk carries the in-kernel calibration's
            # initial weights / eps_0 for host mirroring
            tree["__calib__"] = res_i["calib"]
        # what the round-5 full-f32-ring fetch would have moved for this
        # chunk (aval-level .nbytes — no device op): the compaction
        # ratio ships with each chunk event so payload reduction is a
        # regression-guarded metric, not a one-off
        r5_bytes = sum(
            x.nbytes for x in jax.tree.leaves(
                {k: v for k, v in outs.items() if k != "sumstats"}
            )
        )
        if ss_gens == "all":
            r5_bytes += outs["sumstats"].nbytes
        else:
            r5_bytes += (
                outs["sumstats"].nbytes // outs["sumstats"].shape[0]
            ) * len(ss_gens)
        return tree, r5_bytes

    def _merge_shard_rows(self, fetched, ss_rows, t_at: int,
                          g_lim: int) -> None:
        """Host half of the DYNAMIC shard merge (population schedules /
        in-kernel adaptive n): each generation's fetched rows arrive in
        the shard-blocked reservoir layout; re-index its first ``n_t``
        rows with that generation's static-quota merge gather
        (ops/shard.py::merge_index) so downstream slicing sees the same
        dense accepted order the static in-fetch merge produces. A numpy
        take per generation — microseconds against the fetch itself."""
        from ..ops.shard import merge_index

        cap_loc = self._n_cap // self.mesh_shards
        for g in range(g_lim):
            if self.adaptive_n:
                n_t = int(np.asarray(fetched["n_target"][g]))
            else:
                n_t = int(self.n_of(t_at + g))
            n_t = min(n_t, self._n_cap)
            idx = merge_index(n_t, self.mesh_shards, cap_loc)
            for key in ("theta", "distance", "log_weight", "m",
                        "sumstats"):
                if key in fetched:
                    v = fetched[key]
                    if not v.flags.writeable:
                        v = fetched[key] = np.array(v)
                    v[g, :n_t] = v[g][idx]
            if ss_rows and g in ss_rows:
                v = ss_rows[g]
                if not v.flags.writeable:
                    v = ss_rows[g] = np.array(v)
                v[:n_t] = v[idx]

    def _unpack_fetched(self, fetched):
        """Host-side inverse of the pack kernel: restore the legacy
        per-leaf layout (upcast — the narrowing lives on the wire only)
        and reconstruct the elided leaves."""
        from ..ops.pack import unpack_rows

        rows = fetched.pop("rows")
        theta, dist, log_w = unpack_rows(rows, self.ctx.d_max)
        fetched["theta"] = theta
        fetched["distance"] = dist
        fetched["log_weight"] = log_w
        gn = rows.shape[:2]
        if "m" in fetched:
            fetched["m"] = np.asarray(fetched["m"], np.int32)
        else:
            fetched["m"] = np.zeros(gn, np.int32)
        # the reservoir is written in slot order, so arange is the
        # identity the argsort-by-proposal-id trim expects
        fetched["slot"] = np.broadcast_to(
            np.arange(gn[1], dtype=np.int32), gn
        )
        if "sumstats" in fetched:
            fetched["sumstats"] = np.asarray(
                fetched["sumstats"], np.float32
            )
        return fetched

    def _probe(self, out, disp_ts: float) -> None:
        import jax

        jax.block_until_ready(out)
        self.owner.sync_ledger.record("compute_probe")
        self.owner.probe_events.append((disp_ts, self._clock.now()))

    def _submit(self, res_i, t_at: int, g_lim: int):
        import jax

        if self._probe_pool is not None:
            self._probe_pool.submit(self._probe, res_i["outs"]["gen_ok"],
                                    self._clock.now())
        tree, r5_bytes = self._fetch_tree(res_i, t_at, g_lim)
        if self._executor is None:
            return tree, r5_bytes  # fetched synchronously at pop time
        return self._executor.submit(jax.device_get, tree), r5_bytes

    # ------------------------------------------------------------ the loop
    def _refill(self) -> float:
        """FILL: keep the device fed — dispatch speculative chunks off
        the newest on-device carry and start their fetches, up to
        ``depth`` in flight. Returns the dispatch wall share."""
        self.state = FILL
        t_disp0 = self._clock.now()
        with self.owner.tracer.span("dispatch"):
            while (not self.sumstat_refit
                   and len(self.pending) < self.refill_target):
                lr, lt, lg = self.tail
                g_next = self.g_limit(lt + lg)
                if g_next <= 0:
                    break
                nxt = self._dispatch_chunk(lr["carry"], lt + lg, g_next)
                self.tail = (nxt, lt + lg, g_next)
                self.pending.append((self._submit(nxt, lt + lg, g_next),
                                     lt + lg, g_next, nxt["carry"]))
        return self._clock.now() - t_disp0

    def _maybe_drain_handoff(self) -> bool:
        """DRAIN handoff: schedule exhausted and the owner asked for
        ``drain_async`` — everything left is fetch+persist with no
        successor compute to hide behind, so the SAME step body keeps
        running on a background thread while the caller's next work
        overlaps the latency."""
        owner = self.owner
        if not (owner.drain_async and not self.sumstat_refit
                and self.chunk_index >= 1 and self.pending
                and self.g_limit(self.tail[1] + self.tail[2]) <= 0):
            return False
        import threading

        self.state = DRAIN
        owner._drain_error = None
        owner._drain_thread = threading.Thread(
            target=self._drain_loop, daemon=True, name="pyabc-tpu-drain",
        )
        owner._drain_thread.start()
        self.drained_async = True
        return True

    def _drain_loop(self) -> None:
        """The DRAIN state: the foreground loop's step body, verbatim, on
        the drain thread (only one of the two ever runs — the handoff is
        the foreground's last act, so the engine state is safe)."""
        owner = self.owner
        try:
            try:
                while self.pending:
                    (stop, last_pop, *_rest,
                     health_fail) = self._process_next(0.0)
                    if last_pop is not None:
                        owner._mirror_chunk_fit(last_pop)
                    if health_fail is not None:
                        # the generation schedule already ended: no
                        # redispatch can recover this — record the event
                        # and surface a typed failure through
                        # drain_join() instead of a silent partial db
                        from ..resilience.health import DegenerateRunError

                        owner.health_supervisor.on_failure(
                            health_fail["t"], health_fail["word"],
                            ess=health_fail.get("ess"),
                            acc_rate=health_fail.get("acc_rate"),
                            eps=health_fail.get("eps"),
                        )
                        raise DegenerateRunError(
                            f"in-kernel health failure at "
                            f"t={health_fail['t']} during the async "
                            f"drain (schedule exhausted, no redispatch "
                            f"possible)",
                            owner.health_supervisor.trail,
                        )
                    if stop:
                        self._discard_speculative("stopping_rule")
                        break
            finally:
                self._shutdown_pools()
            self._complete()
        except BaseException as exc:  # surfaced by drain_join()
            owner._drain_error = exc
            try:
                owner.history.flush()
            except Exception:
                logger.exception(
                    "async history writer also failed while draining"
                )

    def _process_next(self, dispatch_s: float):
        """PROCESS: fetch + host-process the oldest pending chunk (shared
        by the foreground loop and the DRAIN thread)."""
        import jax

        owner = self.owner
        clk = self._clock.now
        self.state = PROCESS
        # cooperative graceful stop (serving-layer drain): a stop
        # requested from another thread becomes the SIGTERM path at this
        # chunk boundary — flush + final checkpoint via the owner's
        # BaseException handler, exactly like an in-thread signal
        stop_signum = getattr(owner, "_stop_signum", None)
        if stop_signum is not None:
            from .smc import GracefulShutdown

            raise GracefulShutdown(stop_signum)
        # resilience fault site: an injected orchestrator kill lands
        # HERE — after dispatch, before the chunk's results are
        # processed/persisted — the worst spot for generation-granularity
        # resume and exactly what the mid-chunk checkpoint heals
        from ..resilience.faults import maybe_fault

        maybe_fault("orchestrator.chunk", chunk_index=self.chunk_index)
        (handle, r5_bytes), t_at, g_lim, carry_ref = self.pending.pop(0)
        logger.info("t: %d..%d (fused chunk of %d)", t_at,
                    t_at + g_lim - 1, g_lim)
        with owner.tracer.span("chunk", t_first=int(t_at),
                               gens=int(g_lim)) as c_span:
            t_fetch0 = clk()
            with owner.tracer.span("fetch", t_first=int(t_at)):
                fetched = (handle.result() if self._executor is not None
                           else jax.device_get(handle))
            now = clk()
            fetch_s = now - t_fetch0   # EXPOSED wait (latency pipelined)
            chunk_s = now - self._t_chunk0  # pipeline period
            self._t_chunk0 = now
            # measured wire payload of this chunk (post-compaction);
            # feeds the bench's fetch_bytes_per_chunk regression metric
            fetch_bytes = sum(
                int(np.asarray(leaf).nbytes)
                for leaf in jax.tree.leaves(fetched)
            )
            owner.sync_ledger.record("chunk_fetch", fetch_bytes)
            ss_rows = fetched.pop("__ss_rows__", None)
            if ss_rows is not None:
                ss_rows = {
                    g: np.asarray(v, np.float32)
                    for g, v in ss_rows.items()
                }
            elif "sumstats" not in fetched:
                # no generation of this chunk retains sum stats: the
                # pack kernel shipped none at all
                ss_rows = {}
            calib = fetched.pop("__calib__", None)
            fetched = self._unpack_fetched(fetched)
            if isinstance(self.shard_merge, str):
                self._merge_shard_rows(fetched, ss_rows, t_at, g_lim)
            if calib is not None:
                owner._mirror_fused_calibration(calib)
            mem_telemetry = owner._device_memory_telemetry()
            self.chunk_index += 1
            self.chunks_processed += 1
            t_proc0 = clk()
            s = self.stop
            with owner.tracer.span("process", t_first=int(t_at)):
                (stop, last_pop, last_sample, last_eps, last_acc_rate,
                 self.t, self.sims_total, n_acc_chunk, g_done,
                 health_fail) = owner._process_chunk(
                    fetched, ss_rows, self.t, g_lim, self.n_of,
                    self.adaptive_n, self.adaptive, self.stochastic,
                    self.temp_fixed, self.eps_quantile,
                    self.sumstat_refit, self.chunk_index, chunk_s,
                    dispatch_s, fetch_s, self.depth, mem_telemetry,
                    self.sims_total, s["minimum_epsilon"],
                    s["max_nr_populations"], s["min_acceptance_rate"],
                    s["max_total_nr_simulations"], s["max_walltime"],
                    s["start_walltime"],
                )
            c_span.set(chunk_index=int(self.chunk_index),
                       n_acc=int(n_acc_chunk), g_done=int(g_done),
                       chunk_s=round(float(chunk_s), 6),
                       fetch_s=round(float(fetch_s), 6),
                       dispatch_s=round(float(dispatch_s), 6))
            owner.metrics.histogram(
                "pyabc_tpu_chunk_fetch_seconds",
                "exposed device->host fetch wait per fused chunk",
            ).observe(float(fetch_s))
            owner.metrics.histogram(
                "pyabc_tpu_chunk_fetch_bytes",
                "device->host wire payload per fused chunk "
                "(post-compaction)",
            ).observe(float(fetch_bytes))
            owner.metrics.counter(
                "pyabc_tpu_particles_accepted",
                "accepted particles across fused chunks",
            ).inc(int(n_acc_chunk))
        if self.mesh_shards:
            self._note_mesh_stats(fetched, int(g_done))
        if health_fail is None and not stop and g_done == g_lim:
            # the chunk boundary is known-healthy: it becomes the
            # supervisor's rollback target and the graceful-shutdown
            # final-checkpoint state
            self.good_carry = (self.t, carry_ref)
            if not self.sumstat_refit:
                owner._final_ck_state = (carry_ref, self.t,
                                         self.sims_total,
                                         self.chunk_index)
        if (owner._checkpoint is not None and not self.sumstat_refit
                and health_fail is None
                and not stop and g_done == g_lim
                and self.chunk_index % owner.checkpoint_every == 0):
            # persist the chunk's final device carry (flush-first: the
            # db stays at-or-ahead of the checkpoint). sumstat-refit
            # mode is excluded — its carry is rebuilt host-side at every
            # chunk boundary, so the device carry is not the resume
            # state there (README documents the deviation).
            try:
                owner._save_fused_checkpoint(
                    carry_ref, self.t, self.sims_total, self.chunk_index
                )
            except Exception:
                # a failed checkpoint degrades durability, never the run
                logger.exception(
                    "fused checkpoint save failed (run continues)"
                )
        if owner.chunk_event_cb is not None:
            try:
                ev = {
                    "ts": clk(), "t_first": int(t_at),
                    "gens": int(g_done), "n_acc": int(n_acc_chunk),
                    "chunk_index": int(self.chunk_index),
                    "chunk_s": float(chunk_s),
                    "fetch_s": float(fetch_s),
                    "fetch_bytes": int(fetch_bytes),
                    "fetch_bytes_full_f32": int(r5_bytes),
                    "dispatch_s": float(dispatch_s),
                    "process_s": float(clk() - t_proc0),
                }
                if "refit" in fetched and g_done > 0:
                    # refit-cadence telemetry rides the chunk events so
                    # the bench's scale lane can report refits_per_run
                    # without touching the History
                    ev["refits"] = int(
                        np.asarray(fetched["refit"])[:g_done].sum())
                    ev["drift_last"] = float(
                        np.asarray(fetched["drift"])[g_done - 1])
                self.owner.chunk_event_cb(ev)
            except Exception:
                logger.exception("chunk_event_cb failed")
        # span-federation cadence (ISSUE 19): installed SpanShippers
        # piggyback on the processed chunk — pure host-side TCP, no
        # device touch, so the SyncLedger stays identical with
        # federation on or off (strict-budget-asserted)
        fire_span_ship_hooks()
        return (stop, last_pop, last_sample, last_eps, last_acc_rate,
                t_at, g_lim, health_fail)

    def _after_process(self, outcome) -> bool:
        """Route the processed chunk's outcome to the next state.
        Returns False to leave the loop (STOPPED / schedule done)."""
        (stop, last_pop, last_sample, last_eps, last_acc_rate,
         t_at, g_lim, health_fail) = outcome
        owner = self.owner
        if health_fail is not None:
            self._recover(health_fail, last_pop)
            return bool(self.pending)
        continuing = (not stop and last_pop is not None
                      and (self.pending
                           or self.g_limit(t_at + g_lim) > 0))
        if last_pop is not None \
                and not (continuing and self.sumstat_refit):
            # (the sumstat-refit continue path fits these inside
            # _adapt_components below — don't pay the KDE fit twice)
            owner._mirror_chunk_fit(last_pop)
        if not continuing:
            self.state = STOPPED
            if stop:
                # speculative chunks dispatched past the stopping-rule
                # hit roll back: strictly-in-order processing means
                # nothing of theirs was persisted or mirrored — discard
                # them unfetched and count the rollback
                self._discard_speculative("stopping_rule")
            return False
        if self.sumstat_refit:
            self._boundary_refit(last_sample, last_pop, last_eps,
                                 last_acc_rate)
        return True

    def _recover(self, health_fail: dict, last_pop) -> None:
        """RECOVER: in-kernel health failure — abort the chunk (nothing
        at/past the failed generation was persisted), let the supervisor
        decide — it raises a typed DegenerateRunError for terminal
        conditions — then roll the carry back and redispatch from the
        failed generation. Speculative chunks dispatched off the
        degraded carry are discarded with it."""
        owner = self.owner
        self.state = RECOVER
        t_fail = health_fail["t"]
        t_detect = self._clock.now()
        if last_pop is not None:
            # host proposal state now reflects t_fail - 1 — the state a
            # host carry rebuild fits from
            owner._mirror_chunk_fit(last_pop)
        action = owner.health_supervisor.on_failure(
            t_fail, health_fail["word"],
            ess=health_fail.get("ess"),
            acc_rate=health_fail.get("acc_rate"),
            eps=health_fail.get("eps"),
            chunk_index=self.chunk_index,
        )
        self._discard_speculative("health_rollback")
        carry_rb, source = owner._health_recovery_carry(
            action, t_fail, self.good_carry, self.rebuild_carry,
        )
        g_next = self.g_limit(t_fail)
        if g_next <= 0:
            return
        logger.warning(
            "health recovery at t=%d: %s from %s (kinds=%s)",
            t_fail, action, source,
            owner.health_supervisor.trail[-1]["kinds"],
        )
        with owner.tracer.span("dispatch", recovery=True,
                               t_first=int(t_fail)):
            res = self._dispatch_chunk(carry_rb, t_fail, g_next)
        self.pending[:] = [(self._submit(res, t_fail, g_next), t_fail,
                            g_next, res["carry"])]
        self.tail = (res, t_fail, g_next)
        owner.health_supervisor.note_recovered(
            t_fail, action, source, t_detect)

    def _boundary_refit(self, last_sample, last_pop, last_eps,
                        last_acc_rate) -> None:
        """BOUNDARY: host boundary adaptation for sumstat-refit mode —
        refit the learned statistics on this chunk's final population,
        refit the scale weights in the NEW feature space and re-derive
        the epsilon under the updated distance (the per-generation
        _adapt_components semantics applied at chunk granularity), then
        dispatch the next chunk off a fresh host-built carry."""
        owner = self.owner
        self.state = BOUNDARY
        # Declared deviation: the boundary scale refit sees the ACCEPTED
        # population only (the reference's all_particles=False
        # convention) — the all-evaluations ring stays on device;
        # in-chunk refits use the full ring.
        owner._adapt_components(self.t - 1, last_sample, last_pop,
                                last_eps, last_acc_rate)
        # the boundary refit DID run: flag it for resume's epsilon-trail
        # replay (flush first — the row may still be queued on the
        # writer thread, and update_telemetry skips missing rows)
        owner.history.flush()
        owner.history.update_telemetry(
            self.t - 1, {"distance_changed": True}
        )
        g_next = self.g_limit(self.t)
        res = self._dispatch_chunk(self.rebuild_carry(self.t), self.t,
                                   g_next)
        self.pending = [(self._submit(res, self.t, g_next), self.t,
                         g_next, res["carry"])]
        self.tail = (res, self.t, g_next)

    # ------------------------------------------------------------- teardown
    def _discard_speculative(self, reason: str) -> None:
        """Roll back in-flight speculative chunks: they were dispatched
        past a stopping-rule hit (or off a degraded carry) and nothing of
        theirs may persist — in-order processing guarantees nothing has,
        so the rollback is a discard, counted so the bench can guard it."""
        n = len(self.pending)
        if n == 0:
            return
        self.pending.clear()
        self.speculative_rollbacks += n
        from ..observability import global_metrics

        for reg in (self.owner.metrics, global_metrics()):
            reg.counter(
                SPECULATIVE_ROLLBACKS_TOTAL,
                "speculative chunks rolled back unpersisted (dispatched "
                "past a stopping-rule hit or health failure)",
            ).inc(n)
        self.owner.tracer.record_span(
            "rollback.speculative", self._clock.now(), self._clock.now(),
            thread="dispatch", n=int(n), reason=reason,
        )
        logger.info(
            "rolled back %d speculative chunk(s) (%s) — nothing past "
            "the stop persists", n, reason,
        )

    def _shutdown_pools(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True, cancel_futures=True)
        if self._probe_pool is not None:
            self._probe_pool.shutdown(wait=True)

    def _complete(self) -> None:
        """DONE: close out the run — History done, stale checkpoint
        cleared, sync budget asserted and exported."""
        owner = self.owner
        self.state = DONE
        owner.history.done()
        if owner._checkpoint is not None:
            # clean completion: the History holds everything; a stale
            # checkpoint must not shadow a future run
            owner._checkpoint.clear()
        report = self.sync_budget_report()
        from ..observability import global_metrics

        for reg in (owner.metrics, global_metrics()):
            # the run registry AND the process-wide one: the dashboard's
            # /api/observability and the broker-status path read the
            # global registry even when the run uses its own
            reg.gauge(
                SYNCS_PER_RUN_GAUGE,
                "blocking device round trips of the last completed run "
                "(budget: chunks + O(1))",
            ).set(float(report["syncs"]))
        if not report["ok"]:
            # the budget is an invariant of this engine's design: a
            # violation means a new blocking round trip crept into the
            # per-chunk path — loud by default, fatal under the strict
            # gate (bench dispatch lane, tests)
            import os

            msg = (f"sync budget exceeded: {report['syncs']} syncs for "
                   f"{report['chunks']} chunks "
                   f"(allowed {report['allowed']}; by_kind="
                   f"{owner.sync_ledger.by_kind()})")
            if os.environ.get("PYABC_TPU_SYNC_BUDGET_STRICT"):
                raise RuntimeError(msg)
            logger.warning(msg)


# --------------------------------------------------------------------------
# The per-generation PIPELINED path (host-adaptive configs): generation
# t+1 is DISPATCHED to the device as soon as the adaptive components are
# refit on generation t's final results; the host then persists
# generation t to the History while the device is already simulating
# t+1. Proposals always use FINAL generation-t weights, so the run is
# statistically identical to the serial loop — no preliminary-weight
# correction is needed; only host-side persistence/analysis overlaps.
# --------------------------------------------------------------------------

def dispatch_speculative_round(abc, t_next: int, n_estimate: int):
    """Enqueue ONE eps=+inf proposal round for generation t_next off the
    just-refit transitions (async; the host continues adapting). The
    delayed acceptance (``abc._speculative_accept``) is applied once the
    strategy updates fixed the generation's threshold/temperature."""
    import jax

    from ..core.random import generation_key

    ctx = abc._build_device_ctx()
    B = abc.sampler._pick_B(n_estimate)
    mode, dyn = ctx.build_dyn_args(
        t=t_next, eps_value=np.inf,
        model_probabilities=abc._model_probs,
        transitions=abc.transitions,
        model_perturbation_kernel=abc.model_perturbation_kernel,
    )
    # dedicated key stream: must not collide with the generation
    # kernel's fold_in(gen_key, round) sequence
    key = jax.random.fold_in(
        generation_key(abc._root_key, t_next), 1 << 20
    )
    out = ctx.round_kernel(B, mode)(key, dyn)
    return {"out": out, "B": B, "accept": abc._speculative_accept,
            "t": t_next}


def run_pipelined(abc, t0, minimum_epsilon, max_nr_populations,
                  min_acceptance_rate, max_total_nr_simulations,
                  max_walltime, start_walltime):
    """Cross-generation pipelined loop (the look-ahead analog) — the
    unfused device path's half of the dispatch engine. See the module
    docstring; ``abc`` (ABCSMC) supplies the statistical hooks."""
    import copy

    t = t0
    sims_total = abc.history.total_nr_simulations
    distance_changed_at_t = getattr(
        abc, "_resumed_distance_changed", False)
    last_strategies_s = 0.0  # first generation never speculates

    clk = abc._clock.now

    def _dispatch(t_next, speculative=None):
        t_d0 = clk()
        current_eps = abc.eps(t_next)
        if hasattr(abc.acceptor, "note_epsilon"):
            abc.acceptor.note_epsilon(t_next, current_eps,
                                      distance_changed_at_t)
        n_t = abc.population_strategy(t_next)
        max_eval = (
            n_t / min_acceptance_rate
            if min_acceptance_rate > 0 else np.inf
        )
        logger.info("t: %d, eps: %.8g", t_next, current_eps)
        with abc.tracer.span("dispatch", t=int(t_next), n=int(n_t)):
            spec = abc._generation_spec(t_next)
            spec_s = clk() - t_d0
            handle = abc.sampler.dispatch(n_t, spec, t_next,
                                          max_eval=max_eval,
                                          speculative=speculative)
        handle["dispatch_telemetry"] = {
            "spec_s": round(spec_s, 4),
            "enqueue_s": round(clk() - t_d0 - spec_s, 4),
        }
        if speculative is not None:
            handle["dispatch_telemetry"]["speculative_accepted"] = (
                len(handle["spec"]["slots"])
                if handle.get("spec") else 0
            )
        return handle, current_eps, n_t

    handle, current_eps, n_t = _dispatch(t)
    while True:
        t_gen0 = clk()
        with abc.tracer.span("collect", t=int(t), n=int(n_t)):
            sample = abc.sampler.collect(handle)
        sample_s = clk() - t_gen0
        n_acc = sample.n_accepted if sample.ms is not None else len(
            sample.accepted_particles
        )
        if n_acc < n_t:
            logger.info(
                "stopping: only %d/%d accepted within budget", n_acc, n_t
            )
            break
        pop = abc._sample_to_population(sample)
        nr_evals = abc.sampler.nr_evaluations_
        sims_total += nr_evals
        acceptance_rate = n_t / nr_evals
        logger.info(
            "acceptance rate: %.5f (%d evaluations)", acceptance_rate,
            nr_evals,
        )
        # shallow copy pins the PRE-adaptation distances for the db
        # (_recompute_distances rebinds pop.distances; reference history
        # keeps the original values)
        db_pop = copy.copy(pop)

        # central adaptation — the PROPOSAL part (transition refits)
        # runs first so a speculative eps=+inf round for t+1 can start
        # on the device WHILE the slow strategy updates (temperature
        # bisection, epsilon quantiles, acceptor norms) run on the host;
        # its delayed acceptance is applied at dispatch time (reference
        # look-ahead with delayed evaluation, SURVEY.md §2.3)
        t_adapt0 = clk()
        spec_round = None
        with abc.tracer.span("adapt", t=int(t)):
            abc._adapt_proposal(pop)
            # every stop rule is decidable BEFORE the slow strategy
            # updates (model probs were refreshed by _adapt_proposal
            # above) — don't burn a speculative round on a generation
            # that will never be dispatched
            surely_stopping = abc._check_stop(
                t, current_eps, minimum_epsilon, max_nr_populations,
                acceptance_rate, min_acceptance_rate, sims_total,
                max_total_nr_simulations, max_walltime, start_walltime)
            if (not surely_stopping
                    and abc._speculation_capable()
                    and last_strategies_s > abc.speculation_min_adapt_s):
                spec_round = dispatch_speculative_round(abc, t + 1, n_t)
            t_strat0 = clk()
            distance_changed_at_t = abc._adapt_strategies(
                t, sample, pop, current_eps, acceptance_rate
            )
            last_strategies_s = clk() - t_strat0
        adapt_s = clk() - t_adapt0

        # re-check AFTER the strategy updates: their duration counts
        # against max_walltime (slow temperature bisections / distance
        # refits must not buy an extra generation past the budget)
        stop = surely_stopping or abc._check_stop(
            t, current_eps, minimum_epsilon, max_nr_populations,
            acceptance_rate, min_acceptance_rate, sims_total,
            max_total_nr_simulations, max_walltime, start_walltime)

        if not stop:
            # LOOK-AHEAD: device starts generation t+1 now ...
            next_handle, next_eps, next_n = _dispatch(
                t + 1, speculative=spec_round)

        # ... while the host persists generation t
        t_persist0 = clk()
        with abc.tracer.span("persist", t=int(t)):
            abc.history.append_population(
                t, current_eps, db_pop, nr_evals, abc.model_names,
                telemetry={"sample_s": round(sample_s, 4),
                           "adapt_s": round(adapt_s, 4),
                           "n_evaluations": int(nr_evals),
                           "acceptance_rate": round(acceptance_rate, 6),
                           "distance_changed":
                               bool(distance_changed_at_t),
                           "pipelined": True,
                           **handle.get("dispatch_telemetry", {})},
            )
        abc.history.update_telemetry(
            t, {"persist_s": round(clk() - t_persist0, 4)}
        )
        if stop:
            break
        handle, current_eps, n_t = next_handle, next_eps, next_n
        t += 1
    abc.history.done()
    return abc.history
