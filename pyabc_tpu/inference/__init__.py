from .dispatch import DispatchEngine
from .smc import ABCSMC, GenerationSpec
from .util import (
    DeviceContext,
    create_prior_pdf,
    create_simulate_function,
    create_transition_pdf,
    create_weight_function,
    evaluate_proposal,
    generate_valid_proposal,
)

__all__ = [
    "ABCSMC", "DispatchEngine", "GenerationSpec", "DeviceContext",
    "create_simulate_function", "generate_valid_proposal",
    "evaluate_proposal", "create_prior_pdf", "create_transition_pdf",
    "create_weight_function",
]
