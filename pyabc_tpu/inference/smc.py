"""ABCSMC — the inference engine (orchestrator).

Reference parity: ``pyabc/smc.py::ABCSMC`` (pre-0.12) /
``pyabc/inference/smc.py::ABCSMC`` (0.12+): full SMC loop with component
lifecycle (initialize/update of distance, epsilon, acceptor, transitions,
population strategy), calibration generation, stopping rules
(minimum_epsilon, max_nr_populations, min_acceptance_rate,
max_total_nr_simulations, max_walltime, stop_if_only_single_model_alive),
db persistence every generation, and resume via ``load``.

TPU-first: when every piece is traceable (JaxModel models, jax-native
priors, device-compatible distance/acceptor/transitions), the per-generation
work is dispatched to `BatchedSampler` as one fused XLA round kernel
(`DeviceContext`); otherwise the reference's scalar closure path runs on the
host. Both paths share this loop — adaptation stays central and host-side,
exactly where the reference centralizes it (SURVEY.md §3.2, §7.1).
"""
from __future__ import annotations

import contextlib
import datetime
import json
import logging
from typing import Callable, Sequence

import numpy as np

from ..acceptor import Acceptor, SimpleFunctionAcceptor, StochasticAcceptor, UniformAcceptor
from ..core.population import Population
from ..core.random import generation_key, root_key
from ..core.random_variables import Distribution
from ..core.sumstat_spec import SumStatSpec
from ..distance import (
    AdaptiveAggregatedDistance,
    AdaptivePNormDistance,
    AggregatedDistance,
    Distance,
    PNormDistance,
    StochasticKernel,
    to_distance,
)
from ..epsilon import Epsilon, MedianEpsilon, NoEpsilon
from ..model import JaxModel, Model, assert_models
from ..observability import (
    NULL_METRICS,
    SyncLedger,
    default_tracer,
    fire_span_ship_hooks,
)
from ..populationstrategy import (
    ConstantPopulationSize,
    ListPopulationSize,
    PopulationStrategy,
)
from ..sampler.base import Sampler
from ..sampler.batched import BatchedSampler
from ..sampler.singlecore import SingleCoreSampler
from ..ops.shard import merge_index as _shard_merge_index
from ..storage.history import History
from ..transition import (
    GridSearchCV,
    LocalTransition,
    ModelPerturbationKernel,
    MultivariateNormalTransition,
    NotEnoughParticles,
    Transition,
)
from .util import DeviceContext, create_simulate_function

logger = logging.getLogger("ABC")


def _call_filtered(fn, **kwargs):
    """Call fn with only the kwargs its signature accepts.

    Components follow the reference lifecycle signatures loosely (user
    subclasses may omit newer kwargs); filtering by signature keeps the
    dispatch tolerant WITHOUT swallowing errors raised inside fn.
    """
    import inspect

    sig = inspect.signature(fn)
    if any(p.kind == p.VAR_KEYWORD for p in sig.parameters.values()):
        return fn(**kwargs)
    return fn(**{k: v for k, v in kwargs.items() if k in sig.parameters})


class DefensivePreliminaryTransition:
    """Mixture proposal ``alpha * prior + (1 - alpha) * KDE`` for
    preliminary look-ahead generations (defensive importance sampling).

    The importance ratio prior/proposal is bounded by ``1 / alpha``, so
    ONE mis-centred preliminary KDE — fit, by construction, on the
    accepted-so-far subset of a still-running generation — can no longer
    assign a near-zero proposal density to an accepted particle and
    collapse the adopted generation's ESS (the round-5 look-ahead flake;
    weights stay exact wrt this mixture, so the estimator is unbiased).
    Host-path only: preliminary closures are evaluated by broker workers.
    """

    def __init__(self, inner, prior, alpha: float):
        self.inner = inner
        self.prior = prior
        self.alpha = float(alpha)

    @property
    def X(self):
        return self.inner.X

    def rvs_single(self):
        import pandas as pd

        if np.random.random() < self.alpha:
            return pd.Series(dict(self.prior.rvs_host()))
        return self.inner.rvs_single()

    def pdf(self, x):
        from ..core.parameters import Parameter

        prior_pd = self.prior.pdf_host(Parameter(dict(x)))
        return (self.alpha * prior_pd
                + (1.0 - self.alpha) * float(self.inner.pdf(x)))


class GenerationSpec:
    """The unit handed to samplers: scalar closure + device kernel context."""

    def __init__(self, *, t, host_simulate_one=None, device=None, mode=None,
                 dyn=None, gen_key=None):
        self.t = t
        self.host_simulate_one = host_simulate_one
        self.device = device
        self.mode = mode
        self.dyn = dyn
        self.gen_key = gen_key

    def __call__(self):
        return self.host_simulate_one()


class GracefulShutdown(BaseException):
    """SIGTERM/SIGINT received while a run was active, converted to a
    raisable so the orchestrator can flush the async History writer and
    write a final checkpoint before exiting — an EXTERNAL kill becomes
    exactly as recoverable as an injected ``orchestrator.chunk`` one.
    A ``BaseException`` (like KeyboardInterrupt) so ordinary ``except
    Exception`` recovery code never swallows a termination request."""

    def __init__(self, signum: int):
        super().__init__(f"terminated by signal {signum}")
        self.signum = int(signum)


class ABCSMC:
    """ABC-SMC with multi-model selection and adaptive components."""

    def __init__(self, models, parameter_priors,
                 distance_function: Distance | Callable | None = None,
                 population_size: int | PopulationStrategy = 100,
                 summary_statistics: Callable | None = None,
                 model_prior=None,
                 model_perturbation_kernel: ModelPerturbationKernel | None = None,
                 transitions: Sequence[Transition] | Transition | None = None,
                 eps: Epsilon | None = None,
                 sampler: Sampler | None = None,
                 acceptor: Acceptor | Callable | None = None,
                 stop_if_only_single_model_alive: bool = False,
                 max_nr_recorded_particles: float = np.inf,
                 seed: int = 0,
                 mesh=None,
                 sharded: int | bool | None = None,
                 early_reject: bool | str = "auto",
                 pipeline: bool = True,
                 fused_generations: int = 8,
                 fetch_pipeline_depth: int = 3,
                 fetch_dtype: str = "float16",
                 refit_every: int | None = None,
                 refit_drift_threshold: float = 0.3,
                 tracer=None,
                 metrics=None,
                 checkpoint_path: str | None = None,
                 checkpoint_every: int = 1,
                 health_checks: bool = True,
                 ess_floor: float = 0.0,
                 health_acc_floor: float = 0.0,
                 eps_stall_window: int = 16,
                 eps_stall_rtol: float = 1e-6,
                 max_health_rollbacks: int = 2,
                 health_widen_factor: float = 1.5):
        self.models: list[Model] = assert_models(models)
        if isinstance(parameter_priors, Distribution):
            parameter_priors = [parameter_priors]
        self.parameter_priors: list[Distribution] = list(parameter_priors)
        if len(self.models) != len(self.parameter_priors):
            raise ValueError("need one prior per model")
        self.K = len(self.models)

        self.distance_function = to_distance(
            distance_function if distance_function is not None
            else PNormDistance(p=2)
        )
        self.eps = eps if eps is not None else MedianEpsilon()
        self.acceptor = SimpleFunctionAcceptor.assert_acceptor(
            acceptor if acceptor is not None else UniformAcceptor()
        )
        # reference sanity pairing: stochastic acceptance needs a kernel
        # distance and a temperature epsilon (reference ABCSMC sanity checks)
        if isinstance(self.acceptor, StochasticAcceptor):
            if not isinstance(self.distance_function, StochasticKernel):
                raise ValueError(
                    "StochasticAcceptor requires a StochasticKernel distance"
                )
            from ..epsilon import ListTemperature, Temperature

            if not isinstance(self.eps, (Temperature, ListTemperature)):
                raise ValueError(
                    "StochasticAcceptor requires a Temperature epsilon "
                    "(a distance-quantile epsilon would yield a negative "
                    "'temperature' and invert acceptance)"
                )
        if isinstance(population_size, PopulationStrategy):
            self.population_strategy = population_size
        else:
            self.population_strategy = ConstantPopulationSize(
                int(population_size)
            )
        self.summary_statistics = summary_statistics
        # model prior: probabilities over model indices (uniform default)
        if model_prior is None:
            self.model_prior_probs = np.full(self.K, 1.0 / self.K)
        else:
            self.model_prior_probs = np.asarray(model_prior, np.float64)
            self.model_prior_probs /= self.model_prior_probs.sum()
        self.model_perturbation_kernel = (
            model_perturbation_kernel
            if model_perturbation_kernel is not None
            else ModelPerturbationKernel(self.K, probability_to_stay=0.7)
        )
        if transitions is None:
            transitions = [MultivariateNormalTransition() for _ in range(self.K)]
        if isinstance(transitions, Transition):
            transitions = [transitions]
        self.transitions: list[Transition] = list(transitions)
        self.stop_if_only_single_model_alive = stop_if_only_single_model_alive
        self.max_nr_recorded_particles = max_nr_recorded_particles
        self.seed = seed
        self.mesh = mesh
        #: sharded fused sampling (ISSUE 9): split the population axis of
        #: the multigen kernel over the one-axis device mesh with
        #: shard_map — per-device lane-key blocks and reservoirs, scalar-
        #: column collectives per generation, the accepted-row merge as a
        #: single all-gather riding the packed fetch at chunk boundaries.
        #: ``None`` (auto): shard whenever a single-process multi-device
        #: mesh is present and the config is sharded-capable (constant
        #: population, non-adaptive distance, uniform acceptor; see
        #: ``_sharded_incapable_reason``), else fall back to the GSPMD
        #: constraint path. ``True``: require it (raise with the reason
        #: when unavailable). ``False``/``0``: never. An ``int`` without
        #: a mesh runs the SAME reduction vmapped over that many virtual
        #: shards on one device — the bit-level parity reference the
        #: sharded tests compare a real mesh run against.
        self.sharded = sharded
        #: segmented early-reject execution (ISSUE 15): when every model
        #: declares a segmented-simulation protocol and the distance has
        #: a monotone prefix bound, the fused kernel's proposal loop
        #: runs segment by segment and RETIRES lanes whose partial
        #: distance already exceeds the generation epsilon, refilling
        #: them with fresh proposals — accepted particles stay
        #: bit-identical to the unsegmented run (only provably-rejected
        #: work is skipped). ``"auto"``: on whenever capable; ``True``:
        #: require it (raise with the blocking reason); ``False``: never
        #: (the classic full-trajectory loop).
        if early_reject not in ("auto", True, False):
            raise ValueError(
                f"early_reject must be 'auto', True or False, "
                f"got {early_reject!r}"
            )
        self.early_reject = early_reject
        #: overlap host persistence with the next generation's device run
        #: (the look-ahead analog; proposals use FINAL weights so no weight
        #: correction is needed — reference redis_eps look_ahead semantics
        #: without the preliminary-weight bias)
        self.pipeline = pipeline
        #: broker look-ahead variance guards (the round-5 flake's root
        #: cause, localized with the observability spans: preliminary
        #: proposals fit on the accepted-so-far SUBSET occasionally sit
        #: narrow/shifted against the final posterior, the importance
        #: ratio prior/preliminary-proposal explodes in the tails, and
        #: the adopted generation's ESS collapses — compounding across
        #: consecutively adopted generations). Two defenses, both
        #: bias-free because weights are always computed wrt the
        #: proposal ACTUALLY used:
        #: - skip look-ahead when the builder population's ESS is below
        #:   ``lookahead_min_ess`` (a KDE fit on a degenerate set would
        #:   propagate the collapse into the next generation);
        #: - widen the preliminary KDE bandwidth by
        #:   ``lookahead_proposal_widen`` (a deliberately broader
        #:   proposal softens the density-ratio tails; the cost — a lower
        #:   preliminary acceptance rate — only spends worker time that
        #:   would otherwise be idle);
        #: - propose from the defensive mixture
        #:   ``lookahead_defensive_frac * prior + (1-frac) * KDE``
        #:   (:class:`DefensivePreliminaryTransition`), which HARD-bounds
        #:   the importance ratio at ``1 / frac`` — the collapse
        #:   mechanism (near-zero preliminary density under an accepted
        #:   particle) is eliminated, not just attenuated.
        self.lookahead_min_ess = 10.0
        self.lookahead_proposal_widen = 1.5
        self.lookahead_defensive_frac = 0.2
        #: speculative eps=+inf look-ahead rounds only pay off when the
        #: host's strategy adaptation outweighs one extra device round
        #: trip; measured per generation and gated on this threshold
        #: (seconds). 0 forces speculation for every eligible generation;
        #: inf disables it. Measured on a v5e via the axon tunnel: a
        #: sync costs ~0.1-0.2 s, and toy/medium configs (pop <= 2000,
        #: ARS records <= ~20k) adapt faster than that — speculation LOST
        #: 19-88% there, so the default only engages for genuinely slow
        #: adaptation (huge record sets, big LocalTransition KDTree fits).
        self.speculation_min_adapt_s = 0.25
        #: run up to this many WHOLE GENERATIONS per device dispatch when
        #: every component has a device-adaptation twin (K=1, constant pop,
        #: MVN transition, quantile/list epsilon, (adaptive) p-norm,
        #: uniform acceptor): transition refit, distance reweighting and the
        #: epsilon update all happen on device inside one lax.scan. <=1
        #: disables chunking (per-generation dispatch as usual).
        self.fused_generations = int(fused_generations)
        #: fused-loop fetch pipeline depth: chunks dispatched ahead with
        #: their device_get running on background threads. A TPU-tunnel
        #: round trip costs ~0.1s of LATENCY regardless of payload, and
        #: concurrent fetches pipeline (measured 4x512KB: 1.26s
        #: sequentially, 0.18s concurrently), so overlapping D in-flight
        #: fetches hides the latency behind the device's compute of later
        #: chunks. Stop detection lags up to D chunks; over-dispatched
        #: chunks are device-side no-ops via the carried stopped flag.
        self.fetch_pipeline_depth = int(fetch_pipeline_depth)
        #: dtype of the fused loop's per-particle fetch payload (theta /
        #: distance / log_weight / stored sum stats) on the wire. The
        #: device carry chain stays f32 — acceptances, epsilon trail and
        #: refits are BIT-IDENTICAL for every setting; only the
        #: History-persisted row values round through this dtype
        #: ("float16": ~5e-4 relative, audited in
        #: tests/test_fetch_precision.py; "bfloat16" for range-extreme
        #: sum stats; "float32" restores the round-5 wire format).
        #: Combined with the device-side row compaction (ops/pack.py)
        #: the default cuts the per-chunk tunnel payload ~2.7x — the
        #: round-5 pop-8192 fetch (~2 MB/chunk at ~12 MB/s) inverted
        #: throughput scaling with population size.
        if fetch_dtype not in ("float16", "bfloat16", "float32"):
            raise ValueError(
                f"fetch_dtype must be float16/bfloat16/float32, "
                f"got {fetch_dtype!r}"
            )
        self.fetch_dtype = str(fetch_dtype)
        #: amortized scale-path proposal engine (LocalTransition on the
        #: fused loop): refit the in-kernel k-NN local-covariance
        #: proposal only every ``refit_every`` generations OR when the
        #: acceptance-weighted mean/cov drift of the accepted population
        #: vs the fitted one crosses ``refit_drift_threshold`` — at pop
        #: 16384 the unconditional per-generation refit (blocked 16k-row
        #: kNN + 16k 4x4 Choleskys + a near-full-row-sort top_k at
        #: k=4096) was the dominant device cost and inverted
        #: throughput-vs-population scaling (BASELINE.md r5: 0.8-4k pps
        #: vs the 143.7k headline). Sampling from a stale fit is
        #: statistically exact — importance weights always use the
        #: proposal params actually sampled from — so cadence trades
        #: only proposal freshness, and the drift guard bounds that.
        #: None = auto: 16 for LocalTransition at populations >= 16384,
        #: else 1 (refit every generation, the pre-cadence behavior).
        self.refit_every = (int(refit_every) if refit_every is not None
                            else None)
        self.refit_drift_threshold = float(refit_drift_threshold)
        #: (t, refit?, drift, rows_changed) per fused generation — the
        #: host mirror of the in-kernel refit events (bench `scale` lane
        #: reads refits_per_run off it; metrics get the same events)
        self.refit_events: list[tuple] = []
        #: fused loop: once the generation schedule is exhausted, hand the
        #: still-in-flight final fetches to a background drain thread and
        #: return immediately. The run's LAST chunks' fetch latency (which
        #: has no successor compute of its own to hide behind) then
        #: overlaps whatever the caller does next — e.g. a back-to-back
        #: benchmark run's compute. The History is incomplete until
        #: :meth:`drain_join` returns; ``run()`` callers that read results
        #: right away should leave this off (default).
        self.drain_async = False
        #: optional callback fired after each fused chunk is processed
        #: (on whichever thread processed it) with a dict of completion
        #: telemetry: ts, t_first, gens, n_acc, chunk_index, chunk_s,
        #: fetch_s, dispatch_s, process_s. Exceptions are logged, never
        #: propagated into the loop.
        self.chunk_event_cb = None
        #: when True, a dedicated single-worker thread calls
        #: block_until_ready on one tiny output of every dispatched fused
        #: chunk and records (dispatch_return_ts, device_done_ts) into
        #: :attr:`probe_events` — the bench derives a measured
        #: device-busy fraction from consecutive completion times
        #: (device executes chunks in dispatch order, so
        #: done_k - max(done_{k-1}, dispatch_k) ~ chunk compute). The
        #: probe adds one pipelined tunnel round trip per chunk; off by
        #: default.
        self.compute_probe = False
        self.probe_events: list[tuple[float, float]] = []
        self._drain_thread = None
        self._drain_error: BaseException | None = None
        #: the current run's DispatchEngine (inference/dispatch.py) —
        #: the single owner of chunk dispatch/fetch; tests and the bench
        #: read its snapshot()/sync_budget_report() after a run
        self._engine = None
        #: (carry_ref, t, sims, chunk_index) of the newest healthy chunk
        #: boundary — the graceful-shutdown final-checkpoint state
        self._final_ck_state = None
        self._root_key = root_key(seed)
        #: observability (pyabc_tpu/observability/): host-boundary tracing
        #: spans + metrics. Defaults are no-op-cheap (NullTracer /
        #: NullMetrics); pass ``tracer=Tracer(...)`` or set the env var
        #: PYABC_TPU_TRACE=/path/trace.jsonl to record. Instrumentation
        #: never enters traced/compiled device code, so fused kernels are
        #: byte-identical with observability on or off. All host timing in
        #: this class reads the tracer's injected clock (monotonic).
        self.tracer = tracer if tracer is not None else default_tracer()
        self.metrics = metrics if metrics is not None else NULL_METRICS
        #: capability-gate fallbacks of this run: {"gate", "reason"}
        #: dicts recorded whenever a requested fast path silently fell
        #: back (sharded kernel, segmented early reject, ...). Surfaced
        #: through History telemetry, the dispatch snapshot
        #: (/api/observability) and the fallback counters — "why is
        #: this run not on the fast path" is a query, not a log grep.
        self._capability_fallbacks: list = []
        self._fallbacks_reported = False
        #: resolved device-native learned-sumstat fit plan of the run
        #: (ISSUE 20) — None outside sumstat mode AND in the legacy
        #: host-refit dispatch mode; set by _loop_fused per run
        self._sumstat_device_plan: dict | None = None
        self._sumstat_reported = False
        self._clock = self.tracer.clock
        #: device-sync accounting (observability/sync.py): every blocking
        #: host<->device round trip of this run — chunk fetches, compute
        #: probes, per-generation collects — is recorded here; the bench
        #: multiplies the count by the measured ~102 ms tunnel floor to
        #: ATTRIBUTE the residual wall-clock gap (VERDICT r5 Next #1c)
        self.sync_ledger = SyncLedger(clock=self._clock)
        #: mid-chunk device checkpointing (resilience subsystem, round 9):
        #: with a ``checkpoint_path``, the fused loop persists the chunk
        #: chain's on-device carry (RNG key data, fitted-proposal state,
        #: epsilon / pdf-norm trail, refit-cadence counter) every
        #: ``checkpoint_every`` processed chunks — atomically, after a
        #: History flush, so a killed orchestrator resumes MID-CHUNK from
        #: the exact carry (bit-identical trajectory) instead of
        #: replaying a host transition fit off the last History
        #: generation. A cleanly finished run deletes its checkpoint.
        self.checkpoint_every = max(int(checkpoint_every), 1)
        if checkpoint_path is not None:
            from ..resilience.checkpoint import CheckpointManager

            self._checkpoint = CheckpointManager(
                checkpoint_path, clock=self._clock, tracer=self.tracer,
                metrics=self.metrics,
            )
        else:
            self._checkpoint = None
        #: numerical & statistical health guards (round 10): the fused
        #: multigen kernel computes a per-generation in-kernel health
        #: word (ops/health.py — NaN/Inf in theta/weights/distances,
        #: zero total weight, ESS below ``ess_floor * n_target``,
        #: acceptance below ``health_acc_floor``, an epsilon-progress
        #: stall over ``eps_stall_window`` generations at relative
        #: improvement < ``eps_stall_rtol``, and non-finite/zero-mass
        #: proposal params after the Cholesky jitter-escalation ladder)
        #: that rides the packed fetch at zero extra syncs; the host
        #: RunSupervisor (resilience/health.py) maps nonzero words to
        #: recovery — abort-chunk-and-rollback to the checkpoint / last
        #: healthy carry, forced host refit on PSD failure, proposal
        #: widening (x ``health_widen_factor``) on ESS collapse — under
        #: a ``max_health_rollbacks`` budget, past which (or on a stall)
        #: the run terminates with a typed DegenerateRunError carrying
        #: the per-generation health trail. ``ess_floor``/
        #: ``health_acc_floor`` default to 0 (the NaN/PSD/stall guards
        #: are always armed; the statistical floors are opt-in — tune
        #: them to the workload, see README "Numerical health").
        self.health_checks = bool(health_checks)
        self.ess_floor = float(ess_floor)
        self.health_acc_floor = float(health_acc_floor)
        self.eps_stall_window = int(eps_stall_window)
        self.eps_stall_rtol = float(eps_stall_rtol)
        self.max_health_rollbacks = int(max_health_rollbacks)
        self.health_widen_factor = float(health_widen_factor)
        #: the current run's RunSupervisor (fresh per run; tests read
        #: its trail / rollback count after a run)
        self.health_supervisor = None
        #: cooperative graceful-stop request (round 14, the serving
        #: layer): signal handlers only exist on the main thread, but a
        #: RunScheduler runs MANY tenants on orchestrator threads — a
        #: drain must still give each of them the SIGTERM path (flush +
        #: final checkpoint). :meth:`request_graceful_stop` sets this;
        #: the dispatch engine converts it into a GracefulShutdown at
        #: the next chunk boundary, which flows through the exact
        #: BaseException path an in-thread signal would have taken.
        self._stop_signum: int | None = None
        #: decoded checkpoint carry awaiting adoption by the fused loop
        self._resume_carry = None
        #: generation the last run resumed at via the checkpoint (None =
        #: fresh / generation-granularity resume) — tests assert on it
        self.resumed_from_checkpoint_t: int | None = None

        self._device_capable = self._check_device_capable()
        if sampler is None:
            sampler = (
                BatchedSampler() if self._device_capable
                else SingleCoreSampler()
            )
        self.sampler = sampler
        self.sampler.sample_factory.max_nr_rejected = max_nr_recorded_particles

        # run state
        self.history: History | None = None
        self.x_0: dict | None = None
        self.spec: SumStatSpec | None = None
        self._device_ctx: DeviceContext | None = None
        self._model_probs: dict[int, float] = {}
        self.minimum_epsilon = 0.0
        self.max_nr_populations = np.inf
        self.min_acceptance_rate = 0.0
        self.max_total_nr_simulations = np.inf
        self.max_walltime = None

    # ------------------------------------------------------------- plumbing
    def _check_device_capable(self) -> bool:
        if self.summary_statistics is not None:
            # a user summary_statistics callable runs host-side on raw model
            # output; the device kernel flattens model.sim(...) directly and
            # would silently skip it — force the host path
            return False
        if not all(isinstance(m, JaxModel) for m in self.models):
            return False
        if not all(p.traceable for p in self.parameter_priors):
            return False
        if not self.distance_function.is_device_compatible():
            return False
        if not all(t.is_device_compatible() for t in self.transitions):
            return False
        # acceptor: uniform/stochastic have device forms; plain callables not
        try:
            compat = self.acceptor.is_device_compatible()
        except Exception:
            compat = False
        # StochasticAcceptor only knows after initialize(); optimistic here
        if isinstance(self.acceptor, StochasticAcceptor):
            compat = self.distance_function.is_device_compatible()
        return bool(compat)

    @property
    def model_names(self) -> list[str]:
        return [m.name for m in self.models]

    # ------------------------------------------------------------ lifecycle
    def new(self, db: str, observed_sum_stat: dict | None = None, *,
            gt_model: int | None = None, gt_par: dict | None = None,
            meta_info: dict | None = None,
            store_sum_stats: bool | int = True) -> History:
        """Open a new run in ``db``; store observed data (reference .new).

        ``store_sum_stats``: per-particle sumstat retention (True = every
        generation; False = never; int k = every k-th generation). On the
        fused device path, skipped generations avoid the sumstat
        device->host fetch entirely — the dominant share of the per-chunk
        transfer payload.
        """
        # per-run host setup is part of the wall clock a user experiences
        # between back-to-back runs — span it so the bench's coverage
        # accountant attributes it instead of reporting dark time
        # (VERDICT r5 Next #1b; the span name "setup" is a WORK span, not
        # excluded like the "run" root)
        with self.tracer.span("setup", phase="history.new", db=db):
            observed = {
                k: np.asarray(v)
                for k, v in (observed_sum_stat or {}).items()
            }
            self.x_0 = observed
            self.spec = SumStatSpec(observed) if observed else None
            self._resumed_distance_changed = False  # only load() sets this
            self.history = History(db, store_sum_stats=store_sum_stats,
                                   tracer=self.tracer, metrics=self.metrics)
            options = dict(meta_info or {})
            options["parameter_names"] = {
                m: list(p.space.names)
                for m, p in enumerate(self.parameter_priors)
            }
            self.history.store_initial_data(
                gt_model, options, observed, gt_par or {}, self.model_names,
                json.dumps(self.distance_function.get_config()),
                json.dumps(self.eps.get_config()),
                json.dumps(self.population_strategy.get_config()),
            )
        return self.history

    def load(self, db: str, abc_id: int, observed_sum_stat: dict | None = None
             ) -> History:
        """Resume a stored run (reference .load): continue at max_t + 1."""
        self.history = History(db, abc_id, tracer=self.tracer,
                               metrics=self.metrics)
        observed = observed_sum_stat or self.history.get_observed_sum_stat()
        self.x_0 = {k: np.asarray(v) for k, v in observed.items()}
        self.spec = SumStatSpec(self.x_0)
        return self.history

    def adopt_device_context(self, other: "ABCSMC") -> None:
        """Share another run's compiled device kernels.

        For repeated runs of the SAME statistical configuration (same
        models, priors, observed data shape, distance/acceptor/transition
        types), the jitted generation kernels are identical programs;
        adopting the previous run's ``DeviceContext`` skips re-trace and
        re-compile entirely (used by ``bench.py`` to spend its budget on
        steady-state windows instead of compiles).
        """
        ctx = other._device_ctx
        if ctx is None:
            return
        if not self._device_capable or self.spec is None:
            raise RuntimeError("this run is not device-capable")
        with self.tracer.span("setup", phase="adopt_device_context"):
            self._adopt_device_context_inner(ctx)

    def _adopt_device_context_inner(self, ctx) -> None:
        import copy

        if self.spec.total_size != ctx.spec.total_size or self.K != ctx.K:
            raise ValueError("incompatible configuration for kernel reuse")
        # flatten_host + a cached host copy of ctx.x0: the jnp flatten /
        # np.asarray-on-device-array pair costs two synchronous tunnel
        # round trips (~0.1-0.3 s EACH) that would otherwise tax every
        # adoption in a back-to-back bench
        x0_new = np.asarray(self.spec.flatten_host(self.x_0), np.float32)
        x0_host = getattr(ctx, "_x0_np", None)
        if x0_host is None:
            x0_host = np.asarray(ctx.x0)
            ctx._x0_np = x0_host
        if not np.array_equal(x0_new, x0_host):
            raise ValueError(
                "observed data differs: kernels close over x_0; reuse "
                "requires identical observations"
            )
        # Rebind the context's component references to THIS run's instances
        # (shallow copy shares the compiled-kernel cache): device kernels
        # take all per-generation state (distance weights, pdf norms,
        # epsilon) as ARRAY ARGUMENTS, so compiled programs stay valid, but
        # build_dyn_args reads params off ctx.distance/ctx.acceptor — left
        # pointing at the donor they would leak its fully-adapted state
        # into this run's calibration and generation 0.
        ctx = copy.copy(ctx)
        ctx.distance = self.distance_function
        ctx.acceptor = self.acceptor
        self._device_ctx = ctx

    # ------------------------------------------------------------ internals
    def _build_device_ctx(self) -> DeviceContext | None:
        if not self._device_capable or self.spec is None:
            return None
        # resilience fault site: a (simulated) device-context reset — TPU
        # preemption, tunnel restart — drops the compiled kernels; the
        # self-heal is a rebuild (device state is reconstructible from
        # host state by design: kernels close over x_0 only, and all
        # per-generation state travels as array arguments)
        from ..resilience.faults import InjectedDeviceReset, maybe_fault

        reset_t0 = None
        try:
            maybe_fault("device.context")
        except InjectedDeviceReset:
            reset_t0 = self._clock.now()
            self._device_ctx = None
            logger.warning(
                "device context reset injected: dropping compiled "
                "kernels and rebuilding"
            )
        if self._device_ctx is None:
            with np.errstate(divide="ignore"):
                logits = np.log(self.model_prior_probs)
            self._device_ctx = DeviceContext(
                models=self.models,
                parameter_priors=self.parameter_priors,
                model_prior_logits=logits,
                distance=self.distance_function,
                acceptor=self.acceptor,
                spec=self.spec,
                x_0_flat=np.asarray(self.spec.flatten(self.x_0)),
                transition_classes=[type(tr) for tr in self.transitions],
                mesh=self.mesh,
            )
            self._device_ctx.sync_ledger = self.sync_ledger
        if reset_t0 is not None:
            from ..observability.metrics import DEVICE_RESETS_TOTAL

            self.tracer.record_span(
                "recovery.device_reset", reset_t0, self._clock.now(),
                thread="recovery",
            )
            self.metrics.counter(
                DEVICE_RESETS_TOTAL,
                "device contexts dropped and rebuilt after a reset",
            ).inc()
        return self._device_ctx

    def _model_prior_rvs(self) -> int:
        return int(np.random.choice(self.K, p=self.model_prior_probs))

    def _model_prior_pmf(self, m: int) -> float:
        return float(self.model_prior_probs[m])

    def _generation_spec(self, t: int, *, calibration: bool = False
                         ) -> GenerationSpec:
        gen_key = generation_key(self._root_key, -1 if calibration else t)
        device = self._build_device_ctx()
        mode = dyn = None
        if device is not None:
            if calibration:
                # calibration = the PRIOR kernel at eps = +inf: every valid
                # lane accepts with log-weight 0, which is exactly the
                # all-accepted calibration semantics — and it SHARES the
                # prior kernel's compilation instead of tracing a third
                # program (compile time is the dominant cost of short runs)
                self._ensure_distance_spec(self.distance_function)
                mode, dyn = device.build_dyn_args(t=0, eps_value=np.inf)
            else:
                mode, dyn = device.build_dyn_args(
                    t=t,
                    eps_value=self.eps(t),
                    model_probabilities=self._model_probs if t > 0 else None,
                    transitions=self.transitions if t > 0 else None,
                    model_perturbation_kernel=self.model_perturbation_kernel,
                )
        # standalone closures over the prior-probability array, NOT bound
        # methods: the host closure must stay picklable (cloudpickle for
        # the elastic/SGE/Dask farms) without dragging the whole ABCSMC —
        # History db handles, sampler broker threads, locks — along
        prior_probs = self.model_prior_probs
        K = self.K

        def model_prior_rvs() -> int:
            return int(np.random.choice(K, p=prior_probs))

        def model_prior_pmf(m: int) -> float:
            return float(prior_probs[m])

        host = create_simulate_function(
            0 if calibration else t,
            model_probabilities=self._model_probs,
            model_perturbation_kernel=self.model_perturbation_kernel,
            transitions=self.transitions,
            model_prior_rvs=model_prior_rvs,
            model_prior_pmf=model_prior_pmf,
            parameter_priors=self.parameter_priors,
            models=self.models,
            summary_statistics=self.summary_statistics,
            x_0=self.x_0,
            distance_function=self.distance_function,
            eps=self.eps,
            acceptor=self.acceptor,
            evaluate=not calibration,
            # record_proposal_info (set by Temperature), NOT record_rejected:
            # adaptive-distance runs record rejected sumstats but have no
            # use for an extra per-simulation transition-pdf evaluation
            record_proposal_pd=(
                self.sampler.sample_factory.record_rejected
                and self.sampler.sample_factory.record_proposal_info
            ),
        )
        return GenerationSpec(
            t=t, host_simulate_one=host, device=device, mode=mode, dyn=dyn,
            gen_key=gen_key,
        )

    def _spaces(self):
        return [p.space for p in self.parameter_priors]

    def _sample_to_population(self, sample) -> Population:
        """Normalize a Sample (device arrays or host particle list) to a
        Population."""
        if sample.ms is not None:
            return Population(
                ms=sample.ms, thetas=sample.thetas, weights=sample.weights,
                distances=sample.distances, sumstats=sample.sumstats,
                spaces=self._spaces(), sumstat_spec=self.spec,
                model_names=self.model_names,
                proposal_ids=sample.proposal_ids,
            )
        particles = sample.accepted_particles
        pop = Population.from_particles(
            particles, self._spaces(), self.spec, self.model_names
        )
        pop.proposal_ids = getattr(sample, "accepted_proposal_ids", None)
        return pop

    def _all_records_provider(self, sample) -> Callable:
        """() -> DataFrame['distance','accepted'(,'transition_pd_prev',
        'transition_pd')] over ALL recorded simulations
        (proposal-distributed; used by AcceptanceRateScheme), or None when
        rejected records were not kept.

        The two transition-density columns carry the reference's record
        reweighting: records were drawn under generation t's proposal
        (``transition_pd_prev``, recorded at simulation time) while the
        scheme predicts acceptance under generation t+1's proposal
        (``transition_pd``, computed HERE — the provider runs inside
        eps.update, after the transitions were refit on population t)."""
        def provider():
            import pandas as pd

            if sample.all_distances is not None:
                df = pd.DataFrame({
                    "distance": sample.all_distances,
                    "accepted": sample.all_accepted,
                })
                if getattr(sample, "all_proposal_pds", None) is not None:
                    df["transition_pd_prev"] = sample.all_proposal_pds
                    df["transition_pd"] = self._proposal_pds_now(
                        sample.all_ms, sample.all_thetas
                    )
                return df
            host = getattr(sample, "host_all_records", None)
            if host is not None:
                df = pd.DataFrame({
                    "distance": host.distances, "accepted": host.accepted,
                })
                if (host.proposal_pds is not None
                        and np.isfinite(host.proposal_pds).all()):
                    df["transition_pd_prev"] = host.proposal_pds
                    df["transition_pd"] = self._proposal_pds_now(
                        host.ms, host.parameters
                    )
                return df
            return None

        return provider

    def _proposal_pds_now(self, ms, thetas) -> np.ndarray:
        """Density of recorded (m, theta) under the CURRENT (just-refit)
        proposal — the reference's record ``transition_pd``. ``thetas`` is
        either a list of Parameter dicts or an (n, d) array in the fitted
        column order."""
        import pandas as pd

        ms = np.asarray(ms, np.int64)
        out = np.zeros(len(ms), np.float64)
        for m in np.unique(ms):
            tr = self.transitions[m]
            model_factor = sum(
                p * self.model_perturbation_kernel.pmf(int(m), int(anc))
                for anc, p in self._model_probs.items()
            )
            mask = ms == m
            if model_factor <= 0 or tr.X is None:
                continue
            cols = list(tr.X.columns)
            if isinstance(thetas, np.ndarray):
                df = pd.DataFrame(thetas[mask][:, : len(cols)], columns=cols)
            else:
                idx = np.flatnonzero(mask)
                df = pd.DataFrame([dict(thetas[i]) for i in idx])[cols]
            out[mask] = model_factor * np.asarray(tr.pdf(df), np.float64)
        return out

    def _all_sumstats_provider(self, sample) -> Callable:
        """() -> (n, S) matrix of all recorded sum stats for adaptive comps."""
        def provider():
            if sample.device_records is not None:
                # record ring still on device: adaptive distances reduce it
                # in place; np.asarray(...) fetches for anything else
                return sample.device_records
            if sample.all_sumstats is not None:
                return sample.all_sumstats
            if getattr(sample, "host_all_records", None) is not None:
                return np.stack([
                    np.asarray(self.spec.flatten(s))
                    for s in sample.host_all_records.sum_stats
                ])
            if sample.sumstats is not None:
                return sample.sumstats
            return np.stack([
                np.asarray(self.spec.flatten(p.sum_stat))
                for p in sample.accepted_particles
            ])
        return provider

    def _fit_transitions(self, pop: Population) -> None:
        for m in pop.get_alive_models():
            df, w = pop.get_distribution(m)
            try:
                # a WORK span: host-side proposal refits (per-generation
                # loops, fused chunk-boundary mirrors) show up in the
                # trace next to the sample/persist spans, so refit-vs-
                # sample timing is measurable wherever refits run on the
                # host
                with self.tracer.span("refit", model=int(m), n=len(df)):
                    self.transitions[m].fit(df, w)
            except NotEnoughParticles:
                logger.warning(
                    "not enough particles to fit transition for model %d", m
                )

    def _recompute_distances(self, pop: Population, t: int) -> None:
        """After a distance change, recompute accepted distances for the
        epsilon update (reference semantics: history keeps the old values)."""
        new_d = np.empty(len(pop))
        x0 = self.x_0
        for i in range(len(pop)):
            stats = self.spec.unflatten(pop.sumstats[i])
            new_d[i] = self.distance_function(stats, x0, t)
        pop.distances = new_d

    def _acceptor_config(self, t: int) -> dict:
        return self.acceptor.get_epsilon_config(t)

    # ------------------------------------------------------------------ run
    def run(self, minimum_epsilon: float | None = None,
            max_nr_populations: float = np.inf,
            min_acceptance_rate: float = 0.0,
            max_total_nr_simulations: float = np.inf,
            max_walltime: datetime.timedelta | float | None = None,
            profile_dir: str | None = None) -> History:
        if self.history is None:
            raise RuntimeError("call .new(db, observed) or .load(db, id) first")
        if profile_dir is not None:
            # device-level tracing around the whole run (SURVEY.md §5.1:
            # "add jax.profiler trace hooks"); view with tensorboard/xprof
            import jax.profiler

            jax.profiler.start_trace(profile_dir)
            try:
                return self._run_impl(
                    minimum_epsilon, max_nr_populations, min_acceptance_rate,
                    max_total_nr_simulations, max_walltime,
                )
            finally:
                jax.profiler.stop_trace()
        return self._run_impl(
            minimum_epsilon, max_nr_populations, min_acceptance_rate,
            max_total_nr_simulations, max_walltime,
        )

    def request_graceful_stop(self, signum: int | None = None) -> None:
        """Ask a run owned by ANOTHER thread to stop gracefully.

        Thread-safe and idempotent. The fused dispatch engine checks the
        flag at each chunk boundary and raises :class:`GracefulShutdown`
        there, so the run flushes its async History writer and writes a
        final checkpoint from the newest healthy carry — exactly the
        SIGTERM semantics, without a signal (handlers cannot be
        installed off the main thread). The serving layer's drain path
        calls this on every live tenant. No-op after the run finished.
        """
        import signal as _signal

        self._stop_signum = int(signum if signum is not None
                                else _signal.SIGTERM)

    def drain_join(self) -> None:
        """Block until a ``drain_async`` background drain (the fused
        loop's final in-flight fetches + persist) has finished, and
        re-raise any error it hit. No-op when no drain is running."""
        th = self._drain_thread
        if th is not None:
            th.join()
            self._drain_thread = None
        if self._drain_error is not None:
            err, self._drain_error = self._drain_error, None
            raise err

    # ----------------------------------------------- mid-chunk checkpointing
    def _checkpoint_fingerprint(self) -> str:
        """Config identity a checkpoint must match to be adopted: the
        carry pytree's shape is a function of these (models/priors fix
        the dims, the seed fixes the RNG stream the carry position is
        meaningful for)."""
        return json.dumps({
            "models": self.model_names,
            "K": self.K,
            "seed": int(self.seed),
            "fused_generations": int(self.fused_generations),
        }, sort_keys=True)

    def _maybe_adopt_checkpoint(self, t0: int) -> int:
        """Adopt a mid-chunk checkpoint if one matches this run.

        Returns the (possibly moved) resume generation. On adoption the
        History is pruned back to the checkpoint's generation (rows past
        it were persisted between the save and the kill; the checkpoint
        is canonical), the root PRNG key is restored from the saved key
        data, and the decoded carry is staged for the fused loop."""
        self._resume_carry = None
        self.resumed_from_checkpoint_t = None
        if self._checkpoint is None or t0 <= 0 \
                or not self._fused_chunk_capable():
            return t0
        from ..resilience.checkpoint import CheckpointCorruptError

        try:
            ck = self._checkpoint.load()
        except CheckpointCorruptError as exc:
            # integrity failure (truncation, bit flip, schema mismatch):
            # typed + loud, then degrade to the History epsilon-trail
            # replay path — corruption costs durability, not correctness
            logger.warning(
                "checkpoint failed integrity verification (%s); "
                "resuming at generation granularity from the History",
                exc,
            )
            ck = None
        if ck is None or ck.get("kind") != "fused_carry":
            return t0
        if ck.get("abc_id") != int(self.history.id) \
                or ck.get("fingerprint") != self._checkpoint_fingerprint():
            logger.warning(
                "ignoring checkpoint %s: it belongs to a different "
                "run/config", self._checkpoint.path,
            )
            return t0
        t_ck = int(ck["t"])
        if t_ck < 1 or t_ck > t0:
            # flush-before-save guarantees the db is at-or-ahead of the
            # checkpoint; a checkpoint ahead of the db means the file
            # was paired with a different db copy — don't trust it
            logger.warning(
                "ignoring checkpoint %s: t=%d inconsistent with the "
                "History (resumable t=%d)", self._checkpoint.path,
                t_ck, t0,
            )
            return t0
        import jax

        if t_ck < t0:
            n = self.history.prune_from(t_ck)
            logger.info(
                "pruned %d generation(s) persisted past the checkpoint "
                "(t >= %d): the checkpoint carry is canonical", n, t_ck,
            )
        self._root_key = jax.random.wrap_key_data(
            np.asarray(ck["root_key_data"], np.uint32)
        )
        self._resume_carry = ck["carry"]
        self.resumed_from_checkpoint_t = t_ck
        logger.info(
            "resuming fused run MID-CHUNK from checkpoint %s at t=%d "
            "(chunk %s) — carry restored bit-exact, no host refit replay",
            self._checkpoint.path, t_ck, ck.get("chunk_index"),
        )
        return t_ck

    def _validate_resume_carry(self, decoded, build_carry, t):
        """Structure/shape/dtype-check the decoded carry against a
        freshly host-built one; returns the decoded carry (as-is: numpy
        leaves feed the kernel directly) or None to fall back."""
        import jax

        try:
            ref = build_carry(t)
        except Exception:
            logger.exception(
                "could not build a reference carry to validate the "
                "checkpoint against; falling back to host-built state"
            )
            return None
        ref_leaves, ref_td = jax.tree.flatten(ref)
        dec_leaves, dec_td = jax.tree.flatten(decoded)
        ok = ref_td == dec_td and len(ref_leaves) == len(dec_leaves) \
            and all(
                np.asarray(a).shape == np.asarray(b).shape
                and np.asarray(a).dtype == np.asarray(b).dtype
                for a, b in zip(ref_leaves, dec_leaves)
            )
        if not ok:
            logger.warning(
                "checkpoint carry does not match this config's carry "
                "structure; falling back to host-built state"
            )
            return None
        return decoded

    def _save_fused_checkpoint(self, carry_ref, t_next: int,
                               sims_total: int, chunk_index: int) -> None:
        """Flush History, fetch the chunk's final device carry, persist
        atomically. The flush ordering is the no-gap invariant: the db
        always holds every generation below the checkpoint's t.

        Multi-process meshes: only the PRIMARY writes the file — the
        carry is replicated, so one copy is enough, and N lock-step
        processes sharing a checkpoint path must not race the atomic
        rename. Any process count × width can adopt the primary's file
        on resume (``dist.resume_db`` rebuilds the matching History)."""
        import jax

        from ..parallel import distributed as dist

        self.history.flush()
        if not dist.is_primary():
            return
        host_carry = jax.device_get(carry_ref)
        self.sync_ledger.record("checkpoint_fetch")
        self._checkpoint.save({
            "kind": "fused_carry",
            "abc_id": int(self.history.id),
            "fingerprint": self._checkpoint_fingerprint(),
            "t": int(t_next),
            "sims_total": int(sims_total),
            "chunk_index": int(chunk_index),
            "root_key_data": np.asarray(
                jax.random.key_data(self._root_key)),
            "carry": host_carry,
        })

    # ------------------------------------------------- health recovery
    def _health_recovery_carry(self, action: str, t_fail: int,
                               good_carry, rebuild_carry):
        """The carry to redispatch from after a health failure at
        ``t_fail``; returns ``(carry, source)``.

        ``rollback`` prefers durable, known-clean state: the PR 5
        checkpoint when one covers exactly ``t_fail`` (validated like a
        resume), else the retained last-healthy chunk-boundary carry
        (same state, still on device), else a host rebuild from the
        mirrored fit of the last healthy population. ``refit`` FORCES
        the host rebuild — a PSD/Cholesky failure means the in-kernel
        factors are not trusted, so a fresh host factorization replaces
        them. ``widen`` is the host rebuild from proposals refit with
        the bandwidth inflated by ``health_widen_factor`` (importance
        weights are always computed against the proposal actually
        sampled from, so widening is statistically exact — it trades
        acceptance rate for tail coverage)."""
        if action == "widen":
            from ..observability.metrics import PROPOSAL_WIDENINGS_TOTAL

            self._widen_transitions(self.health_widen_factor)
            self.metrics.counter(
                PROPOSAL_WIDENINGS_TOTAL,
                "proposal-bandwidth widenings on ESS/acceptance collapse",
            ).inc()
            return rebuild_carry(t_fail), "host_rebuild_widened"
        if action == "refit":
            return rebuild_carry(t_fail), "host_rebuild"
        if self._checkpoint is not None:
            from ..resilience.checkpoint import CheckpointCorruptError

            try:
                ck = self._checkpoint.load()
            except CheckpointCorruptError as exc:
                logger.warning(
                    "rollback checkpoint failed integrity (%s); using "
                    "in-memory state", exc)
                ck = None
            if (ck is not None and ck.get("kind") == "fused_carry"
                    and int(ck.get("t", -1)) == int(t_fail)
                    and ck.get("abc_id") == int(self.history.id)
                    and ck.get("fingerprint")
                    == self._checkpoint_fingerprint()):
                decoded = self._validate_resume_carry(
                    ck["carry"], rebuild_carry, t_fail)
                if decoded is not None:
                    return decoded, "checkpoint"
        g_t, g_carry = good_carry
        if g_t == t_fail and g_carry is not None:
            return g_carry, "last_good_carry"
        return rebuild_carry(t_fail), "host_rebuild"

    def _widen_transitions(self, factor: float) -> None:
        """Refit every fitted host transition with its bandwidth scaling
        inflated by ``factor`` (restored afterwards, so only THIS
        rebuild's carry params are widened — the next in-kernel refit
        returns to the configured bandwidth)."""
        for m, tr in enumerate(self.transitions):
            if tr.X is None or not isinstance(
                    getattr(tr, "scaling", None), float):
                continue
            orig = tr.scaling
            tr.scaling = orig * float(factor)
            try:
                with self.tracer.span("refit", model=int(m),
                                      widened=float(factor)):
                    tr.fit(tr.X, tr.w)
            finally:
                tr.scaling = orig

    def _save_final_checkpoint(self) -> None:
        """Graceful-shutdown durability: persist the newest healthy
        chunk-boundary carry so an external SIGTERM/SIGINT is exactly as
        recoverable as an injected orchestrator kill. Best-effort — a
        failed save degrades durability, never the shutdown itself."""
        state = getattr(self, "_final_ck_state", None)
        if self._checkpoint is None or state is None:
            return
        carry_ref, t_next, sims, chunk_index = state
        try:
            self._save_fused_checkpoint(carry_ref, t_next, sims,
                                        chunk_index)
            logger.info(
                "graceful shutdown: final checkpoint written at t=%d",
                t_next,
            )
        except Exception:
            logger.exception("graceful-shutdown checkpoint save failed")

    def _run_impl(self, minimum_epsilon, max_nr_populations,
                  min_acceptance_rate, max_total_nr_simulations,
                  max_walltime) -> History:
        # a stop requested against a PREVIOUS run of this object must
        # not abort the new one (requeued tenants build fresh objects,
        # but back-to-back run() calls on one object are supported)
        self._stop_signum = None
        with self.tracer.span("run", db=getattr(self.history, "db", None)):
            with self._graceful_signals():
                return self._run_inner(
                    minimum_epsilon, max_nr_populations,
                    min_acceptance_rate, max_total_nr_simulations,
                    max_walltime,
                )

    @contextlib.contextmanager
    def _graceful_signals(self):
        """Convert SIGTERM/SIGINT into :class:`GracefulShutdown` for the
        duration of a run, so an external kill flushes the History
        writer and writes a final checkpoint (the fused loop's
        BaseException path) instead of dying with queued generations and
        a stale checkpoint. Main-thread only (signal handlers cannot be
        installed elsewhere); previous handlers are restored on exit."""
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            yield
            return

        def _handler(signum, frame):
            raise GracefulShutdown(signum)

        prev = {}
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev[sig] = signal.signal(sig, _handler)
        except (ValueError, OSError) as exc:
            # embedded interpreters may refuse; run unprotected
            logger.debug("not installing signal handlers: %r", exc)
        try:
            yield
        finally:
            for sig, h in prev.items():
                signal.signal(sig, h)

    def _run_inner(self, minimum_epsilon, max_nr_populations,
                   min_acceptance_rate, max_total_nr_simulations,
                   max_walltime) -> History:
        # a still-running background drain from a previous drain_async run
        # on this object must finish (and surface its errors) first
        self.drain_join()
        if minimum_epsilon is None:
            # reference default: temperature schedules stop at T = 1 (exact
            # posterior); distance thresholds run to the other criteria
            from ..epsilon import Temperature

            minimum_epsilon = (
                1.0 if isinstance(self.eps, Temperature) else 0.0
            )
        self.minimum_epsilon = minimum_epsilon
        start_walltime = self._clock.now()
        if isinstance(max_walltime, datetime.timedelta):
            max_walltime = max_walltime.total_seconds()
        # samplers carry span/metric instrumentation of their own (broker
        # round trips, device dispatch/collect) — share this run's sinks
        # BEFORE calibration, which already samples through them
        self.sampler.tracer = self.tracer
        self.sampler.metrics = self.metrics
        self.sampler.sync_ledger = self.sync_ledger
        if self._device_ctx is not None:
            # an adopted/pre-built context records into THIS run's ledger
            self._device_ctx.sync_ledger = self.sync_ledger
        # learned-sumstat predictors that train on-device (MLP) fetch
        # their fitted params back host-side; that round trip belongs to
        # THIS run's sync accounting, not a lint-baseline exemption
        _ss = getattr(self.distance_function, "sumstat", None)
        _pred = getattr(_ss, "predictor", None)
        if _pred is not None:
            _pred.sync_ledger = self.sync_ledger
            for _sub in getattr(_pred, "predictors", ()):
                _sub.sync_ledger = self.sync_ledger
        # fresh health supervision per run: the trail and the rollback
        # budget are run state (resilience/health.py)
        from ..resilience.health import RunSupervisor

        self.health_supervisor = RunSupervisor(
            max_rollbacks=self.max_health_rollbacks,
            widen_factor=self.health_widen_factor,
            clock=self._clock, tracer=self.tracer, metrics=self.metrics,
        )

        t0 = self.history.max_t + 1
        # checkpoint adoption gates on _fused_chunk_capable, which for
        # horizon-needing temperature schemes (ExpDecayFixedIter, ...)
        # reads the epsilon's population horizon — but eps.initialize
        # only runs AFTER adoption. Pre-seed the horizon from this run's
        # argument so a stochastic run's checkpoint is not silently
        # rejected (initialize re-sets the same value later).
        if getattr(self.eps, "_max_nr_populations", False) is None \
                and np.isfinite(max_nr_populations):
            self.eps._max_nr_populations = int(max_nr_populations)
        # mid-chunk checkpoint adoption (resilience subsystem): a killed
        # orchestrator resumes from the exact device carry it
        # checkpointed — possibly pruning History rows persisted past it
        t0 = self._maybe_adopt_checkpoint(t0)
        if t0 == 0:
            # the fused loop may own calibration (in-kernel, inside the
            # first chunk) — then the host round trip is skipped and the
            # epsilon/weights mirrors arrive with the first chunk's fetch
            skip_cal = (
                self._fused_chunk_capable()
                and getattr(self.distance_function, "sumstat", None) is None
                and self._fused_calibration_cfg() is not None
            )
            with self.tracer.span("calibration", in_kernel=bool(skip_cal)):
                self._initialize_components(max_nr_populations,
                                            skip_calibration=skip_cal)
        else:
            self._restore_state(t0 - 1, max_nr_populations)

        self.distance_function.configure_sampler(self.sampler)
        self.eps.configure_sampler(self.sampler)

        if self._fused_chunk_capable():
            return self._loop_fused(
                t0, minimum_epsilon, max_nr_populations,
                min_acceptance_rate, max_total_nr_simulations,
                max_walltime, start_walltime,
            )

        if (self.pipeline
                and getattr(self.sampler, "supports_pipelining", False)
                and getattr(self.sampler, "fused", False)
                and self._device_capable):
            return self._loop_pipelined(
                t0, minimum_epsilon, max_nr_populations,
                min_acceptance_rate, max_total_nr_simulations,
                max_walltime, start_walltime,
            )

        t = t0
        sims_total = self.history.total_nr_simulations
        distance_changed_at_t = getattr(
            self, "_resumed_distance_changed", False)
        look_ahead = self._look_ahead_capable()
        if look_ahead:
            # mid-generation look-ahead (reference redis look_ahead /
            # look_ahead_delay_evaluation): the sampler calls back for a
            # PRELIMINARY t+1 closure once enough of generation t is in
            self.sampler.lookahead_builder = self._build_lookahead_payload
        elif hasattr(self.sampler, "cancel_look_ahead"):
            # a previous run on this sampler may have left a pre-published
            # proposal / stale acceptance hook; this run's config is not
            # look-ahead-capable, so it must not adopt them
            self.sampler.cancel_look_ahead()
        try:
            self._serial_generation_loop(
                t, look_ahead, distance_changed_at_t, sims_total,
                minimum_epsilon, max_nr_populations, min_acceptance_rate,
                max_total_nr_simulations, max_walltime, start_walltime,
            )
        finally:
            if look_ahead:
                # retire any pre-published next generation — ALSO on an
                # exception mid-loop (generation_timeout, persistence
                # failure): collect-only look-ahead generations have no
                # self-completion, so workers would otherwise simulate the
                # stale proposal until the broker dies
                self.sampler.cancel_look_ahead()
        self.history.done()
        return self.history

    def _serial_generation_loop(self, t, look_ahead, distance_changed_at_t,
                                sims_total, minimum_epsilon,
                                max_nr_populations, min_acceptance_rate,
                                max_total_nr_simulations, max_walltime,
                                start_walltime) -> None:
        while True:
            current_eps = self.eps(t)
            if look_ahead:
                # delayed acceptance for an adopted look-ahead generation.
                # Generation-dependent distances (AdaptivePNormDistance,
                # t-scheduled weights) are RE-EVALUATED here from the
                # shipped sum stats — the preliminary worker recorded a
                # distance under the stale weights; the generation-t
                # weights exist now (reference delayed evaluation). The
                # recomputed distance sticks on the particle, so records
                # and the persisted population carry the final values.
                if getattr(self, "_lookahead_stochastic", False):
                    # fixed-schedule noisy path: the exact stochastic
                    # acceptance rule (temperature from the fixed ladder,
                    # analytic pdf norm) applied host-side; above-norm
                    # excess folds into the importance weight
                    self.sampler.lookahead_accept = (
                        self.acceptor.delayed_accept_fn(
                            t, float(current_eps))
                    )
                elif getattr(self, "_lookahead_recompute", False):
                    def _accept(p, _e=float(current_eps), _t=t):
                        p.distance = float(self.distance_function(
                            p.sum_stat, self.x_0, _t, p.parameter
                        ))
                        return p.distance <= _e
                    self.sampler.lookahead_accept = _accept
                else:
                    self.sampler.lookahead_accept = (
                        lambda p, _e=float(current_eps): p.distance <= _e
                    )
            if hasattr(self.acceptor, "note_epsilon"):
                # complete-history acceptance needs the threshold trail
                self.acceptor.note_epsilon(t, current_eps,
                                           distance_changed_at_t)

            n_t = self.population_strategy(t)
            max_eval = (
                n_t / min_acceptance_rate
                if min_acceptance_rate > 0 else np.inf
            )
            logger.info("t: %d, eps: %.8g", t, current_eps)
            clk = self._clock.now
            with self.tracer.span("generation", t=int(t), n=int(n_t),
                                  eps=float(current_eps)) as g_span:
                t_gen0 = clk()
                with self.tracer.span("sample", t=int(t)):
                    gen_spec = self._generation_spec(t)
                    sample = self.sampler.sample_until_n_accepted(
                        n_t, gen_spec, t, max_eval=max_eval
                    )
                sample_s = clk() - t_gen0
                n_acc = sample.n_accepted if sample.ms is not None else len(
                    sample.accepted_particles
                )
                if n_acc < n_t:
                    logger.info(
                        "stopping: only %d/%d accepted within budget",
                        n_acc, n_t,
                    )
                    break
                pop = self._sample_to_population(sample)
                nr_evals = self.sampler.nr_evaluations_
                sims_total += nr_evals
                acceptance_rate = n_t / nr_evals
                t_persist0 = clk()
                with self.tracer.span("persist", t=int(t)):
                    self.history.append_population(
                        t, current_eps, pop, nr_evals, self.model_names,
                        telemetry={"sample_s": round(sample_s, 4),
                                   "n_evaluations": int(nr_evals)},
                    )
                persist_s = clk() - t_persist0
                logger.info(
                    "acceptance rate: %.5f (%d evaluations)",
                    acceptance_rate, nr_evals,
                )
                t_adapt0 = clk()
                with self.tracer.span("adapt", t=int(t)):
                    distance_changed_at_t = self._adapt_components(
                        t, sample, pop, current_eps, acceptance_rate
                    )
                self.history.update_telemetry(t, {
                    "adapt_s": round(clk() - t_adapt0, 4),
                    "persist_s": round(persist_s, 4),
                    "acceptance_rate": round(acceptance_rate, 6),
                    # "the distance changed AFTER generation t" — the resume
                    # replay reads this to restart the epsilon trail exactly
                    # where the live run did
                    "distance_changed": bool(distance_changed_at_t),
                })
                g_span.set(n_accepted=int(n_acc),
                           n_evaluations=int(nr_evals),
                           acceptance_rate=round(acceptance_rate, 6))

            if self._check_stop(t, current_eps, minimum_epsilon,
                                max_nr_populations, acceptance_rate,
                                min_acceptance_rate, sims_total,
                                max_total_nr_simulations, max_walltime,
                                start_walltime):
                break
            t += 1

    def _adapt_components(self, t, sample, pop, current_eps,
                          acceptance_rate) -> bool:
        """Central adaptation after generation t (reference §3.2 ADAPTATION
        block) — shared by the serial and pipelined loops. Returns True if
        the distance changed (pop.distances is then recomputed in place;
        persist BEFORE calling this, or pin a copy, to keep the reference's
        history-keeps-old-distances semantics)."""
        self._adapt_proposal(pop)
        return self._adapt_strategies(t, sample, pop, current_eps,
                                      acceptance_rate)

    def _adapt_proposal(self, pop) -> None:
        """The proposal-defining part of adaptation (model probabilities +
        transition refits) — split out so the pipelined loop can dispatch a
        SPECULATIVE t+1 proposal round before the slow strategy updates."""
        self._model_probs = {
            m: float(pop.model_probabilities_array()[m])
            for m in pop.get_alive_models()
        }
        self._fit_transitions(pop)

    def _adapt_strategies(self, t, sample, pop, current_eps,
                          acceptance_rate) -> bool:
        """Distance / acceptor / epsilon / population-size updates (the
        slow, proposal-independent part of adaptation)."""
        all_ss = self._all_sumstats_provider(sample)
        changed = _call_filtered(
            self.distance_function.update,
            t=t + 1, get_all_sum_stats=all_ss, population=pop,
        )
        if changed:
            self._recompute_distances(pop, t + 1)
        get_wd = lambda: pop.get_weighted_distances()  # noqa: E731
        _call_filtered(
            self.acceptor.update,
            t=t + 1, get_weighted_distances=get_wd,
            prev_temp=current_eps, acceptance_rate=acceptance_rate,
        )
        _call_filtered(
            self.eps.update,
            t=t + 1, get_weighted_distances=get_wd,
            get_all_records=self._all_records_provider(sample),
            acceptance_rate=acceptance_rate,
            acceptor_config=self._acceptor_config(t + 1),
        )
        self.population_strategy.update(
            [self.transitions[m] for m in pop.get_alive_models()],
            np.asarray(
                [self._model_probs[m] for m in pop.get_alive_models()]
            ),
            t,
        )
        return bool(changed)

    def _check_stop(self, t, current_eps, minimum_epsilon,
                    max_nr_populations, acceptance_rate,
                    min_acceptance_rate, sims_total,
                    max_total_nr_simulations, max_walltime,
                    start_walltime) -> bool:
        """Stopping rules after generation t (reference §3.2) — shared by
        the serial and pipelined loops."""
        if current_eps <= minimum_epsilon:
            logger.info("stopping: eps=%.8g <= minimum_epsilon", current_eps)
            return True
        if t + 1 >= max_nr_populations:
            logger.info("stopping: max_nr_populations reached")
            return True
        if acceptance_rate < min_acceptance_rate:
            logger.info("stopping: acceptance rate below minimum")
            return True
        if sims_total >= max_total_nr_simulations:
            logger.info("stopping: max_total_nr_simulations reached")
            return True
        if (max_walltime is not None
                and self._clock.now() - start_walltime > max_walltime):
            logger.info("stopping: max_walltime reached")
            return True
        if (self.stop_if_only_single_model_alive
                and len(self._model_probs) == 1 and self.K > 1):
            logger.info("stopping: single model alive")
            return True
        return False

    @staticmethod
    def _device_memory_telemetry() -> dict:
        """Device memory highwater, when the runtime exposes it (real local
        TPU/GPU runtimes do; CPU and tunneled devices yield {})."""
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
        except Exception:
            return {}
        if not stats:
            return {}
        out = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                out[f"device_{key}"] = int(stats[key])
        return out

    # -------------------------------------------------- fused multi-gen loop
    def _fused_chunk_capable(self) -> bool:
        """True when whole generations can be chained ON DEVICE: every
        between-generation adaptation (transition refit, distance
        reweighting, epsilon update) has a traceable twin. See
        DeviceContext.multigen_kernel."""
        from ..distance.scale import SCALE_FUNCTIONS
        from ..epsilon import ConstantEpsilon, ListEpsilon, QuantileEpsilon
        from ..transition.util import (
            scott_rule_of_thumb,
            silverman_rule_of_thumb,
        )

        if self.fused_generations <= 1 or not self._device_capable:
            return False
        if not isinstance(self.sampler, BatchedSampler) or not getattr(
            self.sampler, "fused", False
        ):
            return False
        if not isinstance(self.population_strategy,
                          (ConstantPopulationSize, ListPopulationSize)) \
                and not self._fused_adaptive_n_capable():
            return False
        if type(self.acceptor) is StochasticAcceptor:
            return self._fused_stochastic_capable()
        if type(self.acceptor) is not UniformAcceptor:
            return False
        if self.acceptor.use_complete_history \
                and self._distance_may_change():
            # a distance whose space can change between generations
            # restarts the epsilon trail via
            # note_epsilon(distance_changed=True); the host loop keeps
            # those subtle semantics
            return False
        if type(self.model_perturbation_kernel) is not ModelPerturbationKernel:
            # the kernel only honors the stock static transition matrix;
            # custom jump kernels fall back to the per-generation loop
            return False
        tr = self.transitions[0]
        if type(tr) is LocalTransition:
            # local-covariance KDE refits on device (blocked pairwise +
            # top_k) with the host _effective_k rule applied IN-KERNEL to
            # each model's dynamic accepted count — K>1 rides too. The
            # static top_k bound comes from the schedule's (or adaptive
            # cap's) max n.
            if not isinstance(self.population_strategy,
                              (ConstantPopulationSize, ListPopulationSize)) \
                    and not self._fused_adaptive_n_capable():
                return False
            for other in self.transitions:
                # per-model refits share ONE traced device_fit config
                if (type(other) is not LocalTransition
                        or other.scaling != tr.scaling
                        or other.k != tr.k
                        or other.k_fraction != tr.k_fraction
                        or other.k_max != tr.k_max
                        or other.selection != tr.selection):
                    return False
        elif type(tr) is MultivariateNormalTransition:
            for other in self.transitions:
                # per-model refits share ONE traced device_fit configuration
                if (type(other) is not MultivariateNormalTransition
                        or other.scaling != tr.scaling
                        or other.bandwidth_selector
                        is not tr.bandwidth_selector):
                    return False
            if tr.bandwidth_selector not in (scott_rule_of_thumb,
                                             silverman_rule_of_thumb):
                return False
        elif type(tr) is GridSearchCV:
            # in-kernel cross-validated bandwidth selection over the MVN
            # scaling grid (the reference's canonical GridSearchCV use).
            # K>1: per-model masked weights restrict each fit/score to one
            # model's rows; fold membership is row-indexed over the whole
            # population (declared deviation: the host shuffles folds
            # within each model's own rows — same statistics, different
            # fold pattern)
            if isinstance(self.population_strategy, ConstantPopulationSize):
                pass  # host-static fold assignment over the constant n
            elif isinstance(self.population_strategy, ListPopulationSize):
                # per-generation fold tables ride the chunk as a (G,
                # n_cap) argument; every scheduled generation must keep
                # at least cv rows so fold semantics match the host
                if min(self.population_strategy.values) < tr.cv:
                    return False
            else:
                return False
            if self.K != 1:
                for other in self.transitions:
                    if (type(other) is not GridSearchCV
                            or other.param_grid != tr.param_grid
                            or other.cv != tr.cv
                            or type(other.estimator)
                            is not MultivariateNormalTransition):
                        return False
            if set(tr.param_grid) != {"scaling"} \
                    or not tr.param_grid["scaling"] \
                    or any(s <= 0 for s in tr.param_grid["scaling"]):
                # a non-positive candidate would NaN the in-kernel scores
                # (log 0, maha/0) and argmax would silently pick it; the
                # host path survives such grids, so it keeps them
                return False
            if tr.cv < 2 or tr.cv > self.population_strategy(0):
                # degenerate fold counts behave differently on host
                # (empty train sets -> first-entry fallback) than the
                # device rule would; keep host semantics
                return False
            est = tr.estimator
            if type(est) is not MultivariateNormalTransition:
                return False
            if est.bandwidth_selector not in (scott_rule_of_thumb,
                                              silverman_rule_of_thumb):
                return False
        else:
            return False
        if not (isinstance(self.eps, QuantileEpsilon)
                or type(self.eps) in (ListEpsilon, ConstantEpsilon)):
            return False
        if np.isfinite(self.max_nr_recorded_particles):
            return False  # capped retention semantics need the host path
        d = self.distance_function
        if isinstance(d, AdaptivePNormDistance):
            if d.sumstat is not None and not d.sumstat.is_device_compatible():
                return False
            if d.adaptive and d.device_scale_impl() is None:
                return False
            if d.scale_log_file:
                return False  # per-generation host logging: stay unfused
        elif type(d) is PNormDistance:
            if d.sumstat is not None and not d.sumstat.is_device_compatible():
                return False
            # per-generation user weight schedules ride the chunk as a
            # host-resolved (G, S) device_params table indexed by the
            # in-scan generation (weight_sched mode)
        elif type(d) in (AggregatedDistance, AdaptiveAggregatedDistance):
            # weighted sum of plain p-norm sub-distances. Non-adaptive:
            # params are chunk-constant, or a per-generation schedule
            # (top-level and/or sub-weights) rides as a stacked
            # device_params table. Adaptive: the per-generation 1/scale
            # reweighting runs IN-KERNEL over the record ring
            # (device_record_reduce/device_weight_update twins)
            if type(d) is AdaptiveAggregatedDistance:
                if not d.adaptive or d.log_file \
                        or d.device_scale_impl() is None:
                    return False
            for sub in d.distances:
                if (type(sub) is not PNormDistance
                        or sub.sumstat is not None):
                    return False
                if type(d) is AdaptiveAggregatedDistance \
                        and any(k >= 0 for k in sub.weights):
                    # adaptive top-level reweighting owns the carry; a
                    # sub-schedule on top would need both mechanisms
                    return False
        else:
            return False
        return True

    def _sharded_n(self) -> int | None:
        """Resolve the sharded fused path's shard count, or None.

        Mesh present without an explicit count: the shard count IS the
        mesh width — including a MULTI-PROCESS global mesh (round 18):
        the lane-key reduction is a pure function of ``n_shards``, so a
        P-process mesh runs the same shard-local segment sweeps with the
        scalar-column collectives spanning DCN, bit-identical to the
        virtual-shard reference. Mesh present WITH ``sharded=<int>``:
        the mesh width only has to DIVIDE the shard count — each device
        runs its block of virtual shards (the hybrid execution), so an
        n-shard checkpoint resumes bit-identical on any divisor-width
        sub-mesh (mesh-aware serving re-places tenants on whatever
        width is free). No mesh but ``sharded=<int>``: that many
        VIRTUAL shards vmapped on one device — the same reduction, used
        as the parity reference. ``sharded=True`` makes capability
        failures loud."""
        if self.sharded in (False, 0):
            return None
        requested = self.sharded is not None
        n_req = (int(self.sharded)
                 if isinstance(self.sharded, int)
                 and not isinstance(self.sharded, bool) else None)
        if self.mesh is not None:
            devs = list(self.mesh.devices.flat)
            n_proc = len({d.process_index for d in devs})
            if n_proc > 1:
                reason = self._multihost_incapable_reason(devs, n_proc)
                if reason is not None:
                    if requested:
                        raise ValueError(
                            f"sharded fused sampling unavailable: {reason}"
                        )
                    logger.info("sharded fused path off: %s", reason)
                    self._note_capability_fallback("sharded", reason)
                    return None
            w = len(devs)
            if n_req is None:
                n = w
            elif n_req < w or n_req % w:
                raise ValueError(
                    f"sharded={n_req} cannot run on a {w}-device mesh: "
                    f"the mesh width must divide the shard count (each "
                    f"device then runs n_shards/width virtual shards)"
                )
            else:
                n = n_req
        else:
            n = n_req
        if n is None or n <= 1:
            return None
        reason = self._sharded_incapable_reason(n)
        if reason is not None:
            if requested:
                raise ValueError(
                    f"sharded fused sampling unavailable: {reason}"
                )
            logger.info("sharded fused path off: %s", reason)
            self._note_capability_fallback("sharded", reason)
            return None
        return n

    def _note_capability_fallback(self, gate: str, reason: str) -> None:
        """Record a capability-gate fallback: a fast path the config
        implied (mesh present, segmented models built, ...) that the
        run could not take. The reason string lands in
        ``self._capability_fallbacks`` (History telemetry + dispatch
        snapshot) and bumps the fallback counters on both registries —
        per-gate via the name-suffix convention (the registry has no
        label support)."""
        entry = {"gate": str(gate), "reason": str(reason)}
        if entry in self._capability_fallbacks:
            return  # one fallback per (gate, reason) per run
        self._capability_fallbacks.append(entry)
        from ..observability import global_metrics
        from ..observability.metrics import (
            CAPABILITY_FALLBACKS_TOTAL,
            capability_fallback_metric,
        )

        for reg in (self.metrics, global_metrics()):
            reg.counter(
                CAPABILITY_FALLBACKS_TOTAL,
                "requested fast paths that fell back to a slower "
                "serving path (per-gate split: _<gate> suffix)",
            ).inc()
            reg.counter(
                capability_fallback_metric(gate),
                f"capability fallbacks at the {gate} gate",
            ).inc()

    def _fallbacks_telemetry(self) -> dict:
        """The run's fallback list for the FIRST persisted generation's
        History telemetry (reported once — later generations carry no
        duplicate)."""
        if self._capability_fallbacks and not self._fallbacks_reported:
            self._fallbacks_reported = True
            return {"capability_fallbacks":
                    [dict(f) for f in self._capability_fallbacks]}
        return {}

    def _sumstat_telemetry(self) -> dict:
        """Per-run learned-sumstat block for the FIRST persisted
        generation's History telemetry (reported once, like the
        capability fallbacks): serving mode (device-fit plan vs legacy
        host refit), transform kind, and the raw-S -> learned-C' wire
        dimensions of the packed fetch."""
        ss = getattr(self.distance_function, "sumstat", None)
        if ss is None or self._sumstat_reported:
            return {}
        self._sumstat_reported = True
        plan = self._sumstat_device_plan
        block: dict = {
            "mode": "device" if plan is not None else "host",
            "transform": type(ss).__name__,
            "dim_raw": (int(self.spec.total_size)
                        if self.spec is not None else None),
        }
        if plan is not None:
            block["kind"] = plan["kind"]
            block["dim_reduced"] = int(plan["out_dim"])
            block["need"] = int(plan["need"])
        elif getattr(ss, "_out_dim", None):
            block["dim_reduced"] = int(ss._out_dim)
        return {"sumstat": block}

    def _sharded_incapable_reason(self, n_shards: int) -> str | None:
        """Why the sharded multigen kernel cannot serve this config (None
        = capable). Round 16 (ISSUE 12) shrank this gate to the
        genuinely-impossible cases: adaptive distances (pass-decomposable
        scale functions), stochastic acceptors + temperature schemes,
        per-generation weight/population schedules and in-kernel adaptive
        population sizes all SHARD now. Every remaining reason names the
        fallback path that serves the config and the change that would
        shard it — the strings are part of the contract
        (tests/test_sharded.py asserts each is reachable)."""
        if not self._fused_chunk_capable():
            return ("config cannot run fused chunks, so there is no "
                    "multigen kernel to shard; the per-generation host "
                    "loops serve it (see _fused_chunk_capable for the "
                    "fused feature set)")
        d = self.distance_function
        if getattr(d, "sumstat", None) is not None:
            # ISSUE 20: learned statistics SHARD when the boundary fit
            # has a LINEAR in-kernel twin (the ridge solve runs on the
            # gathered reservoir rows the cadence refit already pays
            # for); what stays gated keeps an actionable reason
            from ..sumstat.device import device_fit_plan

            if getattr(d, "adaptive", False):
                return ("adaptive scale refits compose with learned "
                        "summary statistics on the UNSHARDED device-fit "
                        "path only (the scale must refit AFTER the "
                        "transform, in the new feature space — a "
                        "replicated post-collective stage the sharded "
                        "kernel does not run); the replicated GSPMD "
                        "path serves this config")
            plan, plan_reason = device_fit_plan(
                d,
                total_size=(self.spec.total_size
                            if self.spec is not None else 0),
                d_max=1, sharded_n=n_shards,
            )
            if plan is None:
                return (f"learned summary statistics without a device-"
                        f"fit plan refit HOST-side at chunk boundaries "
                        f"({plan_reason}); the replicated GSPMD path "
                        f"serves this config")
        if ((isinstance(d, AdaptivePNormDistance) and d.adaptive)
                or (type(d) is AdaptiveAggregatedDistance and d.adaptive)) \
                and not d.sharded_scale_capable():
            scale_name = getattr(
                getattr(d, "scale_function", None), "__name__",
                repr(getattr(d, "scale_function", None)))
            from ..ops.scale_reduce import SHARDED_SCALE_NAMES

            return (f"adaptive scale function {scale_name!r} has no "
                    f"moment-decomposable sharded reduction (median-"
                    f"based and custom scales need the full cross-shard "
                    f"record ring); the replicated GSPMD path serves "
                    f"this config — switch to a decomposable "
                    f"scale_function "
                    f"({', '.join(sorted(SHARDED_SCALE_NAMES))}) to "
                    f"shard")
        if n_shards & (n_shards - 1):
            return (f"shard count {n_shards} is not a power of two "
                    f"(lane batches and reservoir capacities are "
                    f"power-of-two buckets); the GSPMD path serves this "
                    f"config — pass sharded=<power of two> (or a pow2 "
                    f"mesh) to shard")
        if self._fused_n_cap() % n_shards:
            return (f"population capacity {self._fused_n_cap()} is not "
                    f"divisible by {n_shards} shards; the GSPMD path "
                    f"serves this config — pick a shard count dividing "
                    f"the pow2 population bucket to shard")
        return None

    def _multihost_incapable_reason(self, devs, n_proc: int) -> str | None:
        """Why the sharded multigen kernel cannot serve this MULTI-PROCESS
        mesh (None = capable). The process-count gate lifted in round 18:
        a P-process global mesh runs the same shard-local segment sweeps
        (scalar columns all-gather over DCN, host adaptation replicated-
        deterministic), so the remaining incapabilities are topology
        mistakes. As with :meth:`_sharded_incapable_reason`, every reason
        names the fallback path that serves the config and the change
        that would shard it — the strings are part of the contract."""
        counts: dict[int, int] = {}
        for d in devs:
            counts[d.process_index] = counts.get(d.process_index, 0) + 1
        if len(set(counts.values())) > 1:
            per = {p: counts[p] for p in sorted(counts)}
            return (f"multi-host mesh spans {n_proc} processes with "
                    f"UNEVEN per-process device counts {per}; shard "
                    f"blocks map onto equal per-process device runs — "
                    f"the replicated GSPMD path serves this config "
                    f"(give every process the same device count, e.g. "
                    f"dist.global_mesh(), to shard)")
        blocks: list[int] = []
        for d in devs:
            if not blocks or blocks[-1] != d.process_index:
                blocks.append(d.process_index)
        if len(blocks) != n_proc:
            return (f"multi-host mesh interleaves device blocks from "
                    f"different processes (process order "
                    f"{[int(p) for p in blocks]}); contiguous "
                    f"per-process runs keep shard-local sweeps off DCN "
                    f"— the replicated GSPMD path serves this config "
                    f"(order the mesh devices by process, e.g. "
                    f"dist.global_mesh(), to shard)")
        return None

    def _early_reject_incapable_reason(self, *, adaptive: bool,
                                       stochastic: bool,
                                       sumstat_mode: bool,
                                       sharded_n: int | None
                                       ) -> str | None:
        """Why the segmented early-reject engine cannot serve this fused
        config (None = capable). Mirrors ``_sharded_incapable_reason``:
        every reason names the path that still serves the config —
        incapable configs fall back LOUDLY to the classic
        full-trajectory loop, they never silently change semantics.

        ISSUE 17 killed the three big exclusions: the engine now runs
        INSIDE the sharded kernel (shard-local retire/refill over each
        shard's lane-key block), adaptive distances refit unbiased from
        per-column moments over ALL resolved lanes, and stochastic
        acceptors retire against per-lane pre-committed acceptance
        thresholds when the kernel provides a log-density upper bound.
        What remains gated is genuinely unservable, each reason naming
        why."""
        from ..ops.segment import uniform_protocol_reason

        reason = uniform_protocol_reason(self.models)
        if reason is not None:
            return (f"{reason}; the classic full-trajectory kernel "
                    f"serves this config — declare "
                    f"JaxModel(segmented=...) to enable early reject")
        if self.spec is None:
            return "no SumStatSpec yet (run not initialized)"
        bound = self.distance_function.device_bound_fn(self.spec)
        if bound is None:
            if stochastic:
                return (f"{type(self.distance_function).__name__} has "
                        f"no monotone log-density upper bound "
                        f"(device_bound_fn); the classic kernel serves "
                        f"it — elementwise-separable kernels "
                        f"(IndependentNormal/IndependentLaplace, "
                        f"log-scale Binomial/Poisson) bound soundly")
            return (f"{type(self.distance_function).__name__} has no "
                    f"monotone prefix bound (device_bound_fn); the "
                    f"classic kernel serves it — p-norm-family "
                    f"distances bound soundly")
        upper = bool(bound.get("upper", False))
        if stochastic and not upper:
            return (f"{type(self.distance_function).__name__}'s prefix "
                    f"bound is a distance LOWER bound; stochastic "
                    f"retirement needs a log-density UPPER bound "
                    f"(acceptance provably impossible at the lane's "
                    f"pre-committed draw) — the classic kernel serves "
                    f"this config")
        if not stochastic and upper:
            return ("a log-density upper bound only decides the "
                    "StochasticAcceptor's test; deterministic accepts "
                    "keep the classic kernel")
        if not stochastic and type(self.acceptor) is not UniformAcceptor:
            return ("only the UniformAcceptor's deterministic accept "
                    "test (distance <= eps) is decidable from a "
                    "distance lower bound; custom acceptors keep the "
                    "classic kernel")
        if stochastic and any(
            sch[0] == "acceptance_rate"
            for sch in self._temp_config()[0]
        ):
            return ("the AcceptanceRateScheme reweights the record "
                    "ring of ALL evaluations, but under early reject "
                    "the ring holds completed evaluations only — the "
                    "temperature would be survivor-biased; the classic "
                    "kernel serves this scheme")
        if adaptive:
            d = self.distance_function
            if not d.sharded_scale_capable():
                scale_name = getattr(
                    getattr(d, "scale_function", None), "__name__",
                    repr(getattr(d, "scale_function", None)))
                from ..ops.scale_reduce import SHARDED_SCALE_NAMES

                return (f"adaptive scale function {scale_name!r} has "
                        f"no moment-decomposable reduction, and under "
                        f"early reject the completed-only record ring "
                        f"is survivor-biased — unbiased refits need "
                        f"per-column moments over resolved lanes; the "
                        f"classic kernel serves this config (switch to "
                        f"{', '.join(sorted(SHARDED_SCALE_NAMES))} for "
                        f"early reject)")
            cfg = (d.device_sharded_reduce(self.spec)
                   if self.spec is not None else None)
            if cfg is None or cfg["cols"] is not None:
                return ("adaptive refits under retirement accumulate "
                        "per-column moments over RAW sum-stat columns; "
                        "derived record-column transforms "
                        "(AdaptiveAggregatedDistance sub-distances) "
                        "read whole rows — the classic kernel serves "
                        "this config")
        if sumstat_mode:
            # ISSUE 20: a fitted LINEAR transform admits an EXACT
            # per-prefix bound (null-space projectors of the remaining
            # segments' coefficient rows — ops/fit.py), so the engine
            # serves it under a device-fit plan; anything host-refit
            # or adaptive keeps the classic kernel
            from ..sumstat.device import device_fit_plan

            if adaptive:
                return ("adaptive scale refits interleave with the "
                        "learned-transform refit at the boundary (scale "
                        "follows transform, in the NEW feature space); "
                        "the segmented bound needs a fixed per-"
                        "generation transform — the fused unsharded "
                        "kernel serves this composition")
            plan, plan_reason = device_fit_plan(
                self.distance_function,
                total_size=(self.spec.total_size
                            if self.spec is not None else 0),
                d_max=1, sharded_n=None,
            )
            if plan is None:
                return (f"learned summary statistics without a device-"
                        f"fit plan mix trajectory entries across the "
                        f"prefix with host-refit parameters — no sound "
                        f"per-segment bound ({plan_reason}); the "
                        f"classic kernel serves this config")
            if plan["kind"] != "linear":
                return ("the transformed-space prefix bound is exact "
                        "for LINEAR learned transforms only (projector "
                        "null spaces of the remaining coefficient "
                        "rows); MLP transforms keep the classic kernel")
        if self.mesh is not None and not sharded_n:
            return ("the replicated GSPMD mesh path constrains lane "
                    "arrays per round; segmented early reject composes "
                    "with the sharded kernel (sharded=<n>) or without "
                    "a mesh")
        d = self.distance_function
        for w in getattr(d, "weights", {}).values():
            if np.any(np.asarray(w) < 0):
                return ("negative distance weights break the bound's "
                        "monotonicity; the classic kernel serves them")
        if hasattr(d, "distances"):
            if np.any(np.asarray(d.factors) < 0) or any(
                np.any(np.asarray(w) < 0) for w in d.weights.values()
            ) or any(
                np.any(np.asarray(w) < 0)
                for sub in d.distances
                for w in getattr(sub, "weights", {}).values()
            ):
                return ("negative aggregated-distance weights/factors "
                        "break the bound's monotonicity; the classic "
                        "kernel serves them")
        return None

    def _weight_schedule_fused(self) -> bool:
        """True when the (non-adaptive) distance carries per-generation
        USER weight schedules that must be resolved per chunk generation
        (PNormDistance ``weights={t: ...}``, AggregatedDistance top-level
        or sub-distance schedules)."""
        d = self.distance_function
        if type(d) is PNormDistance:
            return any(k >= 0 for k in d.weights)
        if type(d) is AggregatedDistance:
            return (any(k >= 0 for k in d.weights)
                    or any(any(k >= 0 for k in sub.weights)
                           for sub in d.distances))
        return False

    def _fused_calibration_cfg(self) -> tuple | None:
        """(n_calib, calib_w, calib_eps) when the FIRST fused chunk can
        run the calibration generation in-kernel (prior round at
        eps=+inf; adaptive distances take initial 1/scale weights from
        it, a from-sample quantile epsilon takes eps_0) — removing the
        host calibration round trip from every fresh run. None = host
        calibration (reference ABCSMC._initialize_dist_eps_acc path).

        Declared deviation: the in-kernel calibration sample keeps only
        VALID simulations (NaN/invalid rows are excluded), where the
        host path accepts every row unconditionally — for a model that
        can produce non-finite statistics the host median would be
        poisoned anyway."""
        from ..epsilon import QuantileEpsilon

        d = self.distance_function
        if getattr(d, "sumstat", None) is not None:
            # learned-statistic scales must be fit in the TRANSFORMED
            # feature space; the in-kernel calibration reduces raw
            # sumstats, so that configuration stays host-side
            return None
        if self._sharded_n():
            # sharded chunks keep calibration on the host (the record
            # ring is shard-local); the one calibration collect rides
            # the sync budget's O(1) allowance
            return None
        calib_w = bool(d.requires_calibration())
        calib_eps = bool(self.eps.requires_calibration())
        if self.acceptor.requires_calibration():
            return None  # stochastic pdf-norm init stays on the host
        if not (calib_w or calib_eps):
            return None
        if type(self.acceptor) is not UniformAcceptor \
                or self.acceptor.use_complete_history:
            return None
        if calib_w and not (
            type(d) in (AdaptivePNormDistance, AdaptiveAggregatedDistance)
            and d.adaptive
        ):
            # the in-kernel scale machinery IS the calibration fit; a
            # calibration-requiring distance without it stays host-side
            return None
        if calib_eps and not isinstance(self.eps, QuantileEpsilon):
            return None
        n_cal = (self.population_strategy.nr_calibration_particles
                 or self.population_strategy(0))
        # the calibration sample must fit the chunk's static shapes
        if int(n_cal) > self._fused_n_cap():
            return None
        return (int(n_cal), calib_w, calib_eps)

    def _fused_n_cap(self) -> int:
        """The fused chunks' static particle capacity: the pow2 bucket of
        the schedule's (or adaptive cap's) largest generation. SINGLE
        source for _loop_fused's reservoir sizing and
        _fused_calibration_cfg's fit check."""
        from ..populationstrategy import AdaptivePopulationSize
        from ..utils import pow2_bucket as _pow2

        n0 = self.population_strategy(0)
        if isinstance(self.population_strategy, ListPopulationSize):
            n_max = max(self.population_strategy.values)
        elif isinstance(self.population_strategy, AdaptivePopulationSize) \
                and np.isfinite(self.population_strategy.max_population_size):
            n_max = max(
                n0, int(self.population_strategy.max_population_size)
            )
        else:
            n_max = n0
        return _pow2(n_max, 64)

    def _fused_adaptive_n_capable(self) -> bool:
        """AdaptivePopulationSize configs whose bootstrap-CV bisection can
        run IN-KERNEL (``transition.util.device_mean_cv`` /
        ``device_required_nr`` generics): plain MVN or LocalTransition
        per model (K>1 aggregates per-model CVs weighted by model
        probabilities, reference ``calc_cv``), and a finite
        max_population_size — static shapes are sized to it, so an
        unbounded adaptive growth target cannot ride a chunk.
        GridSearchCV stays on the host path (its host ``mean_cv``
        delegates to the winning estimator chosen per generation, which
        has no chunk-constant static config)."""
        from ..populationstrategy import AdaptivePopulationSize

        return (
            isinstance(self.population_strategy, AdaptivePopulationSize)
            and all(
                type(tr) in (MultivariateNormalTransition, LocalTransition)
                for tr in self.transitions
            )
            and len({type(tr) for tr in self.transitions}) == 1
            and np.isfinite(self.population_strategy.max_population_size)
        )

    #: temperature schemes with device twins (DeviceContext.
    #: _stochastic_gen_update); Daly's contraction state rides the chunk
    #: carry, Ess bisects relative ESS in-kernel — ALL reference schemes
    #: can chain on device
    _DEVICE_TEMP_SCHEMES = {
        "AcceptanceRateScheme", "ExpDecayFixedIterScheme",
        "ExpDecayFixedRatioScheme", "PolynomialDecayFixedIterScheme",
        "FrielPettittScheme", "DalyScheme", "EssScheme",
    }

    def _fused_stochastic_capable(self) -> bool:
        """Noisy-ABC configs the multigen kernel can chain on device:
        single model, max-found pdf norm, Temperature with min-aggregated
        monotone schemes from the device-twin set, device-compatible
        stochastic kernel distance (static params)."""
        from ..acceptor.pdf_norm import pdf_norm_max_found
        from ..epsilon import ListTemperature, Temperature

        if self.K != 1:
            return False
        from ..acceptor.pdf_norm import ScaledPDFNorm

        a = self.acceptor
        meth = a.pdf_norm_method
        if not (meth is pdf_norm_max_found
                or isinstance(meth, ScaledPDFNorm)) or a.log_file:
            return False
        eps = self.eps
        if type(eps) is ListTemperature:
            pass  # deterministic ladder rides the eps_fixed chunk input
        elif type(eps) is not Temperature:
            return False
        else:
            if eps.aggregate_fun is not min \
                    or not eps.enforce_less_equal_prev or eps.log_file:
                return False
            if not eps._effective_schemes():
                # schemes=[] means no device annealing recursion exists;
                # the host loop handles that degenerate configuration
                return False
            need_horizon = {"ExpDecayFixedIterScheme",
                            "PolynomialDecayFixedIterScheme",
                            "FrielPettittScheme"}
            for sch in eps._effective_schemes():
                name = type(sch).__name__
                if name not in self._DEVICE_TEMP_SCHEMES:
                    return False
                if name in need_horizon and eps._max_nr_populations is None:
                    return False
        d = self.distance_function
        if not isinstance(d, StochasticKernel) or not d.is_device_compatible():
            return False
        tr = self.transitions[0]
        from ..transition.util import (
            scott_rule_of_thumb,
            silverman_rule_of_thumb,
        )

        if type(tr) is MultivariateNormalTransition:
            if tr.bandwidth_selector not in (scott_rule_of_thumb,
                                             silverman_rule_of_thumb):
                return False
        elif type(tr) is LocalTransition:
            # static neighbor count k needs a constant population size
            # (same gate as the uniform-acceptor branch)
            if not isinstance(self.population_strategy,
                              ConstantPopulationSize):
                return False
        else:
            return False
        if type(self.model_perturbation_kernel) is not ModelPerturbationKernel:
            return False
        if np.isfinite(self.max_nr_recorded_particles):
            return False
        return True

    def _ensure_distance_spec(self, d) -> None:
        """Attach the observed-data SumStatSpec to a distance (and any
        sub-distances of an aggregate) that hasn't been initialized yet —
        device_params needs the spec before the calibration generation."""
        if hasattr(d, "spec") and getattr(d, "spec", None) is None:
            d.spec = self.spec
        for sub in getattr(d, "distances", ()) or ():
            self._ensure_distance_spec(sub)

    def _distance_may_change(self, d=None) -> bool:
        """True when the distance's space can change between generations
        (update() may return True: adaptive reweighting — AdaptivePNorm,
        AdaptiveAggregated — or learned-sumstat refits, in the distance
        itself or any sub-distance of an aggregate). Such changes make
        past epsilon thresholds incomparable (the complete-history trail
        restarts on them)."""
        if d is None:
            d = self.distance_function
        if bool(getattr(d, "adaptive", False)) \
                or getattr(d, "sumstat", None) is not None:
            return True
        return any(self._distance_may_change(sub)
                   for sub in getattr(d, "distances", ()) or ())

    def _template_transition(self):
        """A throwaway FITTED transition of the configured class, used
        only for its ``device_params`` pytree structure (zeroed into the
        first-chunk carry of a prior-mode fused run)."""
        import pandas as pd

        cp = self.transitions[0].copy_unfitted()
        space = self.parameter_priors[0].space
        dim = space.dim
        names = list(space.names)
        rows = max(dim + 2, 4)
        X = pd.DataFrame(
            np.random.default_rng(0).normal(size=(rows, dim)),
            columns=names,
        )
        cp.fit(X, np.full(rows, 1.0 / rows))
        return cp

    def _transition_fit_statics(self, n: int) -> tuple:
        """Per-model static kwargs for the in-kernel ``device_fit`` refits.

        MVN: (scaling, bandwidth_selector). LocalTransition:
        (scaling, k_cap, k_fixed, k_fraction) — k_cap is the static top_k
        bound (the host ``_effective_k`` rule at the schedule's maximum
        population size); the per-model/per-generation k itself is derived
        IN-KERNEL from each model's accepted count. GridSearchCV:
        (scalings, cv, bandwidth_selector, n) with row-indexed folds over
        the constant population size.
        """
        out = []
        for m, tr in enumerate(self.transitions):
            dim = self.parameter_priors[m].space.dim
            if type(tr) is LocalTransition:
                out.append((
                    ("scaling", tr.scaling),
                    # static top_k bound = the rule at the full population;
                    # the per-model dynamic k is computed in-kernel
                    ("k_cap", tr._effective_k(n, dim)),
                    ("k_fixed", int(tr.k) if tr.k is not None else -1),
                    ("k_fraction", tr.k_fraction),
                    ("k_max", tr.k_max),
                    # neighbor selection: exact top_k below the cutoff,
                    # threshold (radius bisection + masked gather,
                    # ops/select.py) above — the sub-sort scale path
                    ("selection", tr.selection),
                ))
            elif type(tr) is GridSearchCV:
                statics = [
                    ("scalings", tuple(
                        float(s) for s in tr.param_grid["scaling"])),
                    ("cv", int(tr.cv)),
                    ("bandwidth_selector",
                     tr.estimator.bandwidth_selector),
                ]
                if isinstance(self.population_strategy,
                              ListPopulationSize):
                    # a varying schedule ships per-generation fold-id
                    # rows as a dynamic chunk argument instead of the
                    # static n-derived assignment
                    pass
                else:
                    # folds are assigned over the actual population size,
                    # matching the host fit on n accepted rows
                    statics.append(("n", int(n)))
                out.append(tuple(statics))
            else:
                out.append((("scaling", tr.scaling),
                            ("bandwidth_selector", tr.bandwidth_selector)))
        return tuple(out)

    def _refit_cadence_cfg(self, n_cap: int) -> tuple | None:
        """(refit_every, drift_threshold) for the multigen kernel's
        amortized proposal engine, or None — refit every generation, the
        pre-cadence program kept BYTE-IDENTICAL for every configuration
        that doesn't opt in. Scope: LocalTransition (the only transition
        whose refit cost ever dominated a lane — BASELINE.md r5 pop-16k;
        MVN refits are one weighted covariance and not worth a stale
        proposal). Auto (refit_every=None): 16 at populations >= 16384
        — the scale lane — else 1."""
        if type(self.transitions[0]) is not LocalTransition:
            return None
        every = self.refit_every
        if every is None:
            every = 16 if n_cap >= 16384 else 1
        if every <= 1:
            return None
        return (int(every), float(self.refit_drift_threshold))

    def _health_cfg(self) -> tuple | None:
        """(ess_floor, acc_floor, eps_stall_window, eps_stall_rtol) for
        the multigen kernel's in-kernel health word, or None when health
        checks are disabled. The epsilon-stall window only arms for
        schedules that ADAPT epsilon from the data (quantile thresholds,
        temperature schemes) — a fixed List/Constant schedule never
        "improves" and must not read as stalled."""
        from ..epsilon import QuantileEpsilon, Temperature

        if not self.health_checks:
            return None
        stall_w = int(self.eps_stall_window)
        eps_adaptive = isinstance(self.eps, QuantileEpsilon) or (
            type(self.acceptor) is StochasticAcceptor
            and type(self.eps) is Temperature
        )
        if not eps_adaptive:
            stall_w = 0
        return (float(self.ess_floor), float(self.health_acc_floor),
                stall_w, float(self.eps_stall_rtol))

    def _temp_config(self) -> tuple:
        """Static scheme descriptor tuple for the device temperature twin."""
        from ..distance.kernel import SCALE_LIN

        eps = self.eps
        schemes = []
        # ListTemperature has no schemes: the ladder arrives via eps_fixed
        for sch in (eps._effective_schemes()
                    if hasattr(eps, "_effective_schemes") else ()):
            name = type(sch).__name__
            if name == "AcceptanceRateScheme":
                schemes.append(("acceptance_rate", float(sch.target_rate)))
            elif name == "ExpDecayFixedIterScheme":
                schemes.append(("exp_decay_fixed_iter",))
            elif name == "ExpDecayFixedRatioScheme":
                schemes.append(("exp_decay_fixed_ratio", float(sch.alpha),
                                float(sch.min_rate), float(sch.max_rate)))
            elif name == "PolynomialDecayFixedIterScheme":
                schemes.append(("poly_decay_fixed_iter",
                                float(sch.exponent)))
            elif name == "FrielPettittScheme":
                schemes.append(("friel_pettitt",))
            elif name == "DalyScheme":
                schemes.append(("daly", float(sch.alpha),
                                float(sch.min_rate)))
            elif name == "EssScheme":
                schemes.append(("ess", float(sch.target_relative_ess)))
        max_np_raw = getattr(eps, "_max_nr_populations", None)
        max_np = int(max_np_raw) if max_np_raw is not None else -1
        kernel = self.distance_function
        pdf_max = kernel.pdf_max
        lin = kernel.ret_scale == SCALE_LIN
        if pdf_max is not None:
            pdf_max = float(np.log(max(pdf_max, 1e-300))) if lin \
                else float(pdf_max)
            if not np.isfinite(pdf_max):
                pdf_max = None
        from ..acceptor.pdf_norm import ScaledPDFNorm

        meth = self.acceptor.pdf_norm_method
        pdf_scaled = ((float(meth.factor), float(meth.alpha))
                      if isinstance(meth, ScaledPDFNorm) else None)
        return (tuple(schemes), max_np, pdf_max, lin, pdf_scaled)

    def _loop_fused(self, t0, minimum_epsilon, max_nr_populations,
                    min_acceptance_rate, max_total_nr_simulations,
                    max_walltime, start_walltime) -> History:
        """Chunked whole-run-on-device loop: G generations per dispatch.

        Generation 0 (prior mode) runs through the ordinary single-
        generation kernel; afterwards the host only (a) persists fetched
        populations, (b) mirrors the device-side component updates into the
        host objects (epsilon values, adaptive distance weights, transition
        refit from the last population) so resume/config/telemetry stay
        exactly as in the per-generation paths, and (c) applies stopping
        rules between chunks (in-chunk stops are handled by the kernel's
        carried flag; walltime/sim budgets are checked at chunk granularity).
        """
        import copy

        import jax
        import jax.numpy as jnp

        from ..epsilon import ListEpsilon, ListTemperature, QuantileEpsilon
        from ..utils import pow2_bucket as _pow2
        from .util import pad_transition_params

        t = t0
        sims_total = self.history.total_nr_simulations
        n = self.population_strategy(t)

        # learned/transformed statistics ride the chunk as constant device
        # params with host boundary refits; generation 0 stays on the host
        # there (the refit machinery owns the t=0 bring-up). Every other
        # fresh fused run puts generation 0 INSIDE the first chunk
        # (prior-mode first generation): the whole run becomes one
        # dispatch chain with no synchronous gen-0 round trip.
        _sumstat_mode_early = getattr(
            self.distance_function, "sumstat", None
        ) is not None
        first_gen_prior = (t == 0) and not _sumstat_mode_early

        if t == 0 and not first_gen_prior:
            current_eps = self.eps(0)
            if hasattr(self.acceptor, "note_epsilon"):
                self.acceptor.note_epsilon(0, current_eps, False)
            logger.info("t: 0, eps: %.8g", current_eps)
            clk = self._clock.now
            with self.tracer.span("generation", t=0, n=int(n),
                                  eps=float(current_eps)) as g_span:
                t_gen0 = clk()
                with self.tracer.span("sample", t=0):
                    gen_spec = self._generation_spec(0)
                    sample = self.sampler.sample_until_n_accepted(
                        n, gen_spec, 0,
                        max_eval=(n / min_acceptance_rate
                                  if min_acceptance_rate > 0 else np.inf),
                    )
                sample_s = clk() - t_gen0
                if sample.n_accepted < n:
                    logger.info(
                        "stopping: only %d/%d accepted within budget",
                        sample.n_accepted, n)
                    self.history.done()
                    return self.history
                pop = self._sample_to_population(sample)
                nr_evals = self.sampler.nr_evaluations_
                sims_total += nr_evals
                acceptance_rate = n / nr_evals
                db_pop = copy.copy(pop)
                t_adapt0 = clk()
                with self.tracer.span("adapt", t=0):
                    self._adapt_components(0, sample, pop, current_eps,
                                           acceptance_rate)
                adapt_s = clk() - t_adapt0
                t_persist0 = clk()
                with self.tracer.span("persist", t=0):
                    self.history.append_population(
                        0, current_eps, db_pop, nr_evals, self.model_names,
                        telemetry={
                            "sample_s": round(sample_s, 4),
                            "adapt_s": round(adapt_s, 4),
                            "n_evaluations": int(nr_evals),
                            "acceptance_rate": round(acceptance_rate, 6)},
                    )
                self.history.update_telemetry(
                    0, {"persist_s": round(clk() - t_persist0, 4)}
                )
                g_span.set(n_accepted=int(n), n_evaluations=int(nr_evals))
            if self.chunk_event_cb is not None:
                # generation 0 runs outside the chunk pipeline but its
                # particles/time belong to the caller's global clock
                try:
                    self.chunk_event_cb({
                        "ts": clk(), "t_first": 0, "gens": 1,
                        "n_acc": int(n), "chunk_index": 0,
                        "chunk_s": float(sample_s),
                        "fetch_s": 0.0, "dispatch_s": 0.0,
                        "process_s": float(adapt_s),
                    })
                except Exception:
                    logger.exception("chunk_event_cb failed")
            # span-federation cadence: generation 0 runs outside the
            # chunk pipeline but its spans belong to the pod timeline
            fire_span_ship_hooks()
            if self._check_stop(0, current_eps, minimum_epsilon,
                                max_nr_populations, acceptance_rate,
                                min_acceptance_rate, sims_total,
                                max_total_nr_simulations, max_walltime,
                                start_walltime):
                self.history.done()
                return self.history
            t = 1

        ctx = self._build_device_ctx()
        tr = self.transitions[0]
        stochastic = type(self.acceptor) is StochasticAcceptor
        eps_quantile = isinstance(self.eps, QuantileEpsilon)
        adaptive = (
            (isinstance(self.distance_function, AdaptivePNormDistance)
             and self.distance_function.adaptive)
            or (type(self.distance_function) is AdaptiveAggregatedDistance
                and self.distance_function.adaptive)
        )
        # learned/transformed statistics ride the chunk as constant device
        # params; the predictor refits on the host BETWEEN chunks (next
        # chunk gets a fresh carry), so chunks are dispatched non-
        # speculatively in this mode
        sumstat_mode = getattr(self.distance_function, "sumstat", None) \
            is not None
        if sumstat_mode and self._resume_carry is not None \
                and t == self.resumed_from_checkpoint_t:
            # fresh-process resume: the fitted transform lives in the
            # checkpoint carry's dist_w slot, not in this process's
            # (unfitted) predictor — restore it BEFORE the device-fit
            # plan fixes the C' dimension, so the rebuilt validation
            # carry and the resumed carry share one pytree structure
            # (f32 round-trip: bit-identical to the carried operands)
            from ..sumstat.device import (
                mirror_fitted_params,
                seed_params_ready,
            )

            dw = self._resume_carry[3]
            if not seed_params_ready(self.distance_function) \
                    and isinstance(dw, dict) and len(dw.get("ss", ())):
                mirror_fitted_params(
                    self.distance_function,
                    jax.tree.map(np.asarray, dw["ss"]), t - 1,
                )
        # static shapes are sized for the LARGEST generation of a varying
        # (ListPopulationSize) schedule; smaller generations mask down.
        # In-kernel adaptive n sizes them to the strategy's hard cap.
        adaptive_n = self._fused_adaptive_n_capable()
        if isinstance(self.population_strategy, ListPopulationSize):
            n_max = max(self.population_strategy.values)
        elif adaptive_n:
            n_max = max(
                n, int(self.population_strategy.max_population_size)
            )
        else:
            n_max = n
        n_cap = self._fused_n_cap()  # == _pow2(n_max, 64), single source
        # sharded fused sampling (ISSUE 9): population axis over the mesh
        sharded_n = self._sharded_n()
        # ISSUE 20: device-native learned-statistic fits — when the
        # predictor has a traceable in-kernel twin (ops/fit.py) the
        # boundary refit moves INTO the kernel: fitted params ride the
        # chunk carry, the packed fetch ships transformed C'-dim rows,
        # and the engine's legacy host-refit dispatch mode (depth-1
        # pipeline, f32 fetch, no speculation/checkpoints) is bypassed
        sumstat_plan = None
        sumstat_fit_token = None
        if sumstat_mode:
            from ..sumstat.device import (
                device_fit_plan,
                plan_cache_token,
                seed_params_ready,
            )

            ss = self.distance_function.sumstat
            plan, plan_reason = device_fit_plan(
                self.distance_function,
                total_size=self.spec.total_size,
                d_max=int(getattr(ss, "_out_dim", None) or 0),
                sharded_n=sharded_n,
            )
            if plan is not None and not seed_params_ready(
                    self.distance_function):
                plan, plan_reason = None, (
                    "the generation-0 host fit did not seed the "
                    "predictor (min_samples not reached), so the "
                    "carried parameter structure and C' dimension are "
                    "unfixed; the host-refit path serves this run"
                )
            if plan is not None and adaptive and sharded_n:
                # unreachable through _sharded_incapable_reason (it
                # refuses adaptive+sumstat), kept as a structural guard
                plan, plan_reason = None, (
                    "adaptive scale + learned transform compose on the "
                    "unsharded device-fit path only"
                )
            if plan is None:
                logger.info("device-native sumstat fit off: %s",
                            plan_reason)
                self._note_capability_fallback(
                    "sumstat_device", plan_reason)
                if sharded_n:
                    # the STATIC sharded gate admitted this config (a
                    # linear plan resolves), but the runtime seeding
                    # failed (e.g. a no-checkpoint resume: the fitted
                    # transform is unrecoverable from transformed
                    # History rows) — the sharded kernel cannot serve
                    # host-refit sumstat mode, so sharding drops too
                    self._note_capability_fallback(
                        "sharded",
                        "learned-sumstat device-fit plan failed at "
                        "runtime (" + str(plan_reason) + "); the "
                        "host-refit path serves the run unsharded")
                    sharded_n = None
            else:
                sumstat_fit_token = plan_cache_token(plan)
            sumstat_plan = plan
        self._sumstat_device_plan = sumstat_plan
        # record-ring capacity for the adaptive/stochastic mechanisms; in
        # sharded mode the ring is PER SHARD, so the per-shard cap scales
        # down to keep the total recorded evaluations comparable to the
        # unsharded ring (a pure configuration choice — the virtual-shard
        # parity reference uses the identical per-shard cap)
        if adaptive or stochastic:
            rec_cap = _pow2(
                max(8 * n_cap // (sharded_n or 1), 1), 256
            )
        else:
            rec_cap = 1
        B = self.sampler._pick_B(n_max)
        if sharded_n:
            # every shard needs a whole lane block (both are powers of
            # two, so a bump keeps divisibility)
            B = max(B, sharded_n)
        max_rounds = self.sampler.max_rounds
        if min_acceptance_rate > 0:
            max_rounds = max(1, min(
                max_rounds, int(n_max / min_acceptance_rate) // B + 1
            ))

        G = self.fused_generations
        # fetch compaction row cap: the chunk's largest scheduled
        # population, NOT the pow2-padded ring capacity (in-kernel
        # adaptive n can grow to the ring cap, so it keeps every row)
        n_keep = n_cap if adaptive_n else min(n_max, n_cap)
        temp_fixed = stochastic and type(self.eps) is ListTemperature
        complete_history = (
            type(self.acceptor) is UniformAcceptor
            and self.acceptor.use_complete_history
        )
        weight_sched = not adaptive and self._weight_schedule_fused()
        fold_sched_mode = (
            type(tr) is GridSearchCV
            and isinstance(self.population_strategy, ListPopulationSize)
        )
        fused_cal = (
            self._fused_calibration_cfg() if first_gen_prior else None
        )
        refit_cadence = self._refit_cadence_cfg(n_cap)
        if sharded_n:
            # the chunk-boundary proposal refit IS the cadence refit:
            # default to one refit per G-generation chunk (row collective
            # once per chunk); an explicit refit_every is honored. The
            # drift guard needs cross-shard theta moments, so it is
            # inactive here (threshold inf) — PR-3 exactness still holds,
            # importance weights always use the params actually sampled.
            every = self.refit_every if self.refit_every is not None else G
            refit_cadence = (max(int(every), 1), float("inf"))
        # segmented early-reject execution (ISSUE 15): on when requested
        # and capable — incapable configs fall back loudly (the reason
        # names the serving path), early_reject=True makes them fatal
        seg_cfg = None
        if self.early_reject in ("auto", True):
            seg_reason = self._early_reject_incapable_reason(
                adaptive=adaptive, stochastic=stochastic,
                sumstat_mode=sumstat_mode, sharded_n=sharded_n,
            )
            if seg_reason is None and sumstat_mode \
                    and sumstat_plan is None:
                # the static gate admitted a linear device-fit plan but
                # the runtime seeding failed (no-checkpoint resume):
                # host-refit sumstat mode has no sound per-prefix bound
                seg_reason = ("learned-sumstat device-fit plan failed "
                              "at runtime; the transformed prefix "
                              "bound needs the fitted linear transform")
            if seg_reason is None:
                seg_cfg = ctx.segment_cfg(stochastic=stochastic)
            elif self.early_reject is True:
                raise ValueError(
                    f"early_reject=True unavailable: {seg_reason}"
                )
            elif any(
                getattr(m, "segmented", None) is not None
                for m in self.models
            ):
                # only worth a log line when the user built segmented
                # models — every plain config would spam otherwise
                logger.info("segmented early reject off: %s", seg_reason)
                self._note_capability_fallback("early_reject", seg_reason)
        health_cfg = self._health_cfg()
        # the multigen kernel's static configuration; the dispatch engine
        # owns the build (kernel.build span) and every invocation —
        # abc-lint DISP001 bans direct kernel calls outside the engine
        kernel_kwargs = dict(
            segment_cfg=seg_cfg,
            weight_sched=weight_sched,
            fold_sched_mode=fold_sched_mode,
            first_gen_prior=first_gen_prior,
            fused_calibration=fused_cal,
            adaptive=adaptive, eps_quantile=eps_quantile,
            eps_weighted=getattr(self.eps, "weighted", True),
            alpha=getattr(self.eps, "alpha", 0.5),
            multiplier=getattr(self.eps, "quantile_multiplier", 1.0),
            trans_cls=type(tr),
            fit_statics=self._transition_fit_statics(n_max),
            dims=tuple(p.space.dim for p in self.parameter_priors),
            stochastic=stochastic,
            temp_config=self._temp_config() if stochastic else None,
            temp_fixed=temp_fixed,
            complete_history=complete_history,
            sumstat_transform=sumstat_mode,
            sumstat_fit=sumstat_fit_token,
            adaptive_n=(
                (float(self.population_strategy.mean_cv),
                 int(self.population_strategy.min_population_size),
                 int(min(self.population_strategy.max_population_size,
                         n_cap)),
                 int(self.population_strategy.n_bootstrap))
                if adaptive_n else None
            ),
            refit_cadence=refit_cadence,
            health_config=health_cfg,
            sharded=sharded_n,
        )
        # sharded merge semantics: a constant population keeps the STATIC
        # in-fetch merge gather (ops/shard.py::merge_index — the
        # round-13 program byte-identical); per-generation schedules and
        # in-kernel adaptive n ship the full shard-blocked reservoir and
        # the HOST re-indexes each generation with its own static-quota
        # merge (DispatchEngine._merge_shard_rows) — adding a
        # per-generation gather to the kernel outputs perturbs XLA's
        # fusion differently per execution mode and breaks the
        # mesh == virtual-shard bit-identity contract
        dynamic_pop = bool(sharded_n) and (
            adaptive_n
            or type(self.population_strategy) is not ConstantPopulationSize
        )
        if dynamic_pop:
            # the fetch ships every reservoir row (shard-blocked layout);
            # the host merge slices each generation to its scheduled n
            n_keep = n_cap
        # per-generation cross-shard collective payload of the adaptive
        # mechanisms (scalar-per-stat scale partials + the ring's scalar
        # columns for the stochastic record reweighting) — exported into
        # snapshot()["mesh"] so the new traffic is accounted, not assumed
        mesh_scale_bytes = 0
        if sharded_n and adaptive:
            shard_cfg = self.distance_function.device_sharded_reduce(
                self.spec)
            if shard_cfg is not None:
                cols_dim = shard_cfg["cols_dim"] or self.spec.total_size
                mesh_scale_bytes += (
                    4 * shard_cfg["moment_rows"] * cols_dim * sharded_n
                )
        if sharded_n and stochastic:
            schemes = self._temp_config()[0]
            if any(s[0] == "acceptance_rate" for s in schemes):
                # logq / logq_new / kernel value (f32) + validity (bool)
                mesh_scale_bytes += (3 * 4 + 1) * rec_cap * sharded_n

        def _g_limit(t_at: int) -> int:
            g = G
            if np.isfinite(max_nr_populations):
                g = min(g, int(max_nr_populations) - t_at)
            if isinstance(self.eps, ListEpsilon):
                g = min(g, len(self.eps.epsilon_values) - t_at)
            if isinstance(self.population_strategy, ListPopulationSize):
                g = min(g, len(self.population_strategy.values) - t_at)
            return max(g, 0)

        def _chunk_host_args(t_at: int, g_limit: int) -> dict:
            """Host-resolved per-chunk schedules — the STATISTICAL half
            of a dispatch (epsilon ladder, population sizes, user weight
            schedules, CV fold tables); the engine turns these into
            kernel arguments and owns the invocation itself."""
            eps_fixed = np.zeros(G, np.float32)
            if (not eps_quantile and not stochastic) or temp_fixed:
                for g in range(g_limit):
                    eps_fixed[g] = self.eps(t_at + g)
            n_sched = np.full(G, n, np.int32)
            for g in range(g_limit):
                n_sched[g] = self.population_strategy(t_at + g)
            dist_sched = None
            if weight_sched:
                # resolve the user's per-generation weight schedule into a
                # stacked device_params table (leading G axis); inactive
                # tail generations reuse the last active row
                rows = [
                    self.distance_function.device_params(
                        t_at + min(g, max(g_limit - 1, 0))
                    )
                    for g in range(G)
                ]
                dist_sched = jax.tree.map(
                    lambda *xs: jnp.stack(
                        [jnp.asarray(np.asarray(x, np.float32))
                         for x in xs]
                    ),
                    *rows,
                )
            fold_sched = None
            if fold_sched_mode:
                # per-generation fold-id rows (GridSearchCV x
                # ListPopulationSize): the shared fixed-seed rule applied
                # to each generation's scheduled n; inactive tail
                # generations reuse the last active row
                from ..transition.grid_search import fold_ids

                table = np.stack([
                    fold_ids(
                        min(int(n_sched[min(g, max(g_limit - 1, 0))]),
                            n_cap),
                        int(tr.cv), n_cap,
                    )
                    for g in range(G)
                ])
                fold_sched = jnp.asarray(table)
            return {"eps_fixed": eps_fixed, "n_sched": n_sched,
                    "dist_sched": dist_sched, "fold_sched": fold_sched}

        def _build_chunk_carry(t_at: int):
            """Host-state -> device chunk carry: per-model transition params
            (host fit of the previous generation) padded to the reservoir
            shape — never-fitted models get zero placeholders and a False
            fitted-mask entry (the kernel masks them out of the model-
            perturbation matrix) — plus model log-probs, distance params,
            epsilon/temperature and the stochastic pdf-norm state."""
            trans0 = []
            fitted0 = np.zeros(self.K, bool)
            ref_fitted = next(
                (x for x in self.transitions if x.X is not None), None
            )
            if ref_fitted is None:
                if t_at != 0:
                    raise RuntimeError(
                        "no fitted transition to start a fused chunk"
                    )
                # prior-mode first chunk: nothing is fitted yet. A
                # throwaway fit on standard-normal dummies provides the
                # params pytree STRUCTURE; the leaves are zeroed below
                # (fitted0 stays all-False, generation 0 proposes from
                # the prior, and the in-kernel refit replaces these
                # before any transition proposal reads them).
                ref_fitted = self._template_transition()
            for m, tr_m in enumerate(self.transitions):
                if tr_m.X is not None:
                    raw = jax.tree.map(np.asarray, tr_m.device_params())
                    fitted0[m] = True
                else:
                    raw = jax.tree.map(
                        lambda v: np.zeros_like(np.asarray(v)),
                        ref_fitted.device_params(),
                    )
                trans0.append(pad_transition_params(raw, n_cap, ctx.d_max))
            probs0 = np.zeros(self.K)
            for m, p in self._model_probs.items():
                probs0[int(m)] = p
            with np.errstate(divide="ignore"):
                log_probs0 = np.log(probs0)
            # pytree-generic: stochastic kernels / sumstat-bearing
            # distances carry structured params
            dist_w0 = jax.tree.map(
                lambda v: jnp.asarray(np.asarray(v, np.float32)),
                self.distance_function.device_params(t_at),
            )
            if stochastic:
                # seed the device pdf-norm recursion from the host
                # acceptor's state for generation t_at; seed Daly's
                # contraction state from the host scheme's _k dict (its
                # default when never called: the current temperature)
                temp_at = float(self.eps(t_at))
                daly_k0 = temp_at if np.isfinite(temp_at) else 1e4
                for sch in (self.eps._effective_schemes()
                            if hasattr(self.eps, "_effective_schemes")
                            else ()):
                    if type(sch).__name__ == "DalyScheme":
                        k = sch._k.get(t_at, daly_k0)
                        daly_k0 = k if np.isfinite(k) else daly_k0
                acc_state0 = (
                    jnp.asarray(self.acceptor.pdf_norms.get(t_at, 0.0),
                                jnp.float32),
                    jnp.asarray(
                        self.acceptor._max_found
                        if np.isfinite(self.acceptor._max_found) else -1e30,
                        jnp.float32),
                    jnp.asarray(daly_k0, jnp.float32),
                )
            else:
                # with use_complete_history, slot 0 seeds the running min
                # of all epsilons BEFORE the chunk's first generation
                # (device_params(t) IS the acceptor's historic-min export)
                acc_state0 = (
                    jnp.asarray(self.acceptor.device_params(t_at)
                                if complete_history else 0.0, jnp.float32),
                    jnp.asarray(-1e30, jnp.float32),
                    jnp.zeros((), jnp.float32))
            if t_at == 0 and fused_cal is not None and fused_cal[2]:
                # deferred from-sample epsilon: the in-kernel calibration
                # overwrites this placeholder before generation 0 runs
                eps0_host = 0.0
            else:
                eps0_host = self.eps(t_at)
            base = (tuple(trans0), jnp.asarray(log_probs0, jnp.float32),
                    jnp.asarray(fitted0), dist_w0,
                    jnp.asarray(eps0_host, jnp.float32),
                    acc_state0,
                    jnp.asarray(False))
            if adaptive_n:
                # seed the in-kernel n recursion from the host strategy's
                # current decision (gen 0 / resume adapt on the host)
                base = base + (jnp.asarray(
                    min(self.population_strategy(t_at), n_cap), jnp.int32),)
            if refit_cadence is not None:
                # generations-since-refit counter: the carry's params are
                # a fresh host fit (or the forced first in-kernel refit
                # handles the prior-mode chunk), so the cadence starts at 0
                base = base + (jnp.zeros((), jnp.int32),)
            if health_cfg is not None:
                # epsilon-stall recursion seed (eps_prev, stall_count):
                # the previous generation's epsilon when known, else inf
                # (= "no previous", counted as full improvement)
                try:
                    eps_prev0 = (float(self.eps(t_at - 1)) if t_at > 0
                                 else float("inf"))
                except (KeyError, IndexError, ValueError):
                    eps_prev0 = float("inf")
                if not np.isfinite(eps_prev0):
                    eps_prev0 = float("inf")
                base = base + ((jnp.asarray(eps_prev0, jnp.float32),
                                jnp.zeros((), jnp.int32)),)
            return base

        from .dispatch import DispatchEngine

        # the ONE async dispatch engine (round 12): kernel build, chunk
        # dispatch/fetch pipeline, drain, health redispatch and the sync
        # budget all live in inference/dispatch.py — this method only
        # supplies the statistical hooks
        engine = DispatchEngine(
            self, ctx,
            shapes=(B, n_cap, rec_cap, max_rounds, G),
            kernel_kwargs=kernel_kwargs,
            g_limit=_g_limit,
            chunk_host_args=_chunk_host_args,
            rebuild_carry=_build_chunk_carry,
            stop={"minimum_epsilon": minimum_epsilon,
                  "max_nr_populations": max_nr_populations,
                  "min_acceptance_rate": min_acceptance_rate,
                  "max_total_nr_simulations": max_total_nr_simulations,
                  "max_walltime": max_walltime,
                  "start_walltime": start_walltime},
            n_of=self.population_strategy,
            sumstat_refit=sumstat_mode and sumstat_plan is None,
            adaptive=adaptive,
            stochastic=stochastic,
            temp_fixed=temp_fixed,
            eps_quantile=eps_quantile,
            adaptive_n=adaptive_n,
            n_keep=n_keep,
            shard_merge=(
                None if not sharded_n
                else "dynamic" if dynamic_pop
                else _shard_merge_index(
                    n_keep, sharded_n, n_cap // sharded_n)
            ),
            mesh_shards=sharded_n,
            mesh_scale_bytes=mesh_scale_bytes,
        )
        self._engine = engine

        carry0 = None
        if self._resume_carry is not None \
                and t == self.resumed_from_checkpoint_t:
            # checkpoint resume: the decoded carry IS the state — no
            # host refit replay, no RNG restart; validated against the
            # config's carry structure first (numpy leaves feed the
            # kernel directly)
            with self.tracer.span("checkpoint.restore", t=int(t)):
                carry0 = self._validate_resume_carry(
                    self._resume_carry, _build_chunk_carry, t
                )
            self._resume_carry = None
        if carry0 is None:
            carry0 = _build_chunk_carry(t)

        if _g_limit(t) <= 0:
            self.history.done()
            return self.history
        # sqlite persistence moves to a writer thread: the host path per
        # chunk becomes fetch + dispatch, and appends overlap the next
        # chunk's device compute; history.done() flushes before returning
        self.history.start_async_writer()
        try:
            return engine.run(t, carry0, sims_total)
        except BaseException as exc:
            # drain queued generations before propagating — a mid-loop
            # failure (device error, interrupt) must not silently abandon
            # populations already handed to the writer
            try:
                self.history.flush()
            except Exception:
                # the original loop error propagates; the persist failure
                # stays sticky on the writer (re-raised by done()/close())
                # but must not pass without a trace
                logger.exception(
                    "async history writer also failed while draining"
                )
            if isinstance(exc, GracefulShutdown):
                # an EXTERNAL kill (SIGTERM/SIGINT) is made exactly as
                # recoverable as an injected one: the History is flushed
                # (above) and the newest healthy carry becomes a final
                # checkpoint before the signal propagates
                self._save_final_checkpoint()
            raise

    def _mirror_chunk_fit(self, last_pop) -> None:
        """Mirror a processed chunk's final population into the host
        proposal state (model probabilities + transition refits) — the
        state further chunks, resume and telemetry all derive from.
        Called by the dispatch engine after each chunk's processing."""
        self._model_probs = {
            m: float(last_pop.model_probabilities_array()[m])
            for m in last_pop.get_alive_models()
        }
        self._fit_transitions(last_pop)

    def _device_w_to_host(self, w_struct) -> np.ndarray:
        """Convert a fetched device weight-params structure into the host
        ``distance.weights`` dict value. SINGLE authority on the packing
        of the device params: sumstat-bearing distances ship
        {"w":..., "ss":...}; aggregated distances ship (w*factors,
        sub_params) and the host dict stores the factor-free weights."""
        if isinstance(w_struct, dict):
            return np.asarray(w_struct["w"], np.float64)
        if isinstance(w_struct, tuple):
            f = np.asarray(self.distance_function.factors, np.float64)
            comb = np.asarray(w_struct[0], np.float64)
            return np.where(f != 0, comb / np.where(f != 0, f, 1.0), 0.0)
        return np.asarray(w_struct, np.float64)

    def _mirror_fused_calibration(self, calib) -> None:
        """Mirror the first chunk's in-kernel calibration into the host
        components (resume / telemetry / config parity with the host
        calibration path)."""
        if self.eps.requires_calibration() and hasattr(self.eps, "_values"):
            self.eps._values[0] = float(np.asarray(calib["eps0"]))
        d = self.distance_function
        if d.requires_calibration():
            d.weights[0] = self._device_w_to_host(calib["w0"])

    def _process_chunk(self, fetched, ss_rows, t, g_limit, n_of, adaptive_n,
                       adaptive, stochastic, temp_fixed, eps_quantile,
                       sumstat_refit, chunk_index, chunk_s, dispatch_s,
                       fetch_s, fetch_depth, mem_telemetry, sims_total,
                       minimum_epsilon, max_nr_populations,
                       min_acceptance_rate, max_total_nr_simulations,
                       max_walltime, start_walltime):
        """Persist + host-mirror one fetched chunk's generations. Returns
        (stop, last_pop, last_sample, last_eps, last_acc_rate, t,
        sims_total, n_acc_chunk, g_done, health_fail).

        ``health_fail`` is None for a healthy chunk, else the FIRST
        generation whose in-kernel health word came back nonzero —
        nothing at or past that generation is persisted or mirrored
        (the caller rolls back and redispatches; a degraded population
        must never reach the History or the host component state)."""
        from ..sampler.base import Sample, exp_normalize_log_weights

        stop = False
        last_pop = last_sample = None
        last_eps = last_acc_rate = None
        n_acc_chunk = 0
        g_done = 0
        health_fail = None
        # the last complete generation of the chunk is known upfront from
        # the gen_ok flags: only ITS Sample/Population is built on this
        # thread (the cross-chunk transition refit / sumstat boundary
        # needs the object); earlier generations ship raw arrays + a
        # builder to the writer thread, so per-generation normalization
        # and Population construction overlap the next chunk's compute
        g_last_ok = -1
        for g in range(g_limit):
            if bool(fetched["gen_ok"][g]):
                g_last_ok = g
            else:
                break
        last_deferred = None  # newest deferred gen's (builder, eps, rate)
        for g in range(g_limit):
                # health gate FIRST — before gen_ok, before any persist:
                # a poisoned generation can look "complete" (acceptance
                # does not read the importance weights) or "incomplete"
                # (corrupt proposals never accept); either way the
                # recovery path owns it, not the stopping rules
                if "health" in fetched:
                    word = int(np.asarray(fetched["health"][g]))
                    if word != 0:
                        health_fail = {
                            "t": int(t), "g": int(g), "word": word,
                            "ess": float(np.asarray(fetched["ess"][g])),
                            "eps": float(fetched["eps_used"][g]),
                            "n_acc": int(fetched["n_acc"][g]),
                            "acc_rate": (
                                int(fetched["n_acc"][g])
                                / max(int(fetched["n_valid"][g]), 1)
                            ),
                        }
                        break
                # per-generation target (t advances below); in-kernel
                # adaptive n is read back from the chunk outputs
                n = (int(fetched["n_target"][g]) if adaptive_n
                     else n_of(t))
                if not bool(fetched["gen_ok"][g]):
                    logger.info(
                        "stopping: fused generation %d incomplete "
                        "(n_acc=%d/%d)", t, int(fetched["n_acc"][g]), n,
                    )
                    stop = True
                    break
                if ss_rows is None:
                    ss_raw = fetched["sumstats"][g][:n]
                elif g in ss_rows:
                    ss_raw = ss_rows[g][:n]
                else:
                    ss_raw = None

                def _build(ms=fetched["m"][g][:n],
                           thetas=fetched["theta"][g][:n],
                           log_w=fetched["log_weight"][g][:n],
                           dists=fetched["distance"][g][:n],
                           ss=ss_raw,
                           slots=fetched["slot"][g][:n]):
                    sample = Sample()
                    sample.set_accepted(
                        ms=ms,
                        thetas=np.asarray(thetas, np.float64),
                        weights=exp_normalize_log_weights(log_w),
                        distances=np.asarray(dists, np.float64),
                        sumstats=(np.asarray(ss, np.float64)
                                  if ss is not None else None),
                        proposal_ids=slots,
                    )
                    return sample, self._sample_to_population(sample)

                current_eps = float(fetched["eps_used"][g])
                nr_evals = int(fetched["n_valid"][g])
                self.sampler.nr_evaluations_ = nr_evals
                sims_total += nr_evals
                acceptance_rate = n / max(nr_evals, 1)
                n_acc_chunk += n
                refit_tel = {}
                if "refit" in fetched:
                    # mirror the in-kernel refit-cadence events into the
                    # observability subsystem + History telemetry: refit
                    # count, drift statistic and incremental-factorization
                    # occupancy are REPORTED quantities (bench `scale`
                    # lane: util.refits_per_run), not assumptions
                    refit_g = bool(fetched["refit"][g])
                    drift_g = float(fetched["drift"][g])
                    rows_g = int(fetched["rows_changed"][g])
                    self.refit_events.append((t, refit_g, drift_g, rows_g))
                    if refit_g:
                        self.metrics.counter(
                            "pyabc_tpu_refits_total",
                            "in-kernel proposal refits across fused "
                            "generations (cadence/drift/forced)",
                        ).inc()
                        self.metrics.counter(
                            "pyabc_tpu_refit_rows_changed_total",
                            "rows re-factorized by incremental refits",
                        ).inc(rows_g)
                    self.metrics.histogram(
                        "pyabc_tpu_refit_drift",
                        "acceptance-weighted proposal drift statistic "
                        "per fused generation",
                    ).observe(drift_g)
                    refit_tel = {"refit": refit_g,
                                 "drift": round(drift_g, 5),
                                 "refit_rows_changed": rows_g}
                if "retired" in fetched:
                    # early-reject accounting (ISSUE 15) rides the
                    # packed fetch — mirror it into the retired-lanes
                    # counter and the segment-occupancy gauge (global
                    # registry too: /api/observability reads it)
                    from ..observability import global_metrics
                    from ..observability.metrics import (
                        SIM_LANES_RETIRED_TOTAL,
                        SIM_SEGMENT_OCCUPANCY_GAUGE,
                    )

                    retired_g = int(fetched["retired"][g])
                    steps_g = int(fetched["seg_steps"][g])
                    slots_g = int(fetched["seg_lane_slots"][g])
                    occ_g = steps_g / max(slots_g, 1)
                    for reg in (self.metrics, global_metrics()):
                        reg.counter(
                            SIM_LANES_RETIRED_TOTAL,
                            "lanes retired between segments: provably-"
                            "rejected trajectories whose remaining "
                            "simulation work was skipped",
                        ).inc(retired_g)
                        reg.gauge(
                            SIM_SEGMENT_OCCUPANCY_GAUGE,
                            "productive segment-step share of lane "
                            "sweeps in the last fused generation",
                        ).set(occ_g)
                    resolved_g = int(fetched["seg_resolved"][g])
                    refit_tel = {**refit_tel,
                                 "retired_early": retired_g,
                                 "segment_occupancy": round(occ_g, 4),
                                 "seg_steps": steps_g,
                                 "seg_resolved": resolved_g}
                    if "retired_shard" in fetched:
                        # composed sharded+segmented chunks (ISSUE 17):
                        # the per-shard int32 columns ride the same
                        # packed fetch — split the retired counter and
                        # occupancy gauge per shard (suffix convention,
                        # cardinality = shard count) and ship both
                        # breakdowns in telemetry
                        ret_sh = [int(x) for x in
                                  np.asarray(fetched["retired_shard"][g])]
                        steps_sh = np.asarray(
                            fetched["seg_steps_shard"][g])
                        slots_sh = np.asarray(
                            fetched["seg_lane_slots_shard"][g])
                        occ_sh = [
                            round(float(st) / max(int(sl), 1), 4)
                            for st, sl in zip(steps_sh, slots_sh)
                        ]
                        for reg in (self.metrics, global_metrics()):
                            for i, (r_i, o_i) in enumerate(
                                    zip(ret_sh, occ_sh)):
                                reg.counter(
                                    f"{SIM_LANES_RETIRED_TOTAL}"
                                    f"_shard_{i}",
                                    "lanes retired early on this shard",
                                ).inc(r_i)
                                reg.gauge(
                                    f"{SIM_SEGMENT_OCCUPANCY_GAUGE}"
                                    f"_shard_{i}",
                                    "segment occupancy on this shard",
                                ).set(o_i)
                        refit_tel = {**refit_tel,
                                     "retired_per_shard": ret_sh,
                                     "segment_occupancy_per_shard":
                                         occ_sh}
                if g == g_last_ok or sumstat_refit:
                    last_sample, last_pop = _build()
                    last_eps, last_acc_rate = current_eps, acceptance_rate
                    pop_arg = last_pop
                else:
                    last_deferred = (_build, current_eps, acceptance_rate)
                    pop_arg = (lambda b=_build: b()[1])
                if self.history.columnar:
                    # columnar store: the packed-fetch arrays go to the
                    # History AS-IS (narrow dtypes, slot order) wrapped
                    # in a GenerationBatch — no Population round-trip
                    # for persistence; sort + weight normalization run
                    # on the writer thread and land bit-identical to
                    # the row store's values (the host-side last_pop
                    # above is still built where refits need it)
                    from ..storage.columnar import GenerationBatch

                    pop_arg = GenerationBatch.from_fetch(
                        ms=fetched["m"][g][:n],
                        thetas=fetched["theta"][g][:n],
                        log_weights=fetched["log_weight"][g][:n],
                        distances=fetched["distance"][g][:n],
                        sumstats=ss_raw,
                        slots=fetched["slot"][g][:n],
                        param_names=[list(s.names) for s in self._spaces()],
                    )
                self.history.append_population_async(
                    t, current_eps, pop_arg, nr_evals, self.model_names,
                    telemetry={
                        "fused_chunk": g_limit,
                        "chunk_index": chunk_index,
                        "chunk_s": round(chunk_s, 4),
                        "fetch_depth": int(fetch_depth),
                        "dispatch_s": round(dispatch_s, 4),
                        "fetch_s": round(fetch_s, 4),
                        "rounds": int(fetched["rounds"][g]),
                        "sample_s": round(chunk_s / g_limit, 4),
                        "n_evaluations": nr_evals,
                        "acceptance_rate": round(acceptance_rate, 6),
                        # sumstat-mode boundary refits are flagged AFTER
                        # they actually execute (the loop may stop at the
                        # chunk edge, where no refit happens and a resume
                        # must not restart the epsilon trail)
                        "distance_changed": bool(adaptive),
                        **refit_tel,
                        **(mem_telemetry if g == 0 else {}),
                        **self._fallbacks_telemetry(),
                        **self._sumstat_telemetry(),
                    },
                )
                logger.info(
                    "t: %d, eps: %.8g, acceptance rate: %.5f "
                    "(%d evaluations)", t, current_eps, acceptance_rate,
                    nr_evals,
                )
                # mirror the device-side adaptation into host state so
                # resume / further chunks / telemetry are consistent
                if eps_quantile:
                    self.eps._values[t + 1] = float(fetched["eps_next"][g])
                if stochastic:
                    # mirror the device temperature / pdf-norm recursions
                    # into the host objects (resume, config, telemetry) —
                    # except for a fixed ladder (ListTemperature), whose
                    # constructor-built dict is already authoritative and
                    # would be clobbered with chunk-clamped values
                    if not temp_fixed:
                        self.eps.temperatures[t + 1] = float(
                            fetched["eps_next"][g]
                        )
                    self.acceptor.pdf_norms[t + 1] = float(
                        fetched["pdf_norm_next"][g]
                    )
                    mf = float(fetched["max_found_next"][g])
                    if mf > -1e29:
                        self.acceptor._max_found = max(
                            self.acceptor._max_found, mf
                        )
                    if "daly_k_next" in fetched and hasattr(
                            self.eps, "_effective_schemes"):
                        for sch in self.eps._effective_schemes():
                            if type(sch).__name__ == "DalyScheme":
                                sch._k[t + 1] = float(
                                    fetched["daly_k_next"][g]
                                )
                if adaptive:
                    # slice generation g out of the stacked outputs, then
                    # unpack through the single packing authority
                    dwn = fetched["dist_w_next"]
                    if isinstance(dwn, dict):
                        w_g = {"w": dwn["w"][g]}
                    elif isinstance(dwn, tuple):
                        w_g = (dwn[0][g],)
                    else:
                        w_g = dwn[g]
                    self.distance_function.weights[t + 1] = \
                        self._device_w_to_host(w_g)
                plan = self._sumstat_device_plan
                if plan is not None and g == g_limit - 1 \
                        and min(int(fetched["n_acc"][g]), n) \
                        >= int(plan["need"]):
                    # the kernel's boundary learned-sumstat fit fired
                    # for this generation (the host evaluates the SAME
                    # predicate the in-kernel lax.cond did): mirror the
                    # fitted transform into the host predictor — resume-
                    # rebuilt carries, later host predicts and repr-
                    # level diagnostics must reflect the device fit
                    import jax as _jax

                    from ..observability import global_metrics
                    from ..observability.metrics import (
                        SUMSTAT_DIM_GAUGE,
                        SUMSTAT_DIM_REDUCED_GAUGE,
                        SUMSTAT_REFITS_TOTAL,
                    )
                    from ..sumstat.device import mirror_fitted_params

                    ssp_g = _jax.tree.map(
                        lambda v: np.asarray(v[g]),
                        fetched["dist_w_next"]["ss"],
                    )
                    mirror_fitted_params(
                        self.distance_function, ssp_g, t + 1)
                    for reg in (self.metrics, global_metrics()):
                        reg.counter(
                            SUMSTAT_REFITS_TOTAL,
                            "in-kernel learned-sumstat boundary refits "
                            "(device-fit plan runs)",
                        ).inc()
                        reg.gauge(
                            SUMSTAT_DIM_GAUGE,
                            "raw summary-statistic dimension S of the "
                            "learned-sumstat run",
                        ).set(float(self.spec.total_size))
                        reg.gauge(
                            SUMSTAT_DIM_REDUCED_GAUGE,
                            "learned feature dimension C' the packed "
                            "fetch ships per particle",
                        ).set(float(plan["out_dim"]))
                if adaptive_n:
                    # mirror the in-kernel bootstrap-CV decision into the
                    # host strategy (resume / post-loop host generations)
                    self.population_strategy.nr_particles = int(
                        fetched["n_next"][g]
                    )
                if hasattr(self.acceptor, "note_epsilon"):
                    self.acceptor.note_epsilon(t, current_eps, adaptive)
                # device-side model probabilities of this generation (the
                # stop_if_only_single_model_alive rule reads _model_probs)
                self._model_probs = {
                    m: float(p)
                    for m, p in enumerate(fetched["model_probs"][g])
                    if p > 0
                }
                g_done += 1
                if self._check_stop(t, current_eps, minimum_epsilon,
                                    max_nr_populations, acceptance_rate,
                                    min_acceptance_rate, sims_total,
                                    max_total_nr_simulations, max_walltime,
                                    start_walltime):
                    stop = True
                    break
                t += 1
        if last_pop is None and last_deferred is not None:
            # stopped (via _check_stop or a health failure) before
            # reaching the chunk's last complete generation: the newest
            # processed generation was deferred — build it now, the
            # caller's transition refit needs the actual Population
            builder, last_eps, last_acc_rate = last_deferred
            last_sample, last_pop = builder()
        return (stop, last_pop, last_sample, last_eps, last_acc_rate, t,
                sims_total, n_acc_chunk, g_done, health_fail)

    # --------------------------------------------- broker look-ahead path
    def _look_ahead_capable(self) -> bool:
        """Mid-generation look-ahead for the broker path (SURVEY §3.3:
        reference ``look_ahead_delay_evaluation``): gen t+1's proposal is
        built from PRELIMINARY gen-t particles while t still runs, and
        t+1's acceptance/weights are applied on the host once the final
        epsilon is known.

        Full delayed-evaluation semantics (the reference's
        ``look_ahead_delay_evaluation=True``): preliminary workers only
        SIMULATE — each particle ships its summary statistics, and the
        orchestrator recomputes distance AND acceptance once the updated
        distance (e.g. AdaptivePNormDistance's generation-t+1 weights)
        and the final epsilon exist. That is exactly what makes
        look-ahead legal for adaptive and t-scheduled distances; the
        particle's importance weight only depends on the proposal it was
        actually drawn from, which the preliminary closure records, so
        no weight correction is needed. ``_lookahead_recompute`` is set
        here: False for generation-invariant distances (recorded
        distance reused), True when the distance must be re-evaluated
        host-side at adoption time.

        FIXED-SCHEDULE noisy path (round 8, VERDICT r5 #3): a
        StochasticAcceptor ALSO rides look-ahead when nothing in its
        acceptance rule depends on the adopted generation's own records —
        temperature ladder fixed ahead of time (``ListTemperature``) and
        analytic pdf normalization (``pdf_norm_from_kernel``), with a
        static stochastic kernel (kernels never re-weight between
        generations). Delayed acceptance then applies the exact
        stochastic rule host-side via
        :meth:`StochasticAcceptor.delayed_accept_fn`, and the preliminary
        proposals ride the SAME variance guards as the uniform path
        (defensive prior mixture, builder-ESS floor, bandwidth widening —
        ``_build_lookahead_payload`` is acceptor-agnostic).

        Still excluded: ADAPTIVE StochasticAcceptor configs (pdf-norm
        feedback from records / Temperature schemes — delayed acceptance
        would need the full temperature recursion re-run host-side) and
        learned-sumstat distances (the feature transform refits between
        generations, so shipped raw statistics would need the new
        transform AND the scale refit — the fused loop owns that
        configuration)."""
        from ..acceptor import StochasticAcceptor
        from ..acceptor.pdf_norm import pdf_norm_from_kernel
        from ..broker.sampler import ElasticSampler
        from ..distance import AdaptivePNormDistance
        from ..distance.kernel import StochasticKernel
        from ..epsilon import ListTemperature

        self._lookahead_stochastic = False
        if not (isinstance(self.sampler, ElasticSampler)
                and self.sampler.look_ahead):
            return False
        if self.sampler.scheduling != "dynamic" \
                or self.sampler.wait_for_all_samples:
            # adopted generations run the dynamic collect-only protocol;
            # enabling look-ahead would silently override the user's
            # static quotas / complete-record guarantees
            return False
        d = self.distance_function
        if type(self.acceptor) is StochasticAcceptor:
            if not isinstance(self.eps, ListTemperature):
                return False
            if self.acceptor.pdf_norm_method is not pdf_norm_from_kernel:
                return False
            if not isinstance(d, StochasticKernel):
                return False
            # kernel value recorded at simulation time is reusable
            # (static kernel), so no host-side distance recompute
            self._lookahead_recompute = False
            self._lookahead_stochastic = True
            return True
        if type(self.acceptor) is not UniformAcceptor \
                or self.acceptor.use_complete_history:
            return False
        if type(d) is AdaptivePNormDistance and d.sumstat is None:
            self._lookahead_recompute = True
        elif type(d) is PNormDistance and d.sumstat is None:
            # t-scheduled user weights also ride delayed evaluation
            self._lookahead_recompute = any(k >= 0 for k in d.weights)
        else:
            return False
        return True

    def _build_lookahead_payload(self, t_next: int, particles):
        """Pickled PRELIMINARY ``simulate_one`` for generation ``t_next``,
        fitted on generation t's accepted-so-far particles. The closure
        simulates WITHOUT an accept test (``evaluate=False``) and weights
        each particle against the preliminary proposal it was drawn from —
        the sampler applies the delayed d <= eps(t_next) test on arrival.
        Returns None when the preliminary fit fails (the generation then
        proceeds without look-ahead)."""
        try:
            pop = Population.from_particles(
                list(particles), self._spaces(), self.spec,
                self.model_names,
            )
            # ESS guard: a preliminary KDE fit on a weight-degenerate
            # accepted-so-far set produces a proposal whose importance
            # ratios explode — the next generation's ESS then collapses
            # too. Running WITHOUT look-ahead is always statistically
            # sound, so degenerate builders simply skip it.
            w_all = np.asarray(pop.weights, np.float64)
            w_all = w_all / max(w_all.sum(), 1e-300)
            ess = 1.0 / max(float(np.sum(w_all * w_all)), 1e-300)
            if ess < self.lookahead_min_ess:
                logger.info(
                    "look-ahead for generation %d skipped: builder "
                    "ESS %.1f < %.1f (weight-degenerate preliminary "
                    "population)", t_next, ess, self.lookahead_min_ess,
                )
                return None
            probs_arr = pop.model_probabilities_array()
            prelim_probs = {
                m: float(probs_arr[m]) for m in pop.get_alive_models()
            }
            widen = float(self.lookahead_proposal_widen)
            alpha = float(self.lookahead_defensive_frac)
            prelim_transitions = []
            for m, tr in enumerate(self.transitions):
                cp = tr.copy_unfitted()
                # bandwidth widening (variance guard, see __init__):
                # scaling multiplies the KDE bandwidth on every stock
                # transition; custom transitions without it fit unwidened
                if widen != 1.0 and isinstance(
                        getattr(cp, "scaling", None), float):
                    cp.scaling = cp.scaling * widen
                if m in prelim_probs:
                    df, w = pop.get_distribution(m)
                    cp.fit(df, w)
                    if alpha > 0.0:
                        # defensive prior mixture: importance ratios of
                        # the adopted generation are bounded by 1/alpha
                        cp = DefensivePreliminaryTransition(
                            cp, self.parameter_priors[m], alpha
                        )
                prelim_transitions.append(cp)
            prior_probs = self.model_prior_probs
            K = self.K

            def model_prior_rvs() -> int:
                return int(np.random.choice(K, p=prior_probs))

            def model_prior_pmf(m: int) -> float:
                return float(prior_probs[m])

            inner = create_simulate_function(
                t_next,
                model_probabilities=prelim_probs,
                model_perturbation_kernel=self.model_perturbation_kernel,
                transitions=prelim_transitions,
                model_prior_rvs=model_prior_rvs,
                model_prior_pmf=model_prior_pmf,
                parameter_priors=self.parameter_priors,
                models=self.models,
                summary_statistics=self.summary_statistics,
                x_0=self.x_0,
                distance_function=self.distance_function,
                eps=self.eps,
                acceptor=self.acceptor,
                evaluate=False,
            )

            def simulate_one_preliminary(_inner=inner):
                p = _inner()
                p.preliminary = True
                return p

            import cloudpickle

            return cloudpickle.dumps(simulate_one_preliminary)
        except Exception:
            logger.exception(
                "look-ahead preliminary build failed; generation %d will "
                "run without look-ahead", t_next,
            )
            return None

    # ------------------------------------------------ speculative proposals
    def _speculation_capable(self) -> bool:
        """Look-ahead analog for UNFUSED device configs (reference
        ``redis_eps`` look_ahead, SURVEY.md §2.3): a full proposal round
        for generation t+1 is dispatched at eps=+inf as soon as the
        transitions are refit on generation t — i.e. BEFORE the slow
        strategy updates (ARS temperature bisection, epsilon quantile,
        acceptor norms) — and acceptance is applied on the host once the
        true threshold is known (delayed evaluation). Sound whenever the
        recorded per-lane distance is invariant under the pending strategy
        updates: the distance must not re-weight between generations and
        the acceptor must decide from (distance, eps) alone."""
        if not self._device_capable:
            return False
        if not isinstance(self.sampler, BatchedSampler):
            return False
        if np.isfinite(self.max_nr_recorded_particles):
            return False  # capped record retention: keep one record path
        d = self.distance_function
        # WHITELIST of generation-invariant distances: a plain p-norm
        # without weight schedules/sumstats, or a stochastic kernel (static
        # noise model). Anything adaptive (AdaptivePNormDistance,
        # AdaptiveAggregatedDistance, ...) re-weights between generations,
        # making speculative distances incomparable to the new threshold.
        static_pnorm = (
            type(d) is PNormDistance and d.sumstat is None
            and not any(k >= 0 for k in d.weights)
        )
        if not static_pnorm and not isinstance(d, StochasticKernel):
            return False
        a = self.acceptor
        if type(a) is UniformAcceptor and not a.use_complete_history:
            return True
        # StochasticAcceptor: the kernel value v is temperature-independent,
        # so acceptance can be applied on the host once T/pdf_norm are known
        return type(a) is StochasticAcceptor

    def _speculative_accept(self, t_next: int, fetched: dict):
        """Delayed acceptance for a speculative round, applied AFTER the
        strategy updates fixed generation t_next's threshold/temperature.
        Returns (accept_mask, extra_log_weight)."""
        valid = np.asarray(fetched["valid"], bool)
        d = np.asarray(fetched["distance"], np.float64)
        if type(self.acceptor) is StochasticAcceptor:
            from ..distance.kernel import SCALE_LIN

            logv = (np.log(np.maximum(d, 1e-300))
                    if self.distance_function.ret_scale == SCALE_LIN else d)
            norm = self.acceptor.pdf_norms[t_next]
            temp = self.eps(t_next)
            log_ratio = (logv - norm) / temp
            # keyed stream (seed, generation): the delayed acceptance must
            # stay reproducible like every other draw in the device path
            rng = np.random.default_rng((self.seed, t_next, 0x5BEC))
            u = rng.uniform(size=len(d))
            accept = valid & (np.log(np.maximum(u, 1e-300)) < log_ratio)
            extra = (np.clip(log_ratio, 0.0, None)
                     if self.acceptor.apply_importance_weighting
                     else np.zeros_like(d))
            return accept, extra
        accept = valid & (d <= self.eps(t_next))
        return accept, np.zeros_like(d)

    def _loop_pipelined(self, t0, minimum_epsilon, max_nr_populations,
                        min_acceptance_rate, max_total_nr_simulations,
                        max_walltime, start_walltime) -> History:
        """Cross-generation pipelined loop (the look-ahead analog) —
        delegated to the dispatch engine module
        (:func:`pyabc_tpu.inference.dispatch.run_pipelined`): generation
        t+1 is dispatched to the device as soon as the adaptive
        components are refit on generation t's final results,
        persistence overlaps the device's compute, and speculative
        eps=+inf rounds ride the slow strategy updates. Proposals always
        use FINAL generation-t weights, so the run is statistically
        identical to the serial loop."""
        from .dispatch import run_pipelined

        return run_pipelined(
            self, t0, minimum_epsilon, max_nr_populations,
            min_acceptance_rate, max_total_nr_simulations,
            max_walltime, start_walltime,
        )

    # -------------------------------------------------------- initialization
    def _initialize_components(self, max_nr_populations,
                               skip_calibration: bool = False) -> None:
        """Calibration generation + initialize(t=0) of all components
        (reference ABCSMC._initialize_dist_eps_acc).

        ``skip_calibration``: the fused loop runs calibration IN-KERNEL
        inside the first chunk (see ``_fused_calibration_cfg``); the
        components are initialized without a sample here, and the
        initial weights / eps_0 are mirrored from the chunk outputs."""
        if skip_calibration:
            self.distance_function.initialize(0, None, self.x_0)
            _call_filtered(
                self.acceptor.initialize,
                t=0, get_weighted_distances=None,
                distance_function=self.distance_function, x_0=self.x_0,
            )
            # eps.initialize is DEFERRED: a from-sample quantile epsilon
            # gets _values[0] from the first chunk's calibration output
            if not self.eps.requires_calibration():
                _call_filtered(
                    self.eps.initialize,
                    t=0, get_weighted_distances=None,
                    get_all_records=None,
                    max_nr_populations=(
                        int(max_nr_populations)
                        if np.isfinite(max_nr_populations) else None
                    ),
                    acceptor_config=self._acceptor_config(0),
                )
            return
        needs_calibration = (
            self.distance_function.requires_calibration()
            or self.eps.requires_calibration()
            or self.acceptor.requires_calibration()
        )
        calib_sample = None
        calib_distances = None
        if needs_calibration:
            n_calib = (
                self.population_strategy.nr_calibration_particles
                or self.population_strategy(0)
            )
            gen_spec = self._generation_spec(0, calibration=True)
            calib_sample = self.sampler.sample_until_n_accepted(
                n_calib, gen_spec, -1, all_accepted=True
            )
            all_ss = self._all_sumstats_provider(calib_sample)
            self.distance_function.initialize(0, all_ss, self.x_0)
            # distances under the (possibly just-calibrated) distance;
            # one coerced host fetch (row-wise indexing of a device ring
            # would be one RPC per row over a TPU tunnel)
            ss_mat = np.asarray(all_ss(), np.float64)
            batch = getattr(self.distance_function, "host_batch", None)
            calib_distances = batch(
                ss_mat, self.spec.flatten_host(self.x_0), 0
            ) if batch is not None else None
            if calib_distances is None:
                calib_distances = np.asarray([
                    self.distance_function(
                        self.spec.unflatten(ss_mat[i]), self.x_0, 0
                    )
                    for i in range(ss_mat.shape[0])
                ])
        else:
            self.distance_function.initialize(0, None, self.x_0)

        import pandas as pd

        def get_wd():
            if calib_distances is None:
                raise RuntimeError("epsilon needs a calibration sample")
            return pd.DataFrame({
                "distance": calib_distances,
                "w": np.full(len(calib_distances), 1.0 / len(calib_distances)),
            })

        def get_records():
            if calib_distances is None:
                return None
            return pd.DataFrame({
                "distance": calib_distances,
                "accepted": np.ones(len(calib_distances), bool),
            })

        _call_filtered(
            self.acceptor.initialize,
            t=0,
            get_weighted_distances=(
                get_wd if calib_distances is not None else None
            ),
            distance_function=self.distance_function,
            x_0=self.x_0,
        )
        _call_filtered(
            self.eps.initialize,
            t=0,
            get_weighted_distances=(
                get_wd if calib_distances is not None else None
            ),
            get_all_records=get_records,
            max_nr_populations=(
                int(max_nr_populations)
                if np.isfinite(max_nr_populations) else None
            ),
            acceptor_config=self._acceptor_config(0),
        )

    def _restore_state(self, t_last: int,
                       max_nr_populations: float = np.inf) -> None:
        """Rebuild model probs + transitions from the stored last population
        (reference resume caveat §5.4: adaptive internal state is
        reconstructed, not serialized)."""
        probs_df = self.history.get_model_probabilities(t_last)
        self._model_probs = {
            int(m): float(p) for m, p in probs_df["p"].items() if p > 0
        }
        # re-initialize distance/acceptor from the stored population's
        # sum stats (adaptive internal state is reconstructed, not serialized)
        _, stats = self.history.get_weighted_sum_stats(t_last)
        self.distance_function.initialize(
            t_last + 1, (lambda: stats), self.x_0
        )
        wd0 = self.history.get_weighted_distances(t_last)
        _call_filtered(
            self.acceptor.initialize,
            t=t_last + 1, get_weighted_distances=lambda: wd0,
            distance_function=self.distance_function, x_0=self.x_0,
        )
        # replay the epsilon trail from the stored populations so the
        # complete-history acceptor resumes with the SAME historic minimum
        # it would have had in an uninterrupted run. Each generation's
        # telemetry records whether the distance changed AFTER it (the
        # live loops write "distance_changed"); dbs from before that
        # column fall back to the conservative may-change rule.
        if hasattr(self.acceptor, "note_epsilon"):
            fallback = self._distance_may_change()

            def _changed_after(t_row: int) -> bool:
                tel = self.history.get_telemetry(t_row)
                return bool(tel.get("distance_changed", fallback))

            pops = self.history.get_all_populations().query("t >= 0")
            for t_row, eps_row in zip(pops["t"], pops["epsilon"]):
                if t_row <= t_last and np.isfinite(eps_row):
                    restart = _changed_after(int(t_row) - 1) \
                        if t_row > 0 else False
                    self.acceptor.note_epsilon(
                        int(t_row), float(eps_row), restart)
            # the resumed loop's FIRST note_epsilon must see whether the
            # distance changed after the last stored generation
            self._resumed_distance_changed = _changed_after(t_last)
        for m in self._model_probs:
            df, w = self.history.get_distribution(m, t_last)
            df = df[[c for c in df.columns if c != "pid"]]
            try:
                self.transitions[m].fit(df, w)
            except NotEnoughParticles:
                pass
        # re-seed epsilon from the stored population's distances, RECOMPUTED
        # under the just-re-initialized distance — stored values were computed
        # with the previous weighting and would mis-scale the threshold
        import pandas as pd

        wd = self.history.get_weighted_distances(t_last)
        ws, stats_mat = self.history.get_weighted_sum_stats(t_last)
        if stats_mat.shape[1] != self.spec.total_size:
            # device-native learned-sumstat generations persist
            # TRANSFORMED C'-dim rows (ISSUE 20); the raw-space
            # recompute is impossible without the fitted transform AND
            # unnecessary — the stored distances were computed in the
            # transformed space the accept test ran in, so they
            # re-seed the threshold as-is
            wd = pd.DataFrame({
                "distance": wd["distance"].to_numpy(),
                "w": ws / ws.sum(),
            })
        else:
            new_d = np.asarray([
                self.distance_function(
                    self.spec.unflatten(stats_mat[i]), self.x_0, t_last + 1
                )
                for i in range(stats_mat.shape[0])
            ])
            wd = pd.DataFrame({"distance": new_d, "w": ws / ws.sum()})
        from ..epsilon import QuantileEpsilon

        if isinstance(self.eps, QuantileEpsilon):
            # a float initial_epsilon is a fresh-start value; on resume the
            # threshold must come from the stored population's distances
            self.eps.update(t_last + 1, get_weighted_distances=lambda: wd)
        else:
            _call_filtered(
                self.eps.initialize,
                t=t_last + 1,
                get_weighted_distances=lambda: wd,
                max_nr_populations=(
                    int(max_nr_populations)
                    if np.isfinite(max_nr_populations) else None
                ),
                acceptor_config=self._acceptor_config(t_last + 1),
            )
