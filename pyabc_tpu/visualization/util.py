"""Shared helpers for the visualization module.

Reference parity: ``pyabc/visualization/util.py`` (histories/labels
normalization helpers `to_lists`, `get_labels`).
"""
from __future__ import annotations

from ..storage.history import History


def to_lists(histories, labels=None):
    """Normalize (history|list, labels|None) -> (list, list) (reference
    to_lists/get_labels)."""
    if isinstance(histories, History):
        histories = [histories]
    histories = list(histories)
    if labels is None:
        labels = [f"run {h.id}" for h in histories]
    elif isinstance(labels, str):
        labels = [labels]
    if len(labels) != len(histories):
        raise ValueError("labels and histories must have equal length")
    return histories, labels


def get_figure(ax=None, size=None):
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots()
    else:
        fig = ax.get_figure()
    if size is not None:
        fig.set_size_inches(size)
    return fig, ax
