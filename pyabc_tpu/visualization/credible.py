"""Credible interval plots.

Reference parity: ``pyabc/visualization/credible.py::{plot_credible_intervals,
plot_credible_intervals_for_time}`` — weighted posterior quantile bands per
generation.
"""
from __future__ import annotations

import numpy as np

from ..core.weighted_statistics import weighted_quantile
from .util import get_figure


def compute_credible_interval(vals, weights, level: float = 0.95):
    """(lb, ub) weighted central credible interval (reference
    compute_credible_interval)."""
    alpha_lb = 0.5 * (1 - level)
    lb = weighted_quantile(vals, weights, alpha=alpha_lb)
    ub = weighted_quantile(vals, weights, alpha=1 - alpha_lb)
    return lb, ub


def plot_credible_intervals(history, m: int = 0, ts=None, par_names=None,
                            levels=(0.95,), show_mean: bool = True,
                            refval=None, refval_color="C1", size=None,
                            arr_ax=None):
    """Credible interval trajectories over generations
    (reference plot_credible_intervals)."""
    import matplotlib.pyplot as plt

    if ts is None:
        ts = list(range(history.max_t + 1))
    df0, _ = history.get_distribution(m=m, t=ts[-1])
    if par_names is None:
        par_names = list(df0.columns)
    n_par = len(par_names)
    if arr_ax is None:
        fig, arr_ax = plt.subplots(n_par, 1, squeeze=False)
        arr_ax = [a[0] for a in arr_ax]
        if size is not None:
            fig.set_size_inches(size)
    levels = sorted(levels)
    for i, par in enumerate(par_names):
        ax = arr_ax[i]
        means = []
        bands = {lv: ([], []) for lv in levels}
        for t in ts:
            df, w = history.get_distribution(m=m, t=t)
            vals = np.asarray(df[par], np.float64)
            means.append(float(np.sum(w * vals)))
            for lv in levels:
                lb, ub = compute_credible_interval(vals, w, lv)
                bands[lv][0].append(lb)
                bands[lv][1].append(ub)
        for lv in levels:
            ax.fill_between(ts, bands[lv][0], bands[lv][1], alpha=0.3,
                            label=f"{lv:.0%} CI")
        if show_mean:
            ax.plot(ts, means, "x-", label="mean")
        if refval is not None:
            ax.axhline(refval[par], color=refval_color, linestyle="dotted",
                       label="reference")
        ax.set_ylabel(par)
        ax.legend()
    arr_ax[-1].set_xlabel("population index t")
    return arr_ax


def plot_credible_intervals_for_time(histories, m: int = 0, t=None,
                                     par_names=None, levels=(0.95,),
                                     labels=None, size=None, arr_ax=None):
    """Credible intervals of multiple runs at one generation (reference
    plot_credible_intervals_for_time)."""
    import matplotlib.pyplot as plt

    from .util import to_lists

    histories, labels = to_lists(histories, labels)
    df0, _ = histories[0].get_distribution(m=m, t=t)
    if par_names is None:
        par_names = list(df0.columns)
    n_par = len(par_names)
    if arr_ax is None:
        fig, arr_ax = plt.subplots(n_par, 1, squeeze=False)
        arr_ax = [a[0] for a in arr_ax]
        if size is not None:
            fig.set_size_inches(size)
    for i, par in enumerate(par_names):
        ax = arr_ax[i]
        for j, (h, lab) in enumerate(zip(histories, labels)):
            df, w = h.get_distribution(m=m, t=t)
            vals = np.asarray(df[par], np.float64)
            mean = float(np.sum(w * vals))
            for lv in sorted(levels):
                lb, ub = compute_credible_interval(vals, w, lv)
                ax.plot([j, j], [lb, ub], "-", lw=2, alpha=0.6)
            ax.plot([j], [mean], "o")
        ax.set_xticks(range(len(histories)))
        ax.set_xticklabels(labels)
        ax.set_ylabel(par)
    return arr_ax
