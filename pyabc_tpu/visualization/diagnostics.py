"""Run-diagnostic plots over History.

Reference parity: ``pyabc/visualization/{epsilon,sample,model_probabilities,
effective_sample_size,walltime,distance}.py`` — plot_epsilons,
plot_sample_numbers(_trajectory), plot_acceptance_rates_trajectory,
plot_model_probabilities, plot_effective_sample_sizes, plot_total_walltime,
plot_walltime, plot_distance_weights.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from ..core.weighted_statistics import effective_sample_size
from .util import get_figure, to_lists


def plot_epsilons(histories, labels=None, colors=None, scale: str = "lin",
                  ax=None, size=None):
    """Epsilon trajectory per run (reference plot_epsilons)."""
    histories, labels = to_lists(histories, labels)
    fig, ax = get_figure(ax, size)
    for h, lab in zip(histories, labels):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        eps = pops["epsilon"].to_numpy()
        if scale == "log":
            eps = np.log10(np.maximum(eps, 1e-300))
        ax.plot(pops["t"], eps, "x-", label=lab)
    ax.set_xlabel("population index t")
    ax.set_ylabel("epsilon" if scale == "lin" else "log10(epsilon)")
    ax.legend()
    return ax


def plot_sample_numbers(histories, labels=None, rotation: int = 0, ax=None,
                        size=None):
    """Stacked bar of simulations per generation (reference
    plot_sample_numbers)."""
    histories, labels = to_lists(histories, labels)
    fig, ax = get_figure(ax, size)
    width = 0.8 / len(histories)
    for i, (h, lab) in enumerate(zip(histories, labels)):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        ax.bar(pops["t"] + i * width, pops["samples"], width=width, label=lab)
    ax.set_xlabel("population index t")
    ax.set_ylabel("simulations")
    ax.legend()
    return ax


def plot_sample_numbers_trajectory(histories, labels=None, yscale="lin",
                                   ax=None, size=None):
    histories, labels = to_lists(histories, labels)
    fig, ax = get_figure(ax, size)
    for h, lab in zip(histories, labels):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        ax.plot(pops["t"], pops["samples"], "x-", label=lab)
    if yscale == "log":
        ax.set_yscale("log")
    ax.set_xlabel("population index t")
    ax.set_ylabel("simulations")
    ax.legend()
    return ax


def plot_acceptance_rates_trajectory(histories, labels=None, ax=None,
                                     size=None, normalize_by_ess=False):
    """Acceptance rate (n_particles / n_simulations) per generation
    (reference plot_acceptance_rates_trajectory)."""
    histories, labels = to_lists(histories, labels)
    fig, ax = get_figure(ax, size)
    for h, lab in zip(histories, labels):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        nrs = h.get_nr_particles_per_population()
        rates = []
        for t, samples in zip(pops["t"], pops["samples"]):
            n = nrs.get(t, 0)
            rates.append(n / samples if samples else np.nan)
        ax.plot(pops["t"], rates, "x-", label=lab)
    ax.set_xlabel("population index t")
    ax.set_ylabel("acceptance rate")
    ax.legend()
    return ax


def plot_model_probabilities(history, rotation: int = 0, ax=None, size=None):
    """Bar plot of p(m | t) over generations (reference
    plot_model_probabilities)."""
    fig, ax = get_figure(ax, size)
    probs = history.get_model_probabilities()
    probs.plot.bar(ax=ax, rot=rotation)
    ax.set_ylabel("model probability")
    ax.set_xlabel("population index t")
    return ax


def plot_effective_sample_sizes(histories, labels=None, rotation: int = 0,
                                relative: bool = False, ax=None, size=None):
    """ESS of the weighted population per generation (reference
    plot_effective_sample_sizes)."""
    histories, labels = to_lists(histories, labels)
    fig, ax = get_figure(ax, size)
    for h, lab in zip(histories, labels):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        esss = []
        for t in pops["t"]:
            wd = h.get_weighted_distances(t)
            w = np.asarray(wd["w"], np.float64)
            ess = effective_sample_size(w)
            if relative:
                ess /= len(w)
            esss.append(ess)
        ax.plot(pops["t"], esss, "x-", label=lab)
    ax.set_xlabel("population index t")
    ax.set_ylabel("effective sample size" + (" (relative)" if relative else ""))
    ax.legend()
    return ax


def plot_total_walltime(histories, labels=None, unit: str = "s", rotation=0,
                        ax=None, size=None):
    """Total run walltime bar per history (reference plot_total_walltime)."""
    histories, labels = to_lists(histories, labels)
    fig, ax = get_figure(ax, size)
    factor = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}[unit]
    totals = []
    for h in histories:
        pops = h.get_all_populations()
        times = pd.to_datetime(pops["population_end_time"])
        totals.append((times.max() - times.min()).total_seconds() / factor)
    ax.bar(np.arange(len(histories)), totals)
    ax.set_xticks(np.arange(len(histories)))
    ax.set_xticklabels(labels, rotation=rotation)
    ax.set_ylabel(f"total walltime [{unit}]")
    return ax


def plot_walltime(histories, labels=None, unit: str = "s", rotation=0,
                  ax=None, size=None):
    """Per-generation walltime (reference plot_walltime)."""
    histories, labels = to_lists(histories, labels)
    fig, ax = get_figure(ax, size)
    factor = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}[unit]
    for h, lab in zip(histories, labels):
        pops = h.get_all_populations()
        times = pd.to_datetime(pops["population_end_time"])
        ts = pops["t"].to_numpy()
        if len(times) < 2:
            continue
        durations = (times.diff().dt.total_seconds().to_numpy()[1:] / factor)
        ax.plot(ts[1:], durations, "x-", label=lab)
    ax.set_xlabel("population index t")
    ax.set_ylabel(f"walltime [{unit}]")
    ax.legend()
    return ax


def plot_eps_walltime(histories, labels=None, unit: str = "s",
                      ax=None, size=None, yscale: str = "log"):
    """Epsilon against CUMULATIVE walltime (reference plot_eps_walltime):
    the convergence-per-compute view used to compare samplers."""
    histories, labels = to_lists(histories, labels)
    fig, ax = get_figure(ax, size)
    factor = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}[unit]
    for h, lab in zip(histories, labels):
        all_pops = h.get_all_populations()
        # anchor at the run start (the t=-1 calibration row, like
        # plot_total_walltime) so generation 0's cost is visible
        t0 = pd.to_datetime(all_pops["population_end_time"]).min()
        pops = all_pops.query("t >= 0")
        times = pd.to_datetime(pops["population_end_time"])
        cum = (times - t0).dt.total_seconds().to_numpy() / factor
        ax.plot(cum, pops["epsilon"].to_numpy(), "x-", label=lab)
    ax.set_xlabel(f"cumulative walltime [{unit}]")
    ax.set_ylabel("epsilon")
    if yscale:
        ax.set_yscale(yscale)
    ax.legend()
    return ax


def plot_distance_weights(distance, t=None, labels=None, ax=None, size=None,
                          **kwargs):
    """Per-statistic weights of an adaptive distance (reference
    plot_distance_weights)."""
    fig, ax = get_figure(ax, size)
    weights = getattr(distance, "weights", None)
    if not weights:
        raise ValueError("distance carries no per-generation weights")
    ts = sorted(k for k in weights if k >= 0) if t is None else [t]
    spec = getattr(distance, "spec", None)
    names = spec.labels() if spec is not None else None
    for s in ts:
        w = np.asarray(weights[s])
        xs = np.arange(len(w))
        ax.plot(xs, w, "x-", label=f"t={s}", **kwargs)
    if names is not None:
        ax.set_xticks(np.arange(len(names)))
        ax.set_xticklabels(names, rotation=90)
    ax.set_ylabel("weight")
    ax.legend()
    return ax
