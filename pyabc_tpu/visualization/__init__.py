"""Visualization over History — reference plot API (pyabc/visualization/)."""
from .credible import (
    compute_credible_interval,
    plot_credible_intervals,
    plot_credible_intervals_for_time,
)
from .diagnostics import (
    plot_acceptance_rates_trajectory,
    plot_distance_weights,
    plot_effective_sample_sizes,
    plot_eps_walltime,
    plot_epsilons,
    plot_model_probabilities,
    plot_sample_numbers,
    plot_sample_numbers_trajectory,
    plot_total_walltime,
    plot_walltime,
)
from .data import plot_data_callback, plot_data_default
from .sensitivity import plot_sensitivity_sankey
from .histogram import (
    plot_histogram_1d,
    plot_histogram_2d,
    plot_histogram_matrix,
)
from .kde import (
    kde_1d,
    kde_2d,
    plot_kde_1d,
    plot_kde_1d_highlevel,
    plot_kde_2d,
    plot_kde_2d_highlevel,
    plot_kde_matrix,
    plot_kde_matrix_highlevel,
)

__all__ = [
    "kde_1d", "kde_2d", "plot_kde_1d", "plot_kde_1d_highlevel",
    "plot_kde_2d", "plot_kde_2d_highlevel", "plot_kde_matrix",
    "plot_kde_matrix_highlevel",
    "plot_histogram_1d", "plot_histogram_2d", "plot_histogram_matrix",
    "plot_epsilons", "plot_sample_numbers", "plot_sample_numbers_trajectory",
    "plot_acceptance_rates_trajectory", "plot_model_probabilities",
    "plot_effective_sample_sizes", "plot_total_walltime", "plot_walltime",
    "plot_eps_walltime",
    "plot_distance_weights",
    "plot_sensitivity_sankey",
    "plot_data_default", "plot_data_callback",
    "compute_credible_interval", "plot_credible_intervals",
    "plot_credible_intervals_for_time",
]
