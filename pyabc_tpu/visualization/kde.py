"""Weighted KDE plots over posterior samples.

Reference parity: ``pyabc/visualization/kde.py::{kde_1d, plot_kde_1d,
plot_kde_1d_highlevel, kde_2d, plot_kde_2d, plot_kde_2d_highlevel,
plot_kde_matrix, plot_kde_matrix_highlevel}`` — weighted gaussian KDE on a
grid from (DataFrame, weights), with the same (df, w, x, ...) signatures so
reference plotting code ports unchanged.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from .util import get_figure


def _weighted_gaussian_kde(values: np.ndarray, weights: np.ndarray,
                           grid: np.ndarray, bw_factor: float = 1.0):
    """1-D weighted gaussian KDE evaluated on ``grid`` (Silverman bw)."""
    w = weights / weights.sum()
    ess = 1.0 / np.sum(w**2)
    mu = np.sum(w * values)
    sd = np.sqrt(np.sum(w * (values - mu) ** 2))
    if sd <= 0:
        sd = max(abs(mu) * 1e-2, 1e-2)
    bw = bw_factor * sd * ess ** (-1.0 / 5.0)
    bw = max(bw, 1e-12)
    z = (grid[:, None] - values[None, :]) / bw
    dens = (np.exp(-0.5 * z * z) @ w) / (bw * np.sqrt(2 * np.pi))
    return dens


def kde_1d(df: pd.DataFrame, w: np.ndarray, x: str, xmin=None, xmax=None,
           numx: int = 50, kde=None):
    """(grid, density) for parameter ``x`` (reference kde_1d)."""
    values = np.asarray(df[x], np.float64)
    if xmin is None:
        xmin = values.min()
    if xmax is None:
        xmax = values.max()
    if xmax <= xmin:
        xmin, xmax = xmin - 0.5, xmax + 0.5
    grid = np.linspace(xmin, xmax, numx)
    dens = _weighted_gaussian_kde(values, np.asarray(w, np.float64), grid)
    return grid, dens


def plot_kde_1d(df, w, x, xmin=None, xmax=None, numx=50, ax=None, size=None,
                refval=None, refval_color="C1", kde=None, label=None,
                **kwargs):
    fig, ax = get_figure(ax, size)
    grid, dens = kde_1d(df, w, x, xmin, xmax, numx, kde)
    ax.plot(grid, dens, label=label, **kwargs)
    ax.set_xlabel(x)
    ax.set_ylabel("posterior density")
    if refval is not None:
        ax.axvline(refval[x] if isinstance(refval, dict) else refval,
                   color=refval_color, linestyle="dotted")
    return ax


def plot_kde_1d_highlevel(history, x, m=0, t=None, **kwargs):
    df, w = history.get_distribution(m=m, t=t)
    return plot_kde_1d(df, w, x, **kwargs)


def kde_2d(df, w, x, y, xmin=None, xmax=None, ymin=None, ymax=None,
           numx: int = 50, numy: int = 50, kde=None):
    """(X, Y, PDF) meshgrid for parameters x, y (reference kde_2d)."""
    xv = np.asarray(df[x], np.float64)
    yv = np.asarray(df[y], np.float64)
    ww = np.asarray(w, np.float64)
    ww = ww / ww.sum()
    xmin = xv.min() if xmin is None else xmin
    xmax = xv.max() if xmax is None else xmax
    ymin = yv.min() if ymin is None else ymin
    ymax = yv.max() if ymax is None else ymax
    if xmax <= xmin:
        xmin, xmax = xmin - 0.5, xmax + 0.5
    if ymax <= ymin:
        ymin, ymax = ymin - 0.5, ymax + 0.5
    gx = np.linspace(xmin, xmax, numx)
    gy = np.linspace(ymin, ymax, numy)
    ess = 1.0 / np.sum(ww**2)
    factor = ess ** (-1.0 / 6.0)  # silverman d=2

    def bw(v):
        mu = np.sum(ww * v)
        sd = np.sqrt(np.sum(ww * (v - mu) ** 2))
        return max(sd * factor, 1e-12)

    bx, by = bw(xv), bw(yv)
    zx = (gx[:, None] - xv[None, :]) / bx
    zy = (gy[:, None] - yv[None, :]) / by
    kx = np.exp(-0.5 * zx * zx) / (bx * np.sqrt(2 * np.pi))  # (numx, n)
    ky = np.exp(-0.5 * zy * zy) / (by * np.sqrt(2 * np.pi))  # (numy, n)
    pdf = np.einsum("xn,yn,n->yx", kx, ky, ww)
    X, Y = np.meshgrid(gx, gy)
    return X, Y, pdf


def plot_kde_2d(df, w, x, y, xmin=None, xmax=None, ymin=None, ymax=None,
                numx=50, numy=50, ax=None, size=None, colorbar=True,
                title=True, refval=None, refval_color="C1", kde=None,
                **kwargs):
    fig, ax = get_figure(ax, size)
    X, Y, PDF = kde_2d(df, w, x, y, xmin, xmax, ymin, ymax, numx, numy, kde)
    mesh = ax.pcolormesh(X, Y, PDF, shading="auto", **kwargs)
    if colorbar:
        fig.colorbar(mesh, ax=ax)
    ax.set_xlabel(x)
    ax.set_ylabel(y)
    if title:
        ax.set_title("posterior KDE")
    if refval is not None:
        ax.scatter([refval[x]], [refval[y]], color=refval_color, marker="x")
    return ax


def plot_kde_2d_highlevel(history, x, y, m=0, t=None, **kwargs):
    df, w = history.get_distribution(m=m, t=t)
    return plot_kde_2d(df, w, x, y, **kwargs)


def plot_kde_matrix(df, w, limits=None, colorbar=True, refval=None,
                    refval_color="C1", kde=None, names=None, size=None):
    """Matrix of 1d KDEs (diagonal) and 2d KDEs (off-diagonal)
    (reference plot_kde_matrix)."""
    import matplotlib.pyplot as plt

    if names is None:
        names = list(df.columns)
    n = len(names)
    fig, axes = plt.subplots(n, n, squeeze=False)
    if size is not None:
        fig.set_size_inches(size)
    limits = limits or {}
    for i, yi in enumerate(names):
        for j, xj in enumerate(names):
            ax = axes[i][j]
            if i == j:
                xmin, xmax = limits.get(xj, (None, None))
                plot_kde_1d(df, w, xj, xmin=xmin, xmax=xmax, ax=ax,
                            refval=refval, refval_color=refval_color)
            elif i > j:
                xmin, xmax = limits.get(xj, (None, None))
                ymin, ymax = limits.get(yi, (None, None))
                plot_kde_2d(df, w, xj, yi, xmin=xmin, xmax=xmax, ymin=ymin,
                            ymax=ymax, ax=ax, colorbar=False, title=False,
                            refval=refval, refval_color=refval_color)
            else:
                ax.axis("off")
            if i < n - 1:
                ax.set_xlabel("")
            if j > 0:
                ax.set_ylabel("")
    fig.tight_layout()
    return axes


def plot_kde_matrix_highlevel(history, m=0, t=None, **kwargs):
    df, w = history.get_distribution(m=m, t=t)
    return plot_kde_matrix(df, w, **kwargs)
