"""Observed-vs-simulated data comparison plots.

Reference parity: ``pyabc/visualization/data.py::{plot_data_default,
plot_data_callback}`` — quick visual goodness-of-fit checks: one panel per
summary statistic, observed data against one or many simulated datasets.
"""
from __future__ import annotations

import numpy as np


def _panel_grid(n: int):
    import matplotlib.pyplot as plt

    ncols = int(np.ceil(np.sqrt(n)))
    nrows = int(np.ceil(n / ncols))
    fig, axes = plt.subplots(nrows, ncols, squeeze=False,
                             figsize=(4 * ncols, 3 * nrows))
    flat = [ax for row in axes for ax in row]
    for ax in flat[n:]:
        ax.set_axis_off()
    return fig, flat[:n]


def _as_arrays(data: dict) -> dict:
    return {k: np.atleast_1d(np.asarray(v, np.float64))
            for k, v in data.items()}


def plot_data_default(obs_data: dict, sim_data: dict | list[dict],
                      keys=None):
    """One panel per summary statistic: observed (thick) vs simulated
    (thin); vector statistics as index-plots, scalars as paired bars.
    ``sim_data`` may be a single dict or a list of dicts (e.g. posterior
    predictive draws). Returns the axes array."""
    sims = sim_data if isinstance(sim_data, list) else [sim_data]
    obs = _as_arrays(obs_data)
    sims = [_as_arrays(s) for s in sims]
    if keys is None:
        keys = list(obs.keys())
    fig, axes = _panel_grid(len(keys))
    for ax_, key in zip(axes, keys):
        y0 = obs[key]
        if y0.size == 1:
            vals = [float(s[key][0]) for s in sims if key in s]
            ax_.bar(["observed"] + [f"sim {i}" for i in range(len(vals))],
                    [float(y0[0])] + vals)
        else:
            for i, s in enumerate(sims):
                if key in s:
                    ax_.plot(s[key], color="C1", alpha=0.6, lw=1,
                             label="simulated" if i == 0 else None)
            ax_.plot(y0, color="C0", lw=2.5, label="observed")
            ax_.legend()
        ax_.set_title(key)
    return axes


def plot_data_callback(obs_data: dict, sim_data: dict | list[dict],
                       f_plot, f_plot_aggregated=None, keys=None):
    """Per-statistic user callback ``f_plot(key, obs_array, sim_arrays,
    ax)``; optional ``f_plot_aggregated(obs_data, sim_data, ax)`` gets one
    extra panel at the end (reference plot_data_callback contract)."""
    sims = sim_data if isinstance(sim_data, list) else [sim_data]
    obs = _as_arrays(obs_data)
    sims_arr = [_as_arrays(s) for s in sims]
    if keys is None:
        keys = list(obs.keys())
    n = len(keys) + (1 if f_plot_aggregated is not None else 0)
    fig, axes = _panel_grid(n)
    for ax_, key in zip(axes, keys):
        f_plot(key, obs[key], [s[key] for s in sims_arr if key in s], ax_)
        ax_.set_title(key)
    if f_plot_aggregated is not None:
        f_plot_aggregated(obs_data, sim_data, axes[-1])
    return axes
