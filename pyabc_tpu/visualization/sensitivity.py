"""Sensitivity flow plot (reference parity:
``pyabc/visualization/sankey.py::plot_sensitivity_sankey``).

Visualizes how strongly each summary statistic informs each parameter,
from the fitted regression matrix of a learned-summary-statistics
predictor (Fearnhead-Prangle; see ``pyabc_tpu.predictor``). The reference
draws a plotly Sankey; plotly is not available here, so the same
two-column flow diagram is drawn with matplotlib ribbons — statistic
nodes on the left, parameter nodes on the right, ribbon width
proportional to |W[s, p]| on standardized inputs.
"""
from __future__ import annotations

import numpy as np

from .util import get_figure


def _sensitivity_matrix(source) -> np.ndarray:
    """(S, d) absolute sensitivity matrix from a PredictorSumstat /
    Predictor / raw matrix."""
    pred = getattr(source, "predictor", source)
    for attr in ("_W", "W"):
        W = getattr(pred, attr, None)
        if W is not None:
            return np.abs(np.asarray(W, np.float64))
    from ..predictor import Predictor

    if isinstance(pred, Predictor):
        raise ValueError(
            f"{type(pred).__name__} carries no linear sensitivity matrix "
            "(not fitted, or a non-linear predictor) — pass a raw (S, d) "
            "matrix, e.g. finite-difference sensitivities of .predict"
        )
    W = np.abs(np.asarray(source, np.float64))
    if W.ndim != 2:
        raise ValueError(
            f"sensitivity matrix must be 2-d (S, d), got shape {W.shape}"
        )
    return W


def plot_sensitivity_sankey(source, sumstat_labels=None, par_labels=None,
                            ax=None, size=None, min_frac: float = 0.01,
                            cmap: str = "tab10"):
    """Two-column sensitivity flow: statistics (left) -> parameters (right).

    ``source``: a fitted ``PredictorSumstat``/``Predictor`` (its regression
    matrix is used) or a raw (S, d) sensitivity matrix. Ribbons thinner
    than ``min_frac`` of the LARGEST flow are dropped for readability.
    """
    import matplotlib.pyplot as plt

    W = _sensitivity_matrix(source)
    S, d = W.shape
    if sumstat_labels is None:
        sumstat_labels = [f"s{i}" for i in range(S)]
    if par_labels is None:
        par_labels = [f"p{j}" for j in range(d)]
    fig, ax = get_figure(ax, size)
    total = W.sum()
    if total <= 0:
        raise ValueError("sensitivity matrix is all zeros")
    Wn = W / total

    # node extents: stacked by outgoing / incoming flow, with small gaps
    gap = 0.01
    left_sizes = Wn.sum(axis=1)
    right_sizes = Wn.sum(axis=0)

    def stack(sizes):
        tops = []
        y = 0.0
        for sz in sizes:
            tops.append(y)
            y += sz + gap
        return tops, y - gap

    left_tops, left_h = stack(left_sizes)
    right_tops, right_h = stack(right_sizes)
    h = max(left_h, right_h)
    colors = plt.get_cmap(cmap)

    # ribbons
    left_cursor = list(left_tops)
    right_cursor = list(right_tops)
    for i in range(S):
        for j in range(d):
            flow = Wn[i, j]
            if flow < min_frac * Wn.max() or flow <= 0:
                continue
            y0 = left_cursor[i]
            y1 = right_cursor[j]
            left_cursor[i] += flow
            right_cursor[j] += flow
            xs = np.linspace(0.12, 0.88, 50)
            ease = (1 - np.cos(np.pi * (xs - 0.12) / 0.76)) / 2
            top = y0 + (y1 - y0) * ease
            ax.fill_between(xs, top, top + flow,
                            color=colors(j % 10), alpha=0.45, lw=0)
    # node bars + labels
    for i in range(S):
        ax.fill_between([0.08, 0.12], left_tops[i],
                        left_tops[i] + left_sizes[i], color="0.3")
        ax.text(0.07, left_tops[i] + left_sizes[i] / 2,
                str(sumstat_labels[i]), ha="right", va="center", fontsize=8)
    for j in range(d):
        ax.fill_between([0.88, 0.92], right_tops[j],
                        right_tops[j] + right_sizes[j],
                        color=colors(j % 10))
        ax.text(0.93, right_tops[j] + right_sizes[j] / 2,
                str(par_labels[j]), ha="left", va="center", fontsize=8)
    ax.set_xlim(0, 1)
    ax.set_ylim(h + gap, -gap)
    ax.axis("off")
    ax.set_title("summary-statistic -> parameter sensitivity")
    return ax
