"""Weighted histogram plots.

Reference parity: ``pyabc/visualization/histogram.py::{plot_histogram_1d,
plot_histogram_2d, plot_histogram_matrix}`` (+ _lowlevel variants).
"""
from __future__ import annotations

import numpy as np

from .util import get_figure


def plot_histogram_1d(history, x: str, m: int = 0, t=None, xmin=None,
                      xmax=None, ax=None, size=None, refval=None,
                      refval_color="C1", **kwargs):
    df, w = history.get_distribution(m=m, t=t)
    return plot_histogram_1d_lowlevel(df, w, x, xmin, xmax, ax=ax, size=size,
                                      refval=refval,
                                      refval_color=refval_color, **kwargs)


def plot_histogram_1d_lowlevel(df, w, x: str, xmin=None, xmax=None, ax=None,
                               size=None, refval=None, refval_color="C1",
                               **kwargs):
    fig, ax = get_figure(ax, size)
    rng = None
    if xmin is not None and xmax is not None:
        rng = (xmin, xmax)
    ax.hist(np.asarray(df[x]), weights=np.asarray(w), range=rng,
            density=True, **kwargs)
    if refval is not None:
        ax.axvline(refval[x] if isinstance(refval, dict) else refval,
                   color=refval_color, linestyle="dotted")
    ax.set_xlabel(x)
    ax.set_ylabel("posterior")
    return ax


def plot_histogram_2d(history, x: str, y: str, m: int = 0, t=None, xmin=None,
                      xmax=None, ymin=None, ymax=None, ax=None, size=None,
                      refval=None, refval_color="C1", **kwargs):
    df, w = history.get_distribution(m=m, t=t)
    return plot_histogram_2d_lowlevel(df, w, x, y, xmin, xmax, ymin, ymax,
                                      ax=ax, size=size, refval=refval,
                                      refval_color=refval_color, **kwargs)


def plot_histogram_2d_lowlevel(df, w, x: str, y: str, xmin=None, xmax=None,
                               ymin=None, ymax=None, ax=None, size=None,
                               refval=None, refval_color="C1", **kwargs):
    fig, ax = get_figure(ax, size)
    rng = None
    if all(v is not None for v in (xmin, xmax, ymin, ymax)):
        rng = [[xmin, xmax], [ymin, ymax]]
    _, _, _, im = ax.hist2d(np.asarray(df[x]), np.asarray(df[y]),
                            weights=np.asarray(w), range=rng, density=True,
                            **kwargs)
    fig.colorbar(im, ax=ax)
    if refval is not None:
        ax.scatter([refval[x]], [refval[y]], color=refval_color, marker="x")
    ax.set_xlabel(x)
    ax.set_ylabel(y)
    return ax


def plot_histogram_matrix(history, m: int = 0, t=None, size=None, refval=None,
                          refval_color="C1", **kwargs):
    df, w = history.get_distribution(m=m, t=t)
    return plot_histogram_matrix_lowlevel(df, w, size, refval, refval_color,
                                          **kwargs)


def plot_histogram_matrix_lowlevel(df, w, size=None, refval=None,
                                   refval_color="C1", **kwargs):
    import matplotlib.pyplot as plt

    names = list(df.columns)
    n = len(names)
    fig, axes = plt.subplots(n, n, squeeze=False)
    if size is not None:
        fig.set_size_inches(size)
    for i, yi in enumerate(names):
        for j, xj in enumerate(names):
            ax = axes[i][j]
            if i == j:
                plot_histogram_1d_lowlevel(df, w, xj, ax=ax, refval=refval,
                                           refval_color=refval_color,
                                           **kwargs)
            elif i > j:
                plot_histogram_2d_lowlevel(df, w, xj, yi, ax=ax,
                                           refval=refval,
                                           refval_color=refval_color)
            else:
                ax.axis("off")
    fig.tight_layout()
    return axes
