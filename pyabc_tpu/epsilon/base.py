"""Epsilon (acceptance threshold) schedules.

Reference parity: ``pyabc/epsilon/epsilon.py::{Epsilon, NoEpsilon,
ConstantEpsilon, ListEpsilon, QuantileEpsilon, MedianEpsilon}``.

`QuantileEpsilon` shrinks the threshold each generation to the alpha-quantile
of the previous generation's *weighted* accepted distances (the reference's
adaptive default). Host-side float64; the resulting scalar is passed as a
kernel argument each generation (no recompile).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Callable

import numpy as np

from ..core.weighted_statistics import weighted_quantile


class Epsilon(ABC):
    """Abstract epsilon schedule (pyabc Epsilon)."""

    def initialize(self, t: int, get_weighted_distances: Callable | None = None,
                   get_all_records: Callable | None = None,
                   max_nr_populations: int | None = None,
                   acceptor_config: dict | None = None) -> None:
        pass

    def configure_sampler(self, sampler) -> None:
        pass

    def update(self, t: int, get_weighted_distances: Callable | None = None,
               get_all_records: Callable | None = None,
               acceptance_rate: float | None = None,
               acceptor_config: dict | None = None) -> None:
        pass

    @abstractmethod
    def __call__(self, t: int) -> float:
        """The threshold for generation t."""

    def requires_calibration(self) -> bool:
        return False

    def get_config(self) -> dict:
        return {"name": type(self).__name__}

    def __repr__(self):
        return f"{type(self).__name__}()"


class NoEpsilon(Epsilon):
    """No threshold (acceptance decided elsewhere; pyabc NoEpsilon)."""

    def __call__(self, t: int) -> float:
        return np.nan


class ConstantEpsilon(Epsilon):
    """Same threshold every generation (pyabc ConstantEpsilon)."""

    def __init__(self, constant_epsilon_value: float):
        self.constant_epsilon_value = float(constant_epsilon_value)

    def __call__(self, t: int) -> float:
        return self.constant_epsilon_value

    def get_config(self):
        return {"name": type(self).__name__,
                "constant_epsilon_value": self.constant_epsilon_value}


class ListEpsilon(Epsilon):
    """Pre-specified threshold per generation (pyabc ListEpsilon)."""

    def __init__(self, values):
        self.epsilon_values = [float(v) for v in values]

    def __call__(self, t: int) -> float:
        return self.epsilon_values[t]

    def get_config(self):
        return {"name": type(self).__name__, "epsilon_values": self.epsilon_values}


class QuantileEpsilon(Epsilon):
    """alpha-quantile of the previous generation's weighted accepted distances
    (pyabc QuantileEpsilon).

    ``initial_epsilon`` may be a float or 'from_sample' (quantile of the
    calibration sample — requires calibration). ``quantile_multiplier``
    optionally scales the quantile (e.g. aggressive shrink < 1).
    """

    def __init__(self, initial_epsilon: float | str = "from_sample",
                 alpha: float = 0.5, quantile_multiplier: float = 1.0,
                 weighted: bool = True):
        if not 0 < alpha <= 1:
            raise ValueError("alpha must be in (0, 1]")
        self.initial_epsilon = initial_epsilon
        self.alpha = float(alpha)
        self.quantile_multiplier = float(quantile_multiplier)
        self.weighted = bool(weighted)
        self._values: dict[int, float] = {}

    def requires_calibration(self) -> bool:
        return self.initial_epsilon == "from_sample"

    def initialize(self, t, get_weighted_distances=None, get_all_records=None,
                   max_nr_populations=None, acceptor_config=None):
        if self.initial_epsilon == "from_sample":
            if get_weighted_distances is None:
                raise ValueError(
                    "QuantileEpsilon('from_sample') needs calibration distances"
                )
            df = get_weighted_distances()
            self._set(t, df)
        else:
            self._values[t] = float(self.initial_epsilon)

    def update(self, t, get_weighted_distances=None, get_all_records=None,
               acceptance_rate=None, acceptor_config=None):
        if get_weighted_distances is None:
            raise ValueError("QuantileEpsilon.update needs weighted distances")
        self._set(t, get_weighted_distances())

    def _set(self, t: int, df) -> None:
        distances = np.asarray(df["distance"], np.float64)
        weights = (
            np.asarray(df["w"], np.float64)
            if self.weighted and "w" in df
            else np.ones_like(distances)
        )
        val = weighted_quantile(distances, weights, alpha=self.alpha)
        self._values[t] = float(val * self.quantile_multiplier)

    def __call__(self, t: int) -> float:
        try:
            return self._values[t]
        except KeyError:
            raise KeyError(
                f"no epsilon value for generation {t} (have {sorted(self._values)})"
            )

    def get_config(self):
        return {
            "name": type(self).__name__,
            "alpha": self.alpha,
            "quantile_multiplier": self.quantile_multiplier,
            "weighted": self.weighted,
        }

    def __repr__(self):
        return f"{type(self).__name__}(alpha={self.alpha})"


class MedianEpsilon(QuantileEpsilon):
    """QuantileEpsilon at alpha=0.5 (pyabc MedianEpsilon; the default)."""

    def __init__(self, initial_epsilon: float | str = "from_sample",
                 quantile_multiplier: float = 1.0, weighted: bool = True):
        super().__init__(initial_epsilon, alpha=0.5,
                         quantile_multiplier=quantile_multiplier,
                         weighted=weighted)
