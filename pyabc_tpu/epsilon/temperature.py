"""Temperature schedules for stochastic (noisy) ABC.

Reference parity: ``pyabc/epsilon/temperature.py::{Temperature,
TemperatureBase, TemperatureScheme, AcceptanceRateScheme,
ExpDecayFixedIterScheme, ExpDecayFixedRatioScheme,
PolynomialDecayFixedIterScheme, DalyScheme, FrielPettittScheme, EssScheme}``.

With a `StochasticAcceptor`, epsilon(t) is an (inverse) temperature T_t >= 1
on the acceptance density: accept ~ exp((v - pdf_norm)/T). Temperature
orchestrates one or more schemes, takes the *minimum* (most aggressive)
proposal each generation, enforces monotone decay, and lands exactly at
T = 1 (exact sampling) on the final generation when the horizon is known.

All schemes receive the full per-generation context and return a proposed
temperature. Weighted kernel values (log scale) come from the previous
generation's records.
"""
from __future__ import annotations

import logging
from typing import Callable, Sequence

import numpy as np

from .base import Epsilon

logger = logging.getLogger("ABC.Epsilon")


class TemperatureScheme:
    """Base: __call__(t, **ctx) -> proposed temperature."""

    def __call__(self, t: int, *, get_weighted_distances=None,
                 get_all_records=None,
                 pdf_norm: float | None = None, kernel_scale: str = "SCALE_LOG",
                 prev_temperature: float | None = None,
                 acceptance_rate: float | None = None,
                 max_nr_populations: int | None = None) -> float:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}()"


class AcceptanceRateScheme(TemperatureScheme):
    """Choose T so the *predicted* acceptance rate hits ``target_rate``
    (reference AcceptanceRateScheme).

    The prediction model: weighted mean over kernel values v_i of
    min(1, exp((v_i - pdf_norm)/T)); bisection on log10(T). Prefers the
    ALL-simulations record (accepted + rejected); falls back to the
    importance-weighted accepted set.

    Record reweighting (reference semantics): the records are distributed
    under generation t's *proposal*, while the rate being predicted is
    under generation t+1's proposal. When the record carries
    ``transition_pd_prev`` (density under the proposal it was drawn from)
    and ``transition_pd`` (density under the NEXT proposal, computed after
    the transition refit), each record is importance-reweighted by
    transition_pd / transition_pd_prev — correcting for the proposal shift
    between generations. Records without the columns fall back to uniform
    weights (one-generation-lag approximation).
    """

    def __init__(self, target_rate: float = 0.3):
        self.target_rate = float(target_rate)

    def __call__(self, t, *, get_weighted_distances=None, get_all_records=None,
                 pdf_norm=None, kernel_scale="SCALE_LOG",
                 prev_temperature=None, acceptance_rate=None,
                 max_nr_populations=None) -> float:
        if pdf_norm is None:
            return np.inf
        df = None
        if get_all_records is not None:
            df = get_all_records()
        if df is None or len(df) == 0:
            if get_weighted_distances is None:
                return np.inf
            df = get_weighted_distances()
        vals = np.asarray(df["distance"], np.float64)
        if kernel_scale == "SCALE_LIN":
            vals = np.log(np.maximum(vals, 1e-300))
        if "transition_pd_prev" in df and "transition_pd" in df:
            pd_prev = np.asarray(df["transition_pd_prev"], np.float64)
            pd_new = np.asarray(df["transition_pd"], np.float64)
            ok = np.isfinite(pd_prev) & (pd_prev > 0) & np.isfinite(pd_new)
            w = np.where(ok, pd_new / np.where(ok, pd_prev, 1.0), 0.0)
            if w.sum() <= 0:
                w = np.ones_like(vals)
        elif "w" in df:
            w = np.asarray(df["w"], np.float64)
        else:
            w = np.ones_like(vals)
        w = w / w.sum()
        diff = vals - pdf_norm  # <= 0 typically

        def rate_at(temp: float) -> float:
            return float(np.sum(w * np.minimum(1.0, np.exp(diff / temp))))

        # T=1 already accepts often enough -> no tempering needed
        if rate_at(1.0) >= self.target_rate:
            return 1.0
        lo, hi = 0.0, 12.0  # log10 T in [1, 1e12]
        for _ in range(60):
            mid = 0.5 * (lo + hi)
            if rate_at(10.0**mid) >= self.target_rate:
                hi = mid
            else:
                lo = mid
        return float(10.0**hi)


class ExpDecayFixedIterScheme(TemperatureScheme):
    """Exponential decay to T=1 over a fixed horizon (reference
    ExpDecayFixedIterScheme): log T linear in t, hitting 1 at the final
    generation."""

    def __call__(self, t, *, prev_temperature=None, max_nr_populations=None,
                 **ctx) -> float:
        if max_nr_populations is None:
            raise ValueError(
                "ExpDecayFixedIterScheme needs a fixed max_nr_populations"
            )
        if prev_temperature is None or not np.isfinite(prev_temperature):
            return np.inf
        t_to_go = max_nr_populations - t
        if t_to_go <= 1:
            return 1.0
        # geometric interpolation from prev temp to 1 over remaining gens
        return float(prev_temperature ** ((t_to_go - 1) / t_to_go))


class ExpDecayFixedRatioScheme(TemperatureScheme):
    """T_t = alpha * T_{t-1} (reference ExpDecayFixedRatioScheme)."""

    def __init__(self, alpha: float = 0.5, min_rate: float = 1e-4,
                 max_rate: float = 0.5):
        self.alpha = float(alpha)
        self.min_rate = min_rate
        self.max_rate = max_rate

    def __call__(self, t, *, prev_temperature=None, acceptance_rate=None,
                 **ctx) -> float:
        if prev_temperature is None or not np.isfinite(prev_temperature):
            return np.inf
        alpha = self.alpha
        if acceptance_rate is not None:
            # slow down when acceptance collapses, speed up when trivial
            if acceptance_rate < self.min_rate:
                alpha = np.sqrt(alpha)
            elif acceptance_rate > self.max_rate:
                alpha = alpha**2
        return float(max(1.0, alpha * prev_temperature))


class PolynomialDecayFixedIterScheme(TemperatureScheme):
    """T decays polynomially to 1 over a fixed horizon (reference
    PolynomialDecayFixedIterScheme)."""

    def __init__(self, exponent: float = 3.0):
        self.exponent = float(exponent)

    def __call__(self, t, *, prev_temperature=None, max_nr_populations=None,
                 **ctx) -> float:
        if max_nr_populations is None:
            raise ValueError(
                "PolynomialDecayFixedIterScheme needs max_nr_populations"
            )
        if prev_temperature is None or not np.isfinite(prev_temperature):
            return np.inf
        t_to_go = max_nr_populations - t
        if t_to_go <= 1:
            return 1.0
        frac = (t_to_go - 1) / t_to_go
        return float(1.0 + (prev_temperature - 1.0) * frac**self.exponent)


class DalyScheme(TemperatureScheme):
    """Daly et al. 2017 adaptive tolerance contraction (reference DalyScheme):
    keep an internal contraction state k; shrink it by ``alpha`` each
    generation, but react to acceptance-rate collapse by re-expanding."""

    def __init__(self, alpha: float = 0.5, min_rate: float = 1e-4):
        self.alpha = float(alpha)
        self.min_rate = float(min_rate)
        self._k: dict[int, float] = {}

    def __call__(self, t, *, prev_temperature=None, acceptance_rate=None,
                 **ctx) -> float:
        if prev_temperature is None or not np.isfinite(prev_temperature):
            return np.inf
        k_prev = self._k.get(t - 1, prev_temperature)
        if acceptance_rate is not None and acceptance_rate < self.min_rate:
            # back off: SHRINK the contraction step so temperature decreases
            # more slowly while acceptance recovers (reference Daly reaction;
            # dividing by alpha would double the decrement and cool faster,
            # worsening the collapse)
            k = self.alpha * k_prev
        else:
            k = self.alpha * min(k_prev, prev_temperature)
        self._k[t] = k
        return float(max(1.0, prev_temperature - k))


class FrielPettittScheme(TemperatureScheme):
    """Power-posterior tempering ladder beta_t = ((t+1)/n)^2, T = 1/beta
    (reference FrielPettittScheme)."""

    def __call__(self, t, *, max_nr_populations=None, **ctx) -> float:
        if max_nr_populations is None:
            raise ValueError("FrielPettittScheme needs max_nr_populations")
        beta = ((t + 1.0) / max_nr_populations) ** 2
        return float(1.0 / max(beta, 1e-12))


class EssScheme(TemperatureScheme):
    """Choose T so the relative ESS of the tempering reweight factors hits
    ``target_relative_ess`` (reference EssScheme)."""

    def __init__(self, target_relative_ess: float = 0.8):
        self.target_relative_ess = float(target_relative_ess)

    def __call__(self, t, *, get_weighted_distances=None, pdf_norm=None,
                 kernel_scale="SCALE_LOG", prev_temperature=None, **ctx
                 ) -> float:
        if get_weighted_distances is None:
            return np.inf
        df = get_weighted_distances()
        vals = np.asarray(df["distance"], np.float64)
        if kernel_scale == "SCALE_LIN":
            vals = np.log(np.maximum(vals, 1e-300))
        w = np.asarray(df["w"], np.float64) if "w" in df else np.ones_like(vals)
        w = w / w.sum()
        T_prev = (
            prev_temperature
            if prev_temperature is not None and np.isfinite(prev_temperature)
            else None
        )

        def rel_ess(temp: float) -> float:
            # reweight factor from T_prev (or prior) to temp
            beta_new = 1.0 / temp
            beta_old = 0.0 if T_prev is None else 1.0 / T_prev
            lw = (beta_new - beta_old) * vals
            lw = lw - lw.max()
            ww = w * np.exp(lw)
            s = ww.sum()
            if s <= 0:
                return 0.0
            ww = ww / s
            return float(1.0 / np.sum(ww**2) / len(ww))

        target = self.target_relative_ess
        if rel_ess(1.0) >= target:
            return 1.0
        lo, hi = 0.0, 12.0
        for _ in range(60):
            mid = 0.5 * (lo + hi)
            if rel_ess(10.0**mid) >= target:
                hi = mid
            else:
                lo = mid
        return float(10.0**hi)


class Temperature(Epsilon):
    """Adaptive temperature schedule (reference Temperature).

    ``schemes``: list of TemperatureScheme; the per-generation proposal is
    aggregated with ``aggregate_fun`` (default min) and clipped to enforce
    monotone decay and T >= 1. The final generation (known horizon) forces
    T = 1. Defaults follow the reference: AcceptanceRateScheme +
    ExpDecayFixedIterScheme.
    """

    def __init__(self, schemes: Sequence[TemperatureScheme] | None = None,
                 aggregate_fun: Callable = min,
                 initial_temperature: float | TemperatureScheme | None = None,
                 enforce_less_equal_prev: bool = True,
                 log_file: str | None = None):
        self.schemes = list(schemes) if schemes is not None else None
        self.aggregate_fun = aggregate_fun
        self.initial_temperature = (
            initial_temperature
            if initial_temperature is not None
            else AcceptanceRateScheme()
        )
        self.enforce_less_equal_prev = enforce_less_equal_prev
        self.log_file = log_file
        self.temperatures: dict[int, float] = {}
        self._max_nr_populations: int | None = None

    def requires_calibration(self) -> bool:
        return True

    def configure_sampler(self, sampler):
        # acceptance-rate prediction wants all simulations, incl. rejected,
        # with the proposal identity/density per record so the prediction
        # can be importance-reweighted to the next generation's proposal
        sampler.sample_factory.record_rejected = True
        sampler.sample_factory.record_proposal_info = True

    def _effective_schemes(self) -> list[TemperatureScheme]:
        if self.schemes is not None:
            return self.schemes
        schemes: list[TemperatureScheme] = [AcceptanceRateScheme()]
        if self._max_nr_populations is not None:
            schemes.append(ExpDecayFixedIterScheme())
        else:
            schemes.append(ExpDecayFixedRatioScheme())
        return schemes

    def initialize(self, t, get_weighted_distances=None, get_all_records=None,
                   max_nr_populations=None, acceptor_config=None):
        self._max_nr_populations = max_nr_populations
        self._set(t, get_weighted_distances, acceptor_config,
                  acceptance_rate=None, get_all_records=get_all_records)

    def update(self, t, get_weighted_distances=None, get_all_records=None,
               acceptance_rate=None, acceptor_config=None):
        self._set(t, get_weighted_distances, acceptor_config, acceptance_rate,
                  get_all_records=get_all_records)

    def _set(self, t, get_weighted_distances, acceptor_config,
             acceptance_rate, get_all_records=None):
        acceptor_config = acceptor_config or {}
        pdf_norm = acceptor_config.get("pdf_norm")
        kernel_scale = acceptor_config.get("kernel_scale", "SCALE_LOG")
        prev = self.temperatures.get(t - 1)
        is_final = (
            self._max_nr_populations is not None
            and t >= self._max_nr_populations - 1
        )
        if is_final:
            temp = 1.0
        elif t == 0 or prev is None:
            init = self.initial_temperature
            if isinstance(init, (int, float)):
                temp = float(init)
            else:
                temp = init(
                    t, get_weighted_distances=get_weighted_distances,
                    get_all_records=get_all_records,
                    pdf_norm=pdf_norm, kernel_scale=kernel_scale,
                    prev_temperature=None, acceptance_rate=acceptance_rate,
                    max_nr_populations=self._max_nr_populations,
                )
            if not np.isfinite(temp):
                temp = 1e4  # reference-style high fallback start
        else:
            proposals = []
            for scheme in self._effective_schemes():
                try:
                    proposals.append(scheme(
                        t, get_weighted_distances=get_weighted_distances,
                        get_all_records=get_all_records,
                        pdf_norm=pdf_norm, kernel_scale=kernel_scale,
                        prev_temperature=prev,
                        acceptance_rate=acceptance_rate,
                        max_nr_populations=self._max_nr_populations,
                    ))
                except ValueError:
                    continue
            proposals = [p for p in proposals if np.isfinite(p)] or [prev]
            temp = float(self.aggregate_fun(proposals))
        if (self.enforce_less_equal_prev and prev is not None
                and np.isfinite(prev)):
            temp = min(temp, prev)
        temp = max(temp, 1.0)
        self.temperatures[t] = temp
        logger.debug("temperature t=%d: %.4g", t, temp)
        if self.log_file:
            import json

            with open(self.log_file, "w") as fh:
                json.dump({str(k): v for k, v in self.temperatures.items()},
                          fh, indent=1)

    def __call__(self, t: int) -> float:
        return self.temperatures[t]

    def get_config(self):
        return {"name": type(self).__name__}

    def __repr__(self):
        return f"Temperature(schemes={self.schemes})"


class ListTemperature(Epsilon):
    """Pre-specified temperature ladder (reference ListTemperature): the
    user supplies T_t for every generation; the last entry is typically 1
    for exact sampling. No calibration, no adaptation."""

    def __init__(self, values: Sequence[float]):
        self.values = [float(v) for v in values]
        #: mirror Temperature's attribute so StochasticAcceptor/telemetry
        #: code paths that read `.temperatures` work unchanged
        self.temperatures = {t: v for t, v in enumerate(self.values)}

    def requires_calibration(self) -> bool:
        return False

    def initialize(self, t, get_weighted_distances=None,
                   get_all_records=None, max_nr_populations=None,
                   acceptor_config=None):
        pass

    def update(self, t, get_weighted_distances=None, get_all_records=None,
               acceptance_rate=None, acceptor_config=None):
        pass

    def configure_sampler(self, sampler):
        pass

    def __call__(self, t: int) -> float:
        if t >= len(self.values):
            return self.values[-1]
        return self.values[t]

    def get_config(self):
        return {"name": type(self).__name__, "values": self.values}

    def __repr__(self):
        return f"ListTemperature({self.values})"
