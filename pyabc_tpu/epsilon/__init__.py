from .base import (
    ConstantEpsilon,
    Epsilon,
    ListEpsilon,
    MedianEpsilon,
    NoEpsilon,
    QuantileEpsilon,
)
from .temperature import (
    AcceptanceRateScheme,
    DalyScheme,
    EssScheme,
    ExpDecayFixedIterScheme,
    ExpDecayFixedRatioScheme,
    FrielPettittScheme,
    ListTemperature,
    PolynomialDecayFixedIterScheme,
    Temperature,
    TemperatureScheme,
)

__all__ = [
    "Epsilon", "NoEpsilon", "ConstantEpsilon", "ListEpsilon",
    "QuantileEpsilon", "MedianEpsilon",
    "Temperature", "TemperatureScheme", "AcceptanceRateScheme",
    "ExpDecayFixedIterScheme", "ExpDecayFixedRatioScheme",
    "PolynomialDecayFixedIterScheme", "DalyScheme", "FrielPettittScheme",
    "EssScheme", "ListTemperature",
]
