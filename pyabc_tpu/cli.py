"""Command-line entry points (reference parity: ``pyabc/storage/export.py``
CLI ``abc-export`` and the setup.py console_scripts block).

``abc-export``  — dump a History database to CSV/parquet/JSON.
``abc-bench``   — run the Lotka-Volterra benchmark and print the one-line
                  JSON record (the packaged twin of repo-root ``bench.py``).
"""
from __future__ import annotations

import json
import os
import sys

import click

from .utils.bench_defaults import (
    DEFAULT_BUDGET_S,
    DEFAULT_G,
    DEFAULT_GENS,
    DEFAULT_POP,
)


@click.command("abc-export")
@click.argument("db", type=click.Path(exists=True))
@click.option("--run", "run_id", type=int, default=None,
              help="ABC run id within the db (default: latest)")
@click.option("--what", type=click.Choice(
    ["particles", "populations", "model-probabilities",
     "weighted-distances", "runs"]), default="particles",
    help="Which table/view to export")
@click.option("--t", "time_point", type=int, default=None,
              help="Generation index (default: last)")
@click.option("--model", "m", type=int, default=0,
              help="Model index for particle export")
@click.option("--format", "fmt", type=click.Choice(["csv", "parquet", "json"]),
              default="csv")
@click.option("--out", type=click.Path(), default="-",
              help="Output file ('-' = stdout; parquet requires a file)")
def export_cmd(db, run_id, what, time_point, m, fmt, out):
    """Export a pyabc_tpu History database DB to CSV/parquet/JSON."""
    from .storage import History

    url = db if db.startswith("sqlite:") else f"sqlite:///{db}"
    h = History(url, _id=run_id)

    if what == "particles":
        df, w = h.get_distribution(m=m, t=time_point)
        df = df.copy()
        df["w"] = w
    elif what == "populations":
        df = h.get_all_populations()
    elif what == "model-probabilities":
        df = h.get_model_probabilities(time_point)
    elif what == "weighted-distances":
        df = h.get_weighted_distances(time_point)
    else:  # runs
        df = h.all_runs()

    if out == "-":
        if fmt == "parquet":
            raise click.UsageError("parquet needs --out FILE")
        click.echo(
            df.to_csv(index=False) if fmt == "csv"
            else df.to_json(orient="records")
        )
        return
    if fmt == "csv":
        df.to_csv(out, index=False)
    elif fmt == "parquet":
        df.to_parquet(out, index=False)
    else:
        df.to_json(out, orient="records")
    click.echo(f"wrote {len(df)} rows to {out}", err=True)


@click.command("abc-bench")
@click.option("--pop", type=int, default=DEFAULT_POP,
              help="population size")
@click.option("--gens", type=int, default=None,
              help="steady-state generations (default: the shared bench "
                   "default, sized for >=2 post-compile fused chunks)")
@click.option("--budget-s", type=float, default=DEFAULT_BUDGET_S,
              help="walltime budget in seconds")
@click.option("--cpu", is_flag=True, help="force the CPU platform")
@click.option("--lane",
              type=click.Choice(["all", "mesh", "serve", "storage",
                                 "scenario", "traffic"]),
              default="all",
              help="run only one bench lane: 'mesh' runs the sharded "
                   "multi-device lane (the MULTICHIP dryrun promoted to "
                   "a first-class path; forces 8 virtual CPU devices "
                   "when no multi-device platform exists); 'serve' runs "
                   "the multi-tenant chaos lane (N CPU tenants with "
                   "injected kills — guards isolation, fairness and the "
                   "kernel-cache hit rate); 'storage' measures History "
                   "ingest (row store WAL on/off vs the columnar "
                   "generation-batch store, >=10x regression guard). "
                   "Requires a repo checkout (bench.py).")
def bench_cmd(pop, gens, budget_s, cpu, lane):
    """Run the Lotka-Volterra throughput benchmark (one JSON line)."""
    if cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    if lane and lane != "all":
        os.environ["PYABC_TPU_BENCH_LANE"] = lane
    # explicit CLI flags win over any pre-existing env configuration
    os.environ["PYABC_TPU_BENCH_POP"] = str(pop)
    if gens is not None:
        os.environ["PYABC_TPU_BENCH_GENS"] = str(gens)
    os.environ["PYABC_TPU_BENCH_BUDGET_S"] = str(budget_s)
    # repo-root bench.py is the canonical harness; fall back to an inline
    # run when installed without the repo (wheel)
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench_path = os.path.join(here, "bench.py")
    if os.path.exists(bench_path):
        import runpy

        sys.argv = [bench_path]
        runpy.run_path(bench_path, run_name="__main__")
        return
    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv
    from pyabc_tpu.observability import SYSTEM_CLOCK

    if gens is None:
        # mirror the repo bench.py default resolution (env wins, then the
        # shared bench_defaults sizing) so wheel installs run the same
        # benchmark as repo checkouts
        gens = int(os.environ.get("PYABC_TPU_BENCH_GENS", DEFAULT_GENS))
    model = lv.make_lv_model()
    abc = pt.ABCSMC(model, lv.default_prior(),
                    pt.AdaptivePNormDistance(p=2), population_size=pop,
                    eps=pt.MedianEpsilon(),
                    fused_generations=int(
                        os.environ.get("PYABC_TPU_BENCH_G", DEFAULT_G)))
    abc.new("sqlite://", lv.observed_data(seed=123))
    t0 = SYSTEM_CLOCK.now()
    h = abc.run(max_nr_populations=gens + 2, max_walltime=budget_s)
    elapsed = SYSTEM_CLOCK.now() - t0
    click.echo(json.dumps({
        "metric": "accepted_particles_per_sec_lotka_volterra",
        "value": round(pop * h.n_populations / elapsed, 1),
        "unit": "particles/s",
        "generations": int(h.n_populations),
    }))


@click.command("abc-worker")
@click.argument("host")
@click.argument("port", type=int)
@click.option("--id", "worker_id", default=None, help="worker id (default: "
              "hostname-pid-rand)")
@click.option("--runtime-s", type=float, default=float("inf"),
              help="leave the pool after this many seconds")
@click.option("--max-generations", type=float, default=float("inf"),
              help="leave the pool after serving this many generations")
@click.option("--log-file", default=None,
              help="per-worker CSV runtime log (reference parity)")
@click.option("--processes", type=int, default=1,
              help="run N worker processes from this one command "
              "(reference abc-redis-worker --processes)")
@click.option("--catch/--no-catch", "catch_exceptions", default=True,
              help="wrap simulate_one exceptions into rejected error "
              "records instead of killing the worker loop (reference "
              "--catch; default on)")
@click.option("--trace/--no-trace", "trace", default=True,
              help="record worker-side phase spans + clock-offset samples "
              "and piggyback them on result messages (default on; "
              "--no-trace speaks the pre-tracing protocol exactly)")
@click.option("--reconnect-base-s", type=float, default=0.2,
              help="initial reconnect backoff while the broker is "
              "unreachable (doubles per failure, with jitter)")
@click.option("--reconnect-max-s", type=float, default=2.0,
              help="reconnect backoff cap")
@click.option("--fault-plan", "fault_plan", default=None,
              envvar="PYABC_TPU_FAULT_PLAN",
              help="install a deterministic fault plan in this worker "
              "(resilience subsystem), e.g. 'worker.batch:kill:after=2' — "
              "an injected kill dies HARD (no bye; the broker's lease "
              "requeue must heal it). Numeric-corruption kinds "
              "(nan_poison/cov_corrupt/weight_zero at the orchestrator's "
              "device.carry site) exercise the in-kernel health guards "
              "instead. Also read from PYABC_TPU_FAULT_PLAN.")
def worker_cmd(host, port, worker_id, runtime_s, max_generations, log_file,
               processes, catch_exceptions, trace, reconnect_base_s,
               reconnect_max_s, fault_plan):
    """Join an ElasticSampler broker at HOST:PORT as an evaluation worker
    (reference parity: the ``abc-redis-worker`` CLI). Workers may join and
    leave at any time, including mid-generation."""
    from .broker import run_worker

    kwargs = dict(worker_id=worker_id, runtime_s=runtime_s,
                  max_generations=max_generations, log_file=log_file,
                  catch_exceptions=catch_exceptions, trace=trace,
                  reconnect_base_s=reconnect_base_s,
                  reconnect_max_s=reconnect_max_s,
                  fault_plan=fault_plan)
    if processes > 1:
        # one worker per process (reference --processes): each child gets
        # its own id suffix and log file so the CSVs don't interleave.
        # The parent forwards SIGTERM/SIGINT (cluster preemption hits the
        # parent PID only; orphaned spawn children would otherwise keep
        # serving forever under the default infinite runtime) and exits
        # nonzero if any child failed.
        import multiprocessing as mp
        import signal as _signal

        ctx = mp.get_context("spawn")
        procs = []
        for i in range(processes):
            kw = dict(kwargs)
            if worker_id is not None:
                kw["worker_id"] = f"{worker_id}-{i}"
            if log_file is not None:
                kw["log_file"] = f"{log_file}.{i}"
            procs.append(ctx.Process(
                target=_run_worker_child, args=(host, port), kwargs=kw,
            ))

        got_signal = []

        def _forward(signum, frame):
            got_signal.append(signum)
            for p in procs:
                if p.is_alive():
                    p.terminate()

        old = {}
        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                old[sig] = _signal.signal(sig, _forward)
            except (ValueError, OSError):  # pragma: no cover
                pass
        try:
            for p in procs:
                p.start()
            for p in procs:
                p.join()
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for sig, handler in old.items():
                _signal.signal(sig, handler)
        # tolerate signal-driven deaths after a forwarded/terminal-group
        # SIGTERM/SIGINT (Ctrl-C delivers SIGINT to the whole foreground
        # group, so children may die with KeyboardInterrupt before the
        # parent's forward lands) — but a child that crashed for another
        # reason (OOM kill, segfault) must still surface
        ok_codes = {0, -15} | ({-2, 1} if got_signal else set())
        failed = [i for i, p in enumerate(procs)
                  if p.exitcode not in ok_codes]
        if failed:
            raise click.ClickException(
                f"worker process(es) {failed} exited abnormally "
                f"(exitcodes {[procs[i].exitcode for i in failed]})"
            )
        click.echo(f"{processes} workers done", err=True)
        return
    n = run_worker(host, port, **kwargs)
    click.echo(f"worker done: {n} evaluations", err=True)


def _run_worker_child(host, port, **kwargs):
    """Module-level spawn target for ``abc-worker --processes N``."""
    from .broker import run_worker

    run_worker(host, port, **kwargs)


@click.command("abc-manager")
@click.argument("host", required=False)
@click.argument("port", type=int, required=False)
@click.option("--watch", is_flag=True, help="refresh every 2s")
@click.option("--postmortem", "postmortem", default=None,
              type=click.Path(exists=True),
              help="render a crash-safe flight-recorder file (a "
              "tenant's .flight dump) as an offset-corrected timeline "
              "and exit; no server needed")
@click.option("--tenants", "tenants_mode", is_flag=True,
              help="talk to an abc-serve API instead of a broker: list "
              "its tenants (paged — round 19)")
@click.option("--state", default=None,
              help="with --tenants: only tenants in this state "
              "(queued/running/completed/...)")
@click.option("--offset", type=int, default=0,
              help="with --tenants: page start")
@click.option("--limit", type=int, default=None,
              help="with --tenants: page size (default: everything)")
def manager_cmd(host, port, watch, tenants_mode, state, offset, limit,
                postmortem):
    """Show an ElasticSampler broker's live status (reference parity: the
    ``abc-redis-manager`` CLI): generation, counters, connected workers.
    With ``--tenants`` it instead pages an abc-serve scheduler's tenant
    list (``?state=&offset=&limit=`` on ``/api/tenants``); with
    ``--postmortem FILE`` it renders a flight-recorder dump offline."""
    import time as _time

    from .broker.protocol import request

    if postmortem is not None:
        from .observability import read_flight, render_timeline

        click.echo(render_timeline(read_flight(postmortem)))
        return
    if host is None or port is None:
        raise click.UsageError(
            "HOST and PORT are required unless --postmortem is given")
    if tenants_mode:
        return _manager_tenants(host, port, watch, state, offset, limit)
    while True:
        kind, status = request((host, port), ("status",))
        assert kind == "status", (kind, status)
        click.echo(
            f"generation={status.generation} t={status.t} "
            f"acc={status.n_acc}/{status.n_target} "
            f"handed={status.n_eval_handed} results={status.n_results} "
            f"done={status.done}"
        )
        leases = getattr(status, "leases", None) or {}
        if leases:
            # liveness -> ACTION: what the self-healing machinery holds
            # and what it already did (resilience subsystem, round 9)
            click.echo(
                f"  leases: outstanding={leases.get('outstanding_leases', 0)}"
                f" ({leases.get('outstanding_slots', 0)} slots) "
                f"requeued={leases.get('requeued_slots', 0)} "
                f"redispatched={leases.get('redispatched_total', 0)} "
                f"dup_dropped={leases.get('duplicates_dropped', 0)} "
                f"expired={leases.get('leases_expired', 0)} "
                f"retries={getattr(status, 'n_request_retries', 0)}"
            )
        for wid, info in sorted(status.workers.items()):
            line = (
                f"  worker {wid}: results={info.get('n_results', 0)} "
                f"idle={info.get('idle_s', '?')}s"
            )
            if info.get("n_retries"):
                line += f" retries={info['n_retries']}"
            if info.get("clock_offset_s") is not None:
                line += (
                    f" clock_offset={info['clock_offset_s'] * 1e3:.2f}ms"
                    f"(±{(info.get('clock_offset_unc_s') or 0) * 1e3:.2f})"
                )
            if info.get("presumed_dead"):
                line += " PRESUMED-DEAD"
            if info.get("last_recovery"):
                line += f" last_recovery={info['last_recovery']}"
            if info.get("last_error"):
                line += f" last_error={info['last_error']}"
            click.echo(line)
        for ev in getattr(status, "recovery", None) or []:
            click.echo(
                f"  recovery: {ev.get('action')} wid={ev.get('wid')} "
                f"slots={ev.get('n_slots')} gen={ev.get('gen')}"
                + (f" reason={ev['reason']}" if ev.get("reason") else "")
                + (f" orphaned={ev['orphaned_s']:.3f}s"
                   if ev.get("orphaned_s") is not None else "")
            )
        for wid, info in sorted(status.departed.items()):
            click.echo(
                f"  departed {wid}: reason={info.get('reason')} "
                f"results={info.get('n_results', 0)}"
            )
        for eng in getattr(status, "dispatch", None) or []:
            # fused-run dispatch engines live in the broker's process
            # (round 12): speculation / rollback / sync-budget health
            budget = eng.get("sync_budget", {}) or {}
            click.echo(
                f"  dispatch: state={eng.get('state')} t={eng.get('t')} "
                f"in_flight={eng.get('in_flight')}/{eng.get('depth')} "
                f"chunks={eng.get('chunks_processed')}"
                f"/{eng.get('chunks_dispatched')} "
                f"spec_rollbacks={eng.get('speculative_rollbacks')} "
                f"syncs={budget.get('syncs')}<="
                f"{budget.get('allowed')} "
                f"budget_ok={budget.get('ok')}"
            )
        if not watch:
            break
        _time.sleep(2.0)


def _manager_tenants(host, port, watch, state, offset, limit):
    """``abc-manager --tenants``: page an abc-serve tenant list."""
    import http.client
    import json as _json
    import time as _time

    query = f"offset={offset}"
    if state:
        query += f"&state={state}"
    if limit is not None:
        query += f"&limit={limit}"
    while True:
        conn = http.client.HTTPConnection(host, port, timeout=10.0)
        try:
            conn.request("GET", f"/api/tenants?{query}")
            resp = conn.getresponse()
            body = _json.loads(resp.read().decode())
            if resp.status != 200:
                raise click.ClickException(f"HTTP {resp.status}: {body}")
        finally:
            conn.close()
        tenants = body.get("tenants", [])
        total = body.get("tenants_total", len(tenants))
        shown = (f"tenants {offset}..{offset + len(tenants)} of {total}"
                 + (f" state={state}" if state else ""))
        click.echo(shown)
        for st in tenants:
            line = (
                f"  {st['id']}: {st['state']} model={st['spec']['model']} "
                f"pop={st['spec']['population_size']} "
                f"gen={st.get('generations_done', 0)}"
                f"/{st['spec']['generations']} "
                f"bytes={st.get('bytes_on_disk', 0)}"
            )
            quota = st.get("quota_remaining")
            if quota:
                parts = [f"{k}={v:.0f}" if isinstance(v, float) else
                         f"{k}={v}" for k, v in sorted(quota.items())
                         if v is not None]
                if parts:
                    line += f" quota_left[{' '.join(parts)}]"
            click.echo(line)
        life = body.get("lifecycle")
        if life:
            click.echo(
                f"  lifecycle: gced={life.get('generations_gced_total', 0)} "
                f"disposed={life.get('tenants_disposed_total', 0)} "
                f"archived={life.get('archives_total', 0)}")
        if not watch:
            break
        _time.sleep(2.0)


@click.command("abc-serve")
@click.option("--host", default="127.0.0.1", help="bind address")
@click.option("--port", type=int, default=8766, help="port (0 = ephemeral)")
@click.option("--slots", type=int, default=1,
              help="legacy pool sizing: a pool of this many width-1 "
              "devices (ignored when --devices is given)")
@click.option("--devices", "n_devices", type=int, default=None,
              help="device-pool width for sub-mesh placement (0 = "
              "probe the platform); a sharded=n tenant leases a "
              "contiguous 1/2/4/8-wide sub-mesh from this pool")
@click.option("--packing", type=int, default=1,
              help="width-1 tenants packed per device (wider sub-mesh "
              "leases stay exclusive)")
@click.option("--preempt-queue-wait-s", type=float, default=None,
              help="auto-preemption: a queued tenant unplaceable for "
              "this long checkpoint-preempts the widest running tenant "
              "(it requeues and resumes bit-identical on the next free "
              "sub-mesh); unset = explicit POST .../preempt only")
@click.option("--max-queued", type=int, default=16,
              help="admission queue depth; a full queue answers HTTP 429 "
              "with a measured Retry-After instead of queueing unboundedly")
@click.option("--lease-timeout-s", type=float, default=60.0,
              help="run-lease timeout: a tenant orchestrator silent for "
              "this long (hung) is presumed dead, its slot reclaimed and "
              "the tenant requeued from its checkpoint. Size it above the "
              "worst healthy chunk+compile wall time (a fused program's "
              "XLA compile alone is 15-25 s and happens between "
              "heartbeats); DEAD orchestrators are detected immediately "
              "regardless")
@click.option("--max-requeues", type=int, default=1,
              help="lease-expiry requeues per tenant before it fails "
              "terminally with its health trail")
@click.option("--base-dir", default=None,
              help="directory for per-tenant History dbs + checkpoints "
              "(default: a fresh temp dir)")
@click.option("--writer-threads", type=int, default=2,
              help="shared async History writer threads (the pooled "
              "writer serving every tenant's db)")
@click.option("--keep-last-k", type=int, default=None,
              help="retention: GC all but the newest K generations of "
              "each non-running tenant's History (K>=1 keeps resume "
              "safe; unset = keep everything)")
@click.option("--tenant-ttl-s", type=float, default=None,
              help="retention: dispose a terminal tenant's History this "
              "long after it finishes (unset = never)")
@click.option("--archive-on-complete", is_flag=True,
              help="retention: tar.gz a terminal tenant's db + columnar "
              "files instead of deleting them on disposal")
@click.option("--disk-budget-bytes", type=int, default=None,
              help="fleet retention: keep total History bytes under "
              "this by disposing oldest-finished terminal tenants")
@click.option("--quota-chip-seconds", type=float, default=None,
              help="per-tenant quota: reject specs whose estimated "
              "chip-seconds exceed this (HTTP 400, non-retryable)")
@click.option("--quota-bytes", type=int, default=None,
              help="per-tenant quota: bytes-on-disk bound enforced by "
              "the retention sweep")
@click.option("--quota-generations", type=int, default=None,
              help="per-tenant quota: reject specs asking for more "
              "generations than this")
def serve_cmd(host, port, slots, n_devices, packing, preempt_queue_wait_s,
              max_queued, lease_timeout_s, max_requeues,
              base_dir, writer_threads, keep_last_k, tenant_ttl_s,
              archive_on_complete, disk_budget_bytes,
              quota_chip_seconds, quota_bytes, quota_generations):
    """Multi-tenant ABC-SMC serving: a RunScheduler leasing contiguous
    SUB-MESHES of the device pool to tenants (sharded tenants span
    1/2/4/8 devices, small tenants pack per device), fronted by the
    submit/status/stream HTTP API. Big tenants can be checkpoint-
    preempted; device loss shrinks the pool and re-places the affected
    tenants on narrower sub-meshes, bit-identically. SIGTERM/SIGINT
    drains gracefully — every live tenant flushes its History and
    writes a final checkpoint before the process exits."""
    import signal as _signal

    from .serving import (
        RetentionPolicy,
        RunScheduler,
        TenantQuota,
        serve_api,
    )
    from .serving.placement import platform_device_count

    if n_devices == 0:
        n_devices = platform_device_count()
    retention = None
    if (keep_last_k is not None or tenant_ttl_s is not None
            or archive_on_complete or disk_budget_bytes is not None):
        retention = RetentionPolicy(
            keep_last_k=keep_last_k, ttl_s=tenant_ttl_s,
            archive_on_complete=archive_on_complete,
            total_bytes_budget=disk_budget_bytes,
        )
    quota = None
    if (quota_chip_seconds is not None or quota_bytes is not None
            or quota_generations is not None):
        quota = TenantQuota(
            max_chip_seconds=quota_chip_seconds,
            max_bytes_on_disk=quota_bytes,
            max_generations=quota_generations,
        )
    sched = RunScheduler(
        n_slots=slots, n_devices=n_devices, packing=packing,
        preempt_queue_wait_s=preempt_queue_wait_s,
        max_queued=max_queued,
        lease_timeout_s=lease_timeout_s, max_requeues=max_requeues,
        base_dir=base_dir, writer_threads=writer_threads,
        retention=retention, quota=quota,
    )
    httpd = serve_api(sched, host=host, port=port, block=False)
    click.echo(
        f"abc-serve on http://{host}:{httpd.server_port} "
        f"(devices={sched.allocator.n_devices}, "
        f"packing={sched.packing}, max_queued={max_queued}, "
        f"base_dir={sched.base_dir})", err=True,
    )

    stop = {"sig": None}

    def _on_signal(signum, frame):
        stop["sig"] = signum

    for sig in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(sig, _on_signal)
    import time as _time

    while stop["sig"] is None:
        _time.sleep(0.2)
    click.echo(
        f"signal {stop['sig']}: draining tenants (flush + final "
        f"checkpoint)...", err=True,
    )
    summary = sched.drain(timeout_s=60.0)
    httpd.shutdown()
    sched.shutdown()
    n_forced = len(summary["forced"])
    click.echo(
        f"drained: {len(summary['states'])} tenant(s), "
        f"{n_forced} forced", err=True,
    )
    if n_forced:
        raise SystemExit(1)


@click.command("abc-server")
@click.argument("db")
@click.option("--host", default="127.0.0.1", help="bind address")
@click.option("--port", type=int, default=8765, help="port (0 = ephemeral)")
def server_cmd(db, host, port):
    """Serve the web dashboard for the History database DB
    (reference parity: the Flask ``abc-server`` CLI)."""
    from .visserver import serve

    url = db if db.startswith("sqlite:") else f"sqlite:///{db}"
    serve(url, host=host, port=port, block=True)


if __name__ == "__main__":  # pragma: no cover - manual invocation helper
    cmd = sys.argv[1] if len(sys.argv) > 1 else ""
    sys.argv = [sys.argv[0]] + sys.argv[2:]
    {"export": export_cmd, "bench": bench_cmd, "server": server_cmd,
     "worker": worker_cmd, "manager": manager_cmd,
     "serve": serve_cmd}.get(cmd, export_cmd)()
