"""Lotka-Volterra predator-prey ODE benchmark (config 2, BASELINE.md).

Reference analog: the pyABC Lotka-Volterra example notebook
(doc/examples, executed as a CI integration test) — 4 parameters
(alpha, beta, gamma, delta), noisy observations of prey/predator
trajectories. Here the simulator is a traceable RK4-in-scan JaxModel, so a
whole proposal round integrates as one batched XLA program on the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random_variables import RV, Distribution
from ..model import JaxModel
from .ode import rk4_at_times

#: default true parameters (classic textbook values)
TRUE_PARS = {"alpha": 1.0, "beta": 0.1, "gamma": 1.5, "delta": 0.075}
Y0 = (10.0, 5.0)


def _lv_rhs(y, alpha, beta, gamma, delta):
    prey, pred = y[0], y[1]
    dprey = alpha * prey - beta * prey * pred
    dpred = delta * prey * pred - gamma * pred
    return jnp.stack([dprey, dpred])


def make_lv_model(n_obs: int = 20, t1: float = 15.0, n_substeps: int = 10,
                  noise_sd: float = 0.5, log_parameters: bool = False,
                  name: str = "lotka_volterra") -> JaxModel:
    """Build the LV JaxModel: theta = (alpha, beta, gamma, delta).

    Returns noisy trajectories {"prey": (n_obs,), "pred": (n_obs,)}.
    ``log_parameters``: interpret theta as log10 of the rates (the common
    pyABC formulation with uniform-in-log priors).
    """
    ts = np.linspace(0.0, t1, n_obs)

    def sim(key, theta):
        if log_parameters:
            theta = 10.0 ** theta
        alpha, beta, gamma, delta = theta[0], theta[1], theta[2], theta[3]
        traj = rk4_at_times(
            _lv_rhs, jnp.asarray(Y0), ts, n_substeps,
            args=(alpha, beta, gamma, delta),
        )
        traj = jnp.clip(traj, 0.0, 1e6)  # extinction floor / blowup ceiling
        k1, k2 = jax.random.split(key)
        prey = traj[:, 0] + noise_sd * jax.random.normal(k1, (len(ts),))
        pred = traj[:, 1] + noise_sd * jax.random.normal(k2, (len(ts),))
        return {"prey": prey, "pred": pred}

    space = ["alpha", "beta", "gamma", "delta"]
    return JaxModel(sim, space, name=name)


def default_prior(log_parameters: bool = False) -> Distribution:
    if log_parameters:
        return Distribution(
            alpha=RV("uniform", -1.0, 1.3),   # 10^[-1, 0.3]
            beta=RV("uniform", -2.0, 1.3),
            gamma=RV("uniform", -1.0, 1.6),
            delta=RV("uniform", -2.5, 1.5),
        )
    return Distribution(
        alpha=RV("uniform", 0.0, 3.0),
        beta=RV("uniform", 0.0, 0.5),
        gamma=RV("uniform", 0.0, 3.0),
        delta=RV("uniform", 0.0, 0.3),
    )


def observed_data(seed: int = 0, n_obs: int = 20, t1: float = 15.0,
                  n_substeps: int = 10, noise_sd: float = 0.5) -> dict:
    """Ground-truth observation generated at TRUE_PARS."""
    model = make_lv_model(n_obs, t1, n_substeps, noise_sd)
    theta = jnp.asarray([TRUE_PARS["alpha"], TRUE_PARS["beta"],
                         TRUE_PARS["gamma"], TRUE_PARS["delta"]])
    out = model.sim(jax.random.key(seed), theta)
    return {k: np.asarray(v) for k, v in out.items()}
