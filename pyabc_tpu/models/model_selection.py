"""Multi-model selection benchmark (config 5, BASELINE.md).

Reference analog: pyABC's model-selection examples (two tractable models
with analytic posterior model probabilities) and K-ODE-model selection.

Two suites:
- `tractable_pair()`: two conjugate Gaussian models with different noise
  scales — Bayes factors computable in closed form, the statistical anchor.
- `ode_family(K)`: K ODE models of increasing complexity (degradation,
  degradation+production, logistic) sharing one observation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats as st

from ..core.random_variables import RV, Distribution
from ..model import JaxModel
from .ode import rk4_at_times


def tractable_pair(noise_sds=(0.6, 1.2), prior_sd: float = 1.0):
    """Two models: x ~ N(theta, sd_m^2), theta ~ N(0, prior_sd^2).

    Marginal likelihood of model m at observation x0 is
    N(x0; 0, prior_sd^2 + sd_m^2) — posterior model probabilities are exact.
    Returns (models, priors, analytic_model_posterior(x0)).
    """
    models = []
    priors = []
    for i, sd in enumerate(noise_sds):
        def make(sd=sd, i=i):
            def sim(key, theta):
                return {"x": theta[0] + sd * jax.random.normal(key)}

            return JaxModel(sim, ["theta"], name=f"gauss_sd{i}")

        models.append(make())
        priors.append(Distribution(theta=RV("norm", 0.0, prior_sd)))

    def analytic_posterior(x0: float) -> np.ndarray:
        evid = np.asarray([
            st.norm.pdf(x0, 0.0, np.sqrt(prior_sd**2 + sd**2))
            for sd in noise_sds
        ])
        return evid / evid.sum()

    return models, priors, analytic_posterior


def ode_family(n_obs: int = 12, t1: float = 8.0, noise_sd: float = 0.3):
    """K=3 nested ODE models for y(t), observed with noise:

    m0: dy = -a y            (pure decay)
    m1: dy = -a y + b        (decay + constant production)
    m2: dy = a y (1 - y/k)   (logistic growth)
    """
    ts = np.linspace(0.0, t1, n_obs)
    y0 = jnp.asarray([2.0])

    def mk(rhs, names, name):
        def sim(key, theta):
            traj = rk4_at_times(rhs, y0, ts, 6, args=tuple(theta))
            y = traj[:, 0] + noise_sd * jax.random.normal(key, (len(ts),))
            return {"y": y}

        return JaxModel(sim, names, name=name)

    def rhs0(y, a):
        return -a * y

    def rhs1(y, a, b):
        return -a * y + b

    def rhs2(y, a, k):
        return a * y * (1.0 - y / k)

    models = [
        mk(rhs0, ["a"], "decay"),
        mk(rhs1, ["a", "b"], "decay_production"),
        mk(rhs2, ["a", "k"], "logistic"),
    ]
    priors = [
        Distribution(a=RV("uniform", 0.05, 1.0)),
        Distribution(a=RV("uniform", 0.05, 1.0), b=RV("uniform", 0.0, 1.0)),
        Distribution(a=RV("uniform", 0.05, 1.0), k=RV("uniform", 1.0, 9.0)),
    ]
    return models, priors, ts


def observed_ode_family(seed: int = 0, true_model: int = 1,
                        n_obs: int = 12, t1: float = 8.0,
                        noise_sd: float = 0.3) -> dict:
    models, _, _ = ode_family(n_obs, t1, noise_sd)
    true_theta = {0: [0.4], 1: [0.4, 0.5], 2: [0.5, 6.0]}[true_model]
    out = models[true_model].sim(
        jax.random.key(seed), jnp.asarray(true_theta)
    )
    return {k: np.asarray(v) for k, v in out.items()}
