"""Multi-model selection benchmark (config 5, BASELINE.md).

Reference analog: pyABC's model-selection examples (two tractable models
with analytic posterior model probabilities) and K-ODE-model selection.

Two suites:
- `tractable_pair()`: two conjugate Gaussian models with different noise
  scales — Bayes factors computable in closed form, the statistical anchor.
- `ode_family(K)`: K ODE models of increasing complexity (degradation,
  degradation+production, logistic) sharing one observation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats as st

from ..core.random_variables import RV, Distribution
from ..model import JaxModel
from .ode import rk4_at_times


def tractable_pair(noise_sds=(0.6, 1.2), prior_sd: float = 1.0):
    """Two models: x ~ N(theta, sd_m^2), theta ~ N(0, prior_sd^2).

    Marginal likelihood of model m at observation x0 is
    N(x0; 0, prior_sd^2 + sd_m^2) — posterior model probabilities are exact.
    Returns (models, priors, analytic_model_posterior(x0)).
    """
    models = []
    priors = []
    for i, sd in enumerate(noise_sds):
        def make(sd=sd, i=i):
            def sim(key, theta):
                return {"x": theta[0] + sd * jax.random.normal(key)}

            return JaxModel(sim, ["theta"], name=f"gauss_sd{i}")

        models.append(make())
        priors.append(Distribution(theta=RV("norm", 0.0, prior_sd)))

    def analytic_posterior(x0: float) -> np.ndarray:
        evid = np.asarray([
            st.norm.pdf(x0, 0.0, np.sqrt(prior_sd**2 + sd**2))
            for sd in noise_sds
        ])
        return evid / evid.sum()

    return models, priors, analytic_posterior


def ode_family(n_obs: int = 12, t1: float = 8.0, noise_sd: float = 0.3,
               segments: int | None = None, n_substeps: int = 6):
    """K=3 nested ODE models for y(t), observed with noise:

    m0: dy = -a y            (pure decay)
    m1: dy = -a y + b        (decay + constant production)
    m2: dy = a y (1 - y/k)   (logistic growth)

    ``segments=K`` builds every model through the segmented protocol
    (uniform carry — rates padded to 2 — so the K>1 fused kernel can
    switch one early-reject engine over the model id). Observations are
    then the ``n_obs`` times AFTER t=0 (the unsegmented variant includes
    t=0), each perturbed with noise keyed by its global observation
    index.
    """
    y0 = jnp.asarray([2.0])

    def rhs0(y, a, _b):
        return -a * y

    def rhs1(y, a, b):
        return -a * y + b

    def rhs2(y, a, k):
        return a * y * (1.0 - y / k)

    if segments is not None:
        if n_obs % segments:
            raise ValueError(
                f"segments={segments} must divide n_obs={n_obs}"
            )
        from ..ops.segment import SegmentedSim

        ts = np.linspace(0.0, t1, n_obs + 1)[1:]
        obs_per_seg = n_obs // segments
        dt = (t1 / n_obs) / n_substeps

        def mk(rhs, names, name, rates_of):
            def init(key, theta):
                return {"y": y0, "key": key,
                        "rates": rates_of(theta)}

            def step(carry, seg):
                a_, b_ = carry["rates"][0], carry["rates"][1]

                def obs_step(y, j):
                    def micro(y, _):
                        k1 = rhs(y, a_, b_)
                        k2 = rhs(y + 0.5 * dt * k1, a_, b_)
                        k3 = rhs(y + 0.5 * dt * k2, a_, b_)
                        k4 = rhs(y + dt * k3, a_, b_)
                        return (y + (dt / 6.0)
                                * (k1 + 2 * k2 + 2 * k3 + k4), None)

                    y_new, _ = jax.lax.scan(micro, y, None,
                                            length=n_substeps)
                    kn = jax.random.fold_in(
                        carry["key"], seg * obs_per_seg + j)
                    obs = y_new[0] + noise_sd * jax.random.normal(kn)
                    return y_new, obs

                y_fin, ys = jax.lax.scan(
                    obs_step, carry["y"],
                    jnp.arange(obs_per_seg, dtype=jnp.int32))
                return {**carry, "y": y_fin}, ys

            seg_spec = SegmentedSim(n_segments=segments, init=init,
                                    step=step,
                                    layout=(("y", obs_per_seg),))
            return JaxModel(None, names, name=name, segmented=seg_spec)

        models = [
            mk(rhs0, ["a"], "decay",
               lambda th: jnp.stack([th[0], jnp.zeros(())])),
            mk(rhs1, ["a", "b"], "decay_production",
               lambda th: jnp.stack([th[0], th[1]])),
            mk(rhs2, ["a", "k"], "logistic",
               lambda th: jnp.stack([th[0], th[1]])),
        ]
    else:
        ts = np.linspace(0.0, t1, n_obs)

        def mk(rhs, names, name, nargs):
            def sim(key, theta):
                args = tuple(theta[:nargs]) + ((jnp.zeros(()),)
                                               if nargs == 1 else ())
                traj = rk4_at_times(rhs, y0, ts, n_substeps, args=args)
                y = traj[:, 0] + noise_sd * jax.random.normal(
                    key, (len(ts),))
                return {"y": y}

            return JaxModel(sim, names, name=name)

        models = [
            mk(rhs0, ["a"], "decay", 1),
            mk(rhs1, ["a", "b"], "decay_production", 2),
            mk(rhs2, ["a", "k"], "logistic", 2),
        ]
    priors = [
        Distribution(a=RV("uniform", 0.05, 1.0)),
        Distribution(a=RV("uniform", 0.05, 1.0), b=RV("uniform", 0.0, 1.0)),
        Distribution(a=RV("uniform", 0.05, 1.0), k=RV("uniform", 1.0, 9.0)),
    ]
    return models, priors, ts


def observed_ode_family(seed: int = 0, true_model: int = 1,
                        n_obs: int = 12, t1: float = 8.0,
                        noise_sd: float = 0.3,
                        segments: int | None = None) -> dict:
    models, _, _ = ode_family(n_obs, t1, noise_sd, segments=segments)
    true_theta = {0: [0.4], 1: [0.4, 0.5], 2: [0.5, 6.0]}[true_model]
    out = models[true_model].sim(
        jax.random.key(seed), jnp.asarray(true_theta)
    )
    return {k: np.asarray(v) for k, v in out.items()}
