"""Benchmark model library — the 5 BASELINE.md configs as traceable JaxModels."""
from . import gaussian, gillespie, lotka_volterra, model_selection, sir
from .ode import rk4_at_times, rk4_integrate, rk45_integrate
from .gillespie import tau_leap

__all__ = [
    "gaussian", "lotka_volterra", "gillespie", "sir", "model_selection",
    "rk4_integrate", "rk4_at_times", "rk45_integrate", "tau_leap",
]
