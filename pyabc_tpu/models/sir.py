"""SIR epidemiological ODE benchmark (config 4, BASELINE.md).

Reference analog: the pyABC noisy-ABC / stochastic-acceptor examples.
2 parameters (beta, gamma = infection/recovery rates); observations are
noisy infected counts at fixed times, to be paired with
`IndependentNormalKernel` + `StochasticAcceptor` + `Temperature`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random_variables import RV, Distribution
from ..model import JaxModel
from .ode import rk4_at_times

TRUE_PARS = {"beta": 0.4, "gamma": 0.1}
N_POP = 1000.0
Y0 = (N_POP - 1.0, 1.0, 0.0)


def _sir_rhs(y, beta, gamma):
    s, i, r = y[0], y[1], y[2]
    inf = beta * s * i / N_POP
    rec = gamma * i
    return jnp.stack([-inf, inf - rec, rec])


def make_sir_model(n_obs: int = 15, t1: float = 60.0, n_substeps: int = 8,
                   noise_sd: float = 0.0, name: str = "sir") -> JaxModel:
    """theta = (beta, gamma); returns {"infected": (n_obs,)}.

    With ``noise_sd=0`` the simulator is deterministic — observation noise is
    then modeled by the stochastic kernel (noisy-ABC formulation).
    """
    ts = np.linspace(0.0, t1, n_obs)

    def sim(key, theta):
        beta, gamma = theta[0], theta[1]
        traj = rk4_at_times(_sir_rhs, jnp.asarray(Y0), ts, n_substeps,
                            args=(beta, gamma))
        infected = traj[:, 1]
        if noise_sd > 0:
            infected = infected + noise_sd * jax.random.normal(key, (len(ts),))
        return {"infected": infected}

    return JaxModel(sim, ["beta", "gamma"], name=name)


def default_prior() -> Distribution:
    return Distribution(
        beta=RV("uniform", 0.05, 0.95),
        gamma=RV("uniform", 0.01, 0.49),
    )


def observed_data(seed: int = 0, n_obs: int = 15, t1: float = 60.0,
                  noise_sd: float = 10.0) -> dict:
    """Observation at TRUE_PARS with iid normal measurement noise."""
    model = make_sir_model(n_obs, t1, noise_sd=0.0)
    theta = jnp.asarray([TRUE_PARS["beta"], TRUE_PARS["gamma"]])
    out = model.sim(jax.random.key(seed), theta)
    infected = np.asarray(out["infected"])
    rng = np.random.default_rng(seed)
    return {"infected": infected + noise_sd * rng.normal(size=infected.shape)}
