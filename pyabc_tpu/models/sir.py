"""SIR epidemiological ODE benchmark (config 4, BASELINE.md).

Reference analog: the pyABC noisy-ABC / stochastic-acceptor examples.
2 parameters (beta, gamma = infection/recovery rates); observations are
noisy infected counts at fixed times, to be paired with
`IndependentNormalKernel` + `StochasticAcceptor` + `Temperature`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random_variables import RV, Distribution
from ..model import JaxModel
from .ode import rk4_at_times

TRUE_PARS = {"beta": 0.4, "gamma": 0.1}
N_POP = 1000.0
Y0 = (N_POP - 1.0, 1.0, 0.0)


def _sir_rhs(y, beta, gamma):
    s, i, r = y[0], y[1], y[2]
    inf = beta * s * i / N_POP
    rec = gamma * i
    return jnp.stack([-inf, inf - rec, rec])


def make_sir_model(n_obs: int = 15, t1: float = 60.0, n_substeps: int = 8,
                   noise_sd: float = 0.0, name: str = "sir") -> JaxModel:
    """theta = (beta, gamma); returns {"infected": (n_obs,)}.

    With ``noise_sd=0`` the simulator is deterministic — observation noise is
    then modeled by the stochastic kernel (noisy-ABC formulation).
    """
    ts = np.linspace(0.0, t1, n_obs)

    def sim(key, theta):
        beta, gamma = theta[0], theta[1]
        traj = rk4_at_times(_sir_rhs, jnp.asarray(Y0), ts, n_substeps,
                            args=(beta, gamma))
        infected = traj[:, 1]
        if noise_sd > 0:
            infected = infected + noise_sd * jax.random.normal(key, (len(ts),))
        return {"infected": infected}

    return JaxModel(sim, ["beta", "gamma"], name=name)


def default_prior() -> Distribution:
    return Distribution(
        beta=RV("uniform", 0.05, 0.95),
        gamma=RV("uniform", 0.01, 0.49),
    )


def observed_data(seed: int = 0, n_obs: int = 15, t1: float = 60.0,
                  noise_sd: float = 10.0) -> dict:
    """Observation at TRUE_PARS with iid normal measurement noise."""
    model = make_sir_model(n_obs, t1, noise_sd=0.0)
    theta = jnp.asarray([TRUE_PARS["beta"], TRUE_PARS["gamma"]])
    out = model.sim(jax.random.key(seed), theta)
    infected = np.asarray(out["infected"])
    rng = np.random.default_rng(seed)
    return {"infected": infected + noise_sd * rng.normal(size=infected.shape)}


# --------------------------------------------------------------------------
# network / metapopulation SIR (scenario zoo, ISSUE 15): large
# per-particle state — n_patches coupled SIR compartments integrated
# together, observing every patch's infected series (S = n_obs *
# n_patches flat stats, which stresses fetch packing at scale). Built
# FROM the segmented protocol: each segment integrates a block of
# observation intervals, so the early-reject engine can retire a lane
# whose epidemic already diverges from the observed one.
# --------------------------------------------------------------------------

def make_network_sir_model(n_patches: int = 8, n_obs: int = 16,
                           t1: float = 60.0, n_substeps: int = 4,
                           coupling: float = 0.08, segments: int = 4,
                           noise_sd: float = 0.0,
                           name: str = "network_sir") -> JaxModel:
    """Ring-coupled metapopulation SIR; theta = (beta, gamma) global.

    State y = (3, n_patches): S/I/R per patch, infection pressure on
    patch i mixes local prevalence with its ring neighbors' (coupling).
    Patch 0 seeds the epidemic. Observations are the infected counts of
    EVERY patch at ``n_obs`` equally spaced times after t=0, flattened
    time-major: ``{"infected": (n_obs * n_patches,)}`` — a trajectory
    prefix is a flat prefix, so segment bounds are exact.

    ``noise_sd > 0`` adds iid measurement noise to the emitted counts
    INSIDE the simulator (per segment, from the carried key). The
    default stays deterministic; the noisy variant is the honest
    learned-summary scenario — a regression trained on noise-free
    stats mis-extrapolates to a noisy observation, so posterior-quality
    comparisons must train on data drawn like the observed data.
    """
    if n_obs % segments:
        raise ValueError(
            f"segments={segments} must divide n_obs={n_obs}"
        )
    obs_per_seg = n_obs // segments
    dt = (t1 / n_obs) / n_substeps
    n_pop = N_POP

    def rhs(y, beta, gamma):
        s, i = y[0], y[1]
        left = jnp.roll(i, 1)
        right = jnp.roll(i, -1)
        pressure = (1.0 - coupling) * i + 0.5 * coupling * (left + right)
        inf = beta * s * pressure / n_pop
        rec = gamma * i
        return jnp.stack([-inf, inf - rec, rec])

    y_init = jnp.zeros((3, n_patches), jnp.float32)
    y_init = y_init.at[0].set(n_pop).at[0, 0].add(-5.0).at[1, 0].set(5.0)

    def init(key, theta):
        return {"y": y_init, "key": key,
                "rates": jnp.stack([theta[0], theta[1]])}

    def step(carry, seg):
        beta, gamma = carry["rates"][0], carry["rates"][1]

        def obs_step(y, _):
            def micro(y, _):
                k1 = rhs(y, beta, gamma)
                k2 = rhs(y + 0.5 * dt * k1, beta, gamma)
                k3 = rhs(y + 0.5 * dt * k2, beta, gamma)
                k4 = rhs(y + dt * k3, beta, gamma)
                return y + (dt / 6.0) * (k1 + 2 * k2 + 2 * k3 + k4), None

            y_new, _ = jax.lax.scan(micro, y, None, length=n_substeps)
            return y_new, y_new[1]

        y_fin, infected = jax.lax.scan(
            obs_step, carry["y"], None, length=obs_per_seg)
        infected = infected.reshape(-1)  # time-major
        key = carry["key"]
        if noise_sd > 0:
            key, sub = jax.random.split(key)
            infected = infected + noise_sd * jax.random.normal(
                sub, infected.shape)
        return ({**carry, "y": y_fin, "key": key}, infected)

    from ..ops.segment import SegmentedSim

    seg = SegmentedSim(
        n_segments=segments, init=init, step=step,
        layout=(("infected", obs_per_seg * n_patches),),
    )
    return JaxModel(None, ["beta", "gamma"], name=name, segmented=seg)


def network_sir_prior() -> Distribution:
    return Distribution(
        beta=RV("uniform", 0.05, 0.95),
        gamma=RV("uniform", 0.01, 0.49),
    )


def observed_network_sir(seed: int = 0, noise_sd: float = 8.0,
                         **kwargs) -> dict:
    """Network-SIR observation at TRUE_PARS with measurement noise."""
    model = make_network_sir_model(**kwargs)
    theta = jnp.asarray([TRUE_PARS["beta"], TRUE_PARS["gamma"]])
    out = model.sim(jax.random.key(seed), theta)
    infected = np.asarray(out["infected"])
    rng = np.random.default_rng(seed)
    return {"infected": infected + noise_sd * rng.normal(size=infected.shape)}
