"""Stochastic chemical kinetics via tau-leaping (config 3, BASELINE.md).

Reference analog: the pyABC Gillespie/chemical-reaction example notebooks.
Exact SSA has a data-dependent event count, which XLA cannot trace
(SURVEY.md §7.3.3); the framework therefore ships **tau-leaping** with a
fixed leap count — Poisson firing numbers per reaction channel per leap,
statically shaped, vmap/jit-able. For stiff regions a midpoint tau-leap
variant is provided.

Generic engine + two canonical systems: birth-death and the
Lotka-Volterra reaction network (stochastic LV).

Segmented construction (ISSUE 15): passing ``segments=K`` to a model
constructor factors the leap chain into K fixed-length segments
(:class:`~pyabc_tpu.ops.segment.SegmentedSim`) — per-leap keys derive
from the lane's sim key via ``fold_in(key, leap_index)`` so any segment
is reproducible in isolation, and the full simulator is synthesized
FROM the segment chain, so the classic kernel and the early-reject
engine run identical math on identical keys. The unsegmented
constructors keep the original ``split(key, n_leaps)`` stream.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random_variables import RV, Distribution
from ..model import JaxModel
from ..ops.segment import SegmentedSim


def tau_leap(key, x0, stoich: jnp.ndarray, propensity_fn: Callable,
             t1: float, n_leaps: int, save_every: int = 1,
             midpoint: bool = False):
    """Tau-leaping simulation of a reaction network.

    Parameters
    ----------
    x0: (n_species,) initial counts (float; kept >= 0).
    stoich: (n_reactions, n_species) stoichiometry matrix.
    propensity_fn: (x, *)-> (n_reactions,) nonneg rates.
    n_leaps: fixed number of tau leaps; tau = t1 / n_leaps.
    save_every: thin the saved trajectory to every save_every-th state;
        must divide ``n_leaps`` — a non-dividing value would silently
        drop the trailing partial window and return a wrong-length
        trajectory.
    midpoint: midpoint (2nd-order) tau-leap.

    Returns (n_saved, n_species) trajectory of the post-leap states.
    """
    if save_every < 1:
        raise ValueError(f"save_every must be >= 1, got {save_every}")
    if n_leaps % save_every:
        raise ValueError(
            f"save_every={save_every} does not divide n_leaps={n_leaps}: "
            f"the saved trajectory would silently drop the trailing "
            f"{n_leaps % save_every} leap(s)"
        )
    tau = t1 / n_leaps
    stoich = jnp.asarray(stoich, jnp.float32)

    def leap(carry, k):
        x = carry
        a = jnp.maximum(propensity_fn(x), 0.0)
        if midpoint:
            x_mid = jnp.maximum(x + 0.5 * tau * a @ stoich, 0.0)
            a = jnp.maximum(propensity_fn(x_mid), 0.0)
        n_fire = jax.random.poisson(k, a * tau).astype(jnp.float32)
        x_new = jnp.maximum(x + n_fire @ stoich, 0.0)
        return x_new, x_new

    keys = jax.random.split(key, n_leaps)
    _, traj = jax.lax.scan(leap, jnp.asarray(x0, jnp.float32), keys)
    if save_every > 1:
        traj = traj[save_every - 1 :: save_every]
    return traj


def _check_obs_grid(n_leaps: int, n_obs: int, segments: int | None) -> int:
    """Validate the leap/observation/segment grid; returns save_every."""
    if n_leaps % n_obs:
        raise ValueError(
            f"n_obs={n_obs} does not divide n_leaps={n_leaps}: the "
            f"implied save_every would silently yield a wrong-length "
            f"trajectory — pick n_obs | n_leaps"
        )
    if segments is not None:
        if segments < 1:
            raise ValueError(f"segments must be >= 1, got {segments}")
        if n_obs % segments or n_leaps % segments:
            raise ValueError(
                f"segments={segments} must divide both n_obs={n_obs} "
                f"and n_leaps={n_leaps} (each segment emits a whole "
                f"block of observations)"
            )
    return n_leaps // n_obs


def tau_leap_segmented(*, x0: Sequence[float], stoich, prop: Callable,
                       rates_of: Callable, t1: float, n_leaps: int,
                       n_obs: int, segments: int,
                       channels: tuple, midpoint: bool = False
                       ) -> SegmentedSim:
    """Factor a tau-leap system into the segmented-simulation protocol.

    ``prop(x, rates) -> (n_reactions,)`` and ``rates_of(theta) ->
    (n_rates,)`` keep the carry a plain array pytree; ``channels`` is a
    tuple of ``(stat_name, species_index)`` in emit order. Per-leap keys
    are ``fold_in(sim_key, global_leap_index)`` — segment ``j`` is
    reproducible without replaying segments ``< j``.
    """
    save_every = _check_obs_grid(n_leaps, n_obs, segments)
    leaps_per_seg = n_leaps // segments
    obs_per_seg = n_obs // segments
    tau = t1 / n_leaps
    stoich = jnp.asarray(stoich, jnp.float32)
    x0 = jnp.asarray(x0, jnp.float32)

    def init(key, theta):
        return {"x": x0, "key": key,
                "rates": jnp.asarray(rates_of(theta), jnp.float32)}

    def step(carry, seg):
        rates = carry["rates"]

        def leap(x, i):
            k = jax.random.fold_in(carry["key"],
                                   seg * leaps_per_seg + i)
            a = jnp.maximum(prop(x, rates), 0.0)
            if midpoint:
                x_mid = jnp.maximum(x + 0.5 * tau * a @ stoich, 0.0)
                a = jnp.maximum(prop(x_mid, rates), 0.0)
            n_fire = jax.random.poisson(k, a * tau).astype(jnp.float32)
            x_new = jnp.maximum(x + n_fire @ stoich, 0.0)
            return x_new, x_new

        x_fin, traj = jax.lax.scan(
            leap, carry["x"],
            jnp.arange(leaps_per_seg, dtype=jnp.int32))
        saved = traj[save_every - 1 :: save_every]
        vals = jnp.concatenate([saved[:, si] for _n, si in channels])
        return {**carry, "x": x_fin}, vals

    layout = tuple((name, obs_per_seg) for name, _si in channels)
    return SegmentedSim(n_segments=segments, init=init, step=step,
                        layout=layout)


# --------------------------------------------------------------------------
# canonical systems
# --------------------------------------------------------------------------

_BD_STOICH = ((1.0,), (-1.0,))


def make_birth_death_model(x0: float = 40.0, t1: float = 10.0,
                           n_leaps: int = 200, n_obs: int = 20,
                           segments: int | None = None,
                           midpoint: bool = False,
                           name: str = "birth_death") -> JaxModel:
    """Birth-death process: 0 ->(b) X, X ->(d) 0; theta = (log10 b, log10 d).

    ``segments=K`` builds the segmented early-reject protocol (the full
    simulator is then the synthesized segment chain).
    """
    save_every = _check_obs_grid(n_leaps, n_obs, segments)
    stoich = jnp.asarray(_BD_STOICH)

    if segments is not None:
        seg = tau_leap_segmented(
            x0=[x0], stoich=_BD_STOICH,
            prop=lambda x, r: jnp.stack([r[0], r[1] * x[0]]),
            rates_of=lambda th: jnp.stack([10.0 ** th[0], 10.0 ** th[1]]),
            t1=t1, n_leaps=n_leaps, n_obs=n_obs, segments=segments,
            channels=(("x", 0),), midpoint=midpoint,
        )
        return JaxModel(None, ["log_b", "log_d"], name=name, segmented=seg)

    def sim(key, theta):
        b, d = 10.0 ** theta[0], 10.0 ** theta[1]

        def prop(x):
            return jnp.stack([b, d * x[0]])

        traj = tau_leap(key, jnp.asarray([x0]), stoich, prop, t1, n_leaps,
                        save_every=save_every, midpoint=midpoint)
        return {"x": traj[:, 0]}

    return JaxModel(sim, ["log_b", "log_d"], name=name)


def birth_death_prior() -> Distribution:
    return Distribution(
        log_b=RV("uniform", -1.0, 2.0),
        log_d=RV("uniform", -2.0, 2.0),
    )


_LV_STOICH = (
    (1.0, 0.0),   # prey birth
    (-1.0, 1.0),  # predation converts prey to predator
    (0.0, -1.0),  # predator death
)


def make_stochastic_lv_model(t1: float = 15.0, n_leaps: int = 300,
                             n_obs: int = 20,
                             segments: int | None = None,
                             midpoint: bool = False,
                             name: str = "stochastic_lv") -> JaxModel:
    """Stochastic Lotka-Volterra reaction network (3 channels):
    prey birth, predation, predator death; theta = log10 rates."""
    save_every = _check_obs_grid(n_leaps, n_obs, segments)
    stoich = jnp.asarray(_LV_STOICH)

    if segments is not None:
        seg = tau_leap_segmented(
            x0=[50.0, 100.0], stoich=_LV_STOICH,
            prop=lambda x, r: jnp.stack(
                [r[0] * x[0], r[1] * x[0] * x[1], r[2] * x[1]]),
            rates_of=lambda th: 10.0 ** th[:3],
            t1=t1, n_leaps=n_leaps, n_obs=n_obs, segments=segments,
            channels=(("pred", 1), ("prey", 0)), midpoint=midpoint,
        )
        return JaxModel(None, ["log_r1", "log_r2", "log_r3"], name=name,
                        segmented=seg)

    def sim(key, theta):
        r1, r2, r3 = 10.0 ** theta[0], 10.0 ** theta[1], 10.0 ** theta[2]

        def prop(x):
            prey, pred = x[0], x[1]
            return jnp.stack([r1 * prey, r2 * prey * pred, r3 * pred])

        traj = tau_leap(key, jnp.asarray([50.0, 100.0]), stoich, prop, t1,
                        n_leaps, save_every=save_every, midpoint=midpoint)
        return {"prey": traj[:, 0], "pred": traj[:, 1]}

    return JaxModel(sim, ["log_r1", "log_r2", "log_r3"], name=name)


def stochastic_lv_prior() -> Distribution:
    return Distribution(
        log_r1=RV("uniform", -1.0, 1.5),
        log_r2=RV("uniform", -3.0, 1.5),
        log_r3=RV("uniform", -1.0, 1.5),
    )


def observed_birth_death(seed: int = 0, **kwargs) -> dict:
    model = make_birth_death_model(**kwargs)
    theta = jnp.asarray([1.0, -0.5])  # b=10, d=0.32
    out = model.sim(jax.random.key(seed), theta)
    return {k: np.asarray(v) for k, v in out.items()}


def observed_stochastic_lv(seed: int = 0, **kwargs) -> dict:
    model = make_stochastic_lv_model(**kwargs)
    theta = jnp.asarray([0.2, -1.9, 0.1])
    out = model.sim(jax.random.key(seed), theta)
    return {k: np.asarray(v) for k, v in out.items()}
