"""Stochastic chemical kinetics via tau-leaping (config 3, BASELINE.md).

Reference analog: the pyABC Gillespie/chemical-reaction example notebooks.
Exact SSA has a data-dependent event count, which XLA cannot trace
(SURVEY.md §7.3.3); the framework therefore ships **tau-leaping** with a
fixed leap count — Poisson firing numbers per reaction channel per leap,
statically shaped, vmap/jit-able. For stiff regions a midpoint tau-leap
variant is provided.

Generic engine + two canonical systems: birth-death and the
Lotka-Volterra reaction network (stochastic LV).
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random_variables import RV, Distribution
from ..model import JaxModel


def tau_leap(key, x0, stoich: jnp.ndarray, propensity_fn: Callable,
             t1: float, n_leaps: int, save_every: int = 1,
             midpoint: bool = False):
    """Tau-leaping simulation of a reaction network.

    Parameters
    ----------
    x0: (n_species,) initial counts (float; kept >= 0).
    stoich: (n_reactions, n_species) stoichiometry matrix.
    propensity_fn: (x, *)-> (n_reactions,) nonneg rates.
    n_leaps: fixed number of tau leaps; tau = t1 / n_leaps.
    midpoint: midpoint (2nd-order) tau-leap.

    Returns (n_saved, n_species) trajectory of the post-leap states.
    """
    tau = t1 / n_leaps
    stoich = jnp.asarray(stoich, jnp.float32)

    def leap(carry, k):
        x = carry
        a = jnp.maximum(propensity_fn(x), 0.0)
        if midpoint:
            x_mid = jnp.maximum(x + 0.5 * tau * a @ stoich, 0.0)
            a = jnp.maximum(propensity_fn(x_mid), 0.0)
        n_fire = jax.random.poisson(k, a * tau).astype(jnp.float32)
        x_new = jnp.maximum(x + n_fire @ stoich, 0.0)
        return x_new, x_new

    keys = jax.random.split(key, n_leaps)
    _, traj = jax.lax.scan(leap, jnp.asarray(x0, jnp.float32), keys)
    if save_every > 1:
        traj = traj[save_every - 1 :: save_every]
    return traj


# --------------------------------------------------------------------------
# canonical systems
# --------------------------------------------------------------------------

def make_birth_death_model(x0: float = 40.0, t1: float = 10.0,
                           n_leaps: int = 200, n_obs: int = 20,
                           name: str = "birth_death") -> JaxModel:
    """Birth-death process: 0 ->(b) X, X ->(d) 0; theta = (log10 b, log10 d)."""
    stoich = jnp.asarray([[1.0], [-1.0]])
    save_every = n_leaps // n_obs

    def sim(key, theta):
        b, d = 10.0 ** theta[0], 10.0 ** theta[1]

        def prop(x):
            return jnp.stack([b, d * x[0]])

        traj = tau_leap(key, jnp.asarray([x0]), stoich, prop, t1, n_leaps,
                        save_every=save_every)
        return {"x": traj[:, 0]}

    return JaxModel(sim, ["log_b", "log_d"], name=name)


def birth_death_prior() -> Distribution:
    return Distribution(
        log_b=RV("uniform", -1.0, 2.0),
        log_d=RV("uniform", -2.0, 2.0),
    )


def make_stochastic_lv_model(t1: float = 15.0, n_leaps: int = 300,
                             n_obs: int = 20,
                             name: str = "stochastic_lv") -> JaxModel:
    """Stochastic Lotka-Volterra reaction network (3 channels):
    prey birth, predation, predator death; theta = log10 rates."""
    stoich = jnp.asarray([
        [1.0, 0.0],   # prey birth
        [-1.0, 1.0],  # predation converts prey to predator
        [0.0, -1.0],  # predator death
    ])
    save_every = n_leaps // n_obs

    def sim(key, theta):
        r1, r2, r3 = 10.0 ** theta[0], 10.0 ** theta[1], 10.0 ** theta[2]

        def prop(x):
            prey, pred = x[0], x[1]
            return jnp.stack([r1 * prey, r2 * prey * pred, r3 * pred])

        traj = tau_leap(key, jnp.asarray([50.0, 100.0]), stoich, prop, t1,
                        n_leaps, save_every=save_every)
        return {"prey": traj[:, 0], "pred": traj[:, 1]}

    return JaxModel(sim, ["log_r1", "log_r2", "log_r3"], name=name)


def stochastic_lv_prior() -> Distribution:
    return Distribution(
        log_r1=RV("uniform", -1.0, 1.5),
        log_r2=RV("uniform", -3.0, 1.5),
        log_r3=RV("uniform", -1.0, 1.5),
    )


def observed_birth_death(seed: int = 0, **kwargs) -> dict:
    model = make_birth_death_model(**kwargs)
    theta = jnp.asarray([1.0, -0.5])  # b=10, d=0.32
    out = model.sim(jax.random.key(seed), theta)
    return {k: np.asarray(v) for k, v in out.items()}
