"""Fixed-step ODE integrators, traceable and TPU-friendly.

The reference delegates ODE models to user code (scipy.integrate etc. inside
``Model.sample``, e.g. the Lotka-Volterra notebook doc/examples). On TPU,
data-dependent adaptive stepping defeats XLA (SURVEY.md §7.3.3), so the
framework ships bounded-iteration integrators in ``lax.scan``: classic RK4
and Tsitouras/Dormand-Prince-style embedded RK with a *fixed* step budget and
per-step error-controlled step-size clipping (PI controller on a bounded
grid) — statically shaped, vmap/jit/pmap-able.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def rk4_integrate(f: Callable, y0, t0: float, dt: float, n_steps: int,
                  save_every: int = 1, args=()):
    """Classic RK4 with fixed dt; returns (n_saved, dim) trajectory.

    ``save_every`` thins the saved trajectory (n_saved = n_steps//save_every).
    """

    def step(y, _):
        k1 = f(y, *args)
        k2 = f(y + 0.5 * dt * k1, *args)
        k3 = f(y + 0.5 * dt * k2, *args)
        k4 = f(y + dt * k3, *args)
        y_new = y + (dt / 6.0) * (k1 + 2 * k2 + 2 * k3 + k4)
        return y_new, y_new

    _, traj = jax.lax.scan(step, jnp.asarray(y0), None, length=n_steps)
    if save_every > 1:
        traj = traj[save_every - 1 :: save_every]
    return traj


def rk45_integrate(f: Callable, y0, t0: float, t1: float, n_steps: int,
                   rtol: float = 1e-4, atol: float = 1e-6, args=()):
    """Embedded Dormand-Prince (RK45) with bounded adaptive stepping.

    A fixed budget of ``n_steps`` stages is scanned; each stage either
    advances with the current step (error accepted) or retries with a
    smaller one (error rejected) — control flow is branchless `where`, so
    the program is statically shaped. Integration that exhausts the budget
    before t1 returns the state reached (and a flag).

    Returns (y_final, t_reached, ok).
    """
    # Dormand-Prince coefficients
    c = jnp.array([0.0, 1 / 5, 3 / 10, 4 / 5, 8 / 9, 1.0, 1.0])
    a = [
        jnp.array([]),
        jnp.array([1 / 5]),
        jnp.array([3 / 40, 9 / 40]),
        jnp.array([44 / 45, -56 / 15, 32 / 9]),
        jnp.array([19372 / 6561, -25360 / 2187, 64448 / 6561, -212 / 729]),
        jnp.array([9017 / 3168, -355 / 33, 46732 / 5247, 49 / 176,
                   -5103 / 18656]),
        jnp.array([35 / 384, 0.0, 500 / 1113, 125 / 192, -2187 / 6784,
                   11 / 84]),
    ]
    b5 = jnp.array([35 / 384, 0.0, 500 / 1113, 125 / 192, -2187 / 6784,
                    11 / 84, 0.0])
    b4 = jnp.array([5179 / 57600, 0.0, 7571 / 16695, 393 / 640,
                    -92097 / 339200, 187 / 2100, 1 / 40])

    y0 = jnp.asarray(y0, jnp.float32)
    h0 = (t1 - t0) / n_steps * 4.0

    def stage(carry, _):
        y, t, h, ok = carry
        h = jnp.minimum(h, t1 - t)
        ks = []
        for i in range(7):
            yi = y
            for j, aij in enumerate(a[i]):
                yi = yi + h * aij * ks[j]
            ks.append(f(yi, *args))
        k_mat = jnp.stack(ks)  # (7, dim)
        y5 = y + h * (b5 @ k_mat)
        y4 = y + h * (b4 @ k_mat)
        err = jnp.max(jnp.abs(y5 - y4) / (atol + rtol * jnp.abs(y5)))
        accept = (err <= 1.0) | (h <= (t1 - t0) * 1e-7)
        y_new = jnp.where(accept, y5, y)
        t_new = jnp.where(accept, t + h, t)
        # PI-ish controller, clipped
        scale = jnp.clip(0.9 * err ** (-0.2), 0.2, 5.0)
        h_new = jnp.clip(h * scale, (t1 - t0) * 1e-7, (t1 - t0))
        done = t_new >= t1 - 1e-9 * (t1 - t0)
        h_new = jnp.where(done, 0.0, h_new)
        return (y_new, t_new, h_new, ok & jnp.all(jnp.isfinite(y_new))), None

    (y, t, _, ok), _ = jax.lax.scan(
        stage, (y0, jnp.asarray(t0, jnp.float32), jnp.asarray(h0, jnp.float32),
                jnp.asarray(True)),
        None, length=n_steps,
    )
    return y, t, ok & (t >= t1 - 1e-6 * (t1 - t0))


def rk4_at_times(f: Callable, y0, ts, n_substeps: int, args=()):
    """RK4 trajectory sampled at the (uniformly spaced) times ``ts``.

    ``ts`` must start at t=ts[0] with constant spacing; each observation
    interval is integrated with ``n_substeps`` RK4 steps.
    """
    ts = jnp.asarray(ts)
    dt = (ts[1] - ts[0]) / n_substeps

    def obs_step(y, _):
        def micro(y, _):
            k1 = f(y, *args)
            k2 = f(y + 0.5 * dt * k1, *args)
            k3 = f(y + 0.5 * dt * k2, *args)
            k4 = f(y + dt * k3, *args)
            return y + (dt / 6.0) * (k1 + 2 * k2 + 2 * k3 + k4), None

        y_new, _ = jax.lax.scan(micro, y, None, length=n_substeps)
        return y_new, y_new

    _, traj = jax.lax.scan(obs_step, jnp.asarray(y0), None,
                           length=ts.shape[0] - 1)
    return jnp.concatenate([jnp.asarray(y0)[None], traj], axis=0)
