"""Conjugate Gaussian toy benchmark (config 1, BASELINE.md).

The correctness anchor: 2-parameter Gaussian with known conjugate posterior
(reference analog: pyABC's quickstart example & posterior-estimation tests).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random_variables import RV, Distribution
from ..model import JaxModel

PRIOR_MU_SD = 1.0
PRIOR_SD = (0.2, 1.5)  # uniform band for sigma
NOISE_N = 10  # iid observations per simulation


def make_gaussian_model(n: int = NOISE_N, name: str = "gaussian") -> JaxModel:
    """theta = (mu, sigma); returns mean/std of n iid N(mu, sigma) draws."""

    def sim(key, theta):
        mu, sigma = theta[0], jnp.abs(theta[1])
        x = mu + sigma * jax.random.normal(key, (n,))
        return {"mean": jnp.mean(x), "std": jnp.std(x)}

    return JaxModel(sim, ["mu", "sigma"], name=name)


def default_prior() -> Distribution:
    return Distribution(
        mu=RV("norm", 0.0, PRIOR_MU_SD),
        sigma=RV("uniform", PRIOR_SD[0], PRIOR_SD[1] - PRIOR_SD[0]),
    )


def make_mean_only_model(noise_sd: float = 0.5, name: str = "gauss1d"
                         ) -> JaxModel:
    """1-parameter version with exact conjugate posterior
    (x | theta ~ N(theta, noise_sd^2), theta ~ N(0,1))."""

    def sim(key, theta):
        return {"x": theta[0] + noise_sd * jax.random.normal(key)}

    return JaxModel(sim, ["theta"], name=name)


def mean_only_prior() -> Distribution:
    return Distribution(theta=RV("norm", 0.0, 1.0))


def conjugate_posterior(x_obs: float, noise_sd: float = 0.5,
                        prior_sd: float = 1.0) -> tuple[float, float]:
    var = 1.0 / (1.0 / prior_sd**2 + 1.0 / noise_sd**2)
    return var * x_obs / noise_sd**2, float(np.sqrt(var))
