"""Morpheus multicellular-simulation adapter (gated on a ``morpheus``
binary).

Reference parity: ``pyabc/external/morpheus.py::MorpheusModel`` (newer
reference versions; SURVEY.md §2.4 external row): a Morpheus model is an
XML file; sampled parameters are written into the XML via XPath-addressed
``value`` attributes, the ``morpheus`` CLI runs the simulation into a
temp directory, and the logger CSV comes back as summary statistics.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import tempfile
import xml.etree.ElementTree as ET

import numpy as np

from ..model import Model


def _require_morpheus(executable: str) -> str:
    path = shutil.which(executable)
    if path is None:
        raise RuntimeError(
            f"The Morpheus adapter needs a {executable!r} executable on "
            "PATH (install Morpheus, morpheus.gitlab.io). For other "
            "external simulators use ExternalModel."
        )
    return path


class MorpheusModel(Model):
    """A Morpheus XML model as a simulator.

    ``par_map``: parameter name -> XPath (ElementTree syntax, relative to
    the XML root) of the element whose ``value`` attribute receives the
    sampled value — the reference's parameter mapping contract.
    ``output_file``: the logger CSV Morpheus writes (TSV/CSV autodetected).
    """

    def __init__(self, model_file: str, par_map: dict[str, str],
                 executable: str = "morpheus",
                 output_file: str = "logger.csv",
                 timeout_s: float | None = None,
                 name: str | None = None):
        super().__init__(
            name=name or f"Morpheus({os.path.basename(model_file)})"
        )
        self.executable = _require_morpheus(executable)
        self.model_file = os.path.abspath(model_file)
        self.par_map = dict(par_map)
        self.output_file = output_file
        self.timeout_s = timeout_s

    def _write_model(self, pars, path: str) -> None:
        tree = ET.parse(self.model_file)
        root = tree.getroot()
        for key, xpath in self.par_map.items():
            node = root.find(xpath)
            if node is None:
                raise KeyError(
                    f"par_map[{key!r}]: XPath {xpath!r} matches no element "
                    f"in {self.model_file}"
                )
            node.set("value", repr(float(pars[key])))
        tree.write(path)

    def sample(self, pars):
        with tempfile.TemporaryDirectory(prefix="abc_morpheus_") as loc:
            model_xml = os.path.join(loc, "model.xml")
            self._write_model(pars, model_xml)
            cmd = [self.executable, "-file", model_xml, "-outdir", loc]
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=self.timeout_s,
            )
            if proc.returncode != 0:
                # surface morpheus's own diagnostics (ExternalHandler.run
                # pattern) instead of an opaque CalledProcessError
                raise RuntimeError(
                    f"morpheus command {' '.join(cmd)!r} failed "
                    f"(rc={proc.returncode}): {proc.stderr[-2000:]}"
                )
            out = os.path.join(loc, self.output_file)
            if not os.path.exists(out):
                raise RuntimeError(
                    f"morpheus produced no {self.output_file!r} in {loc}"
                )
            import pandas as pd

            df = pd.read_csv(out, sep=None, engine="python")
            return {c: df[c].to_numpy(np.float64) for c in df.columns}
