"""Julia-language model adapter (gated on a ``julia`` binary).

Reference parity: ``pyabc/external/julia`` (pyjulia binding, newer
versions). pyjulia is optional and absent here, so the adapter shells out
to the ``julia`` executable with a JSON file contract (same philosophy as
``ExternalModel`` / the R adapter).

User script contract: the ``.jl`` file defines a function taking a
``Dict{String,Float64}`` of parameters and returning a ``Dict`` of
statistics:

.. code-block:: julia

    function mymodel(pars)
        Dict("x" => pars["theta"] + 0.5 * randn())
    end
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import tempfile

import numpy as np

from ..model import Model


def _require_julia() -> str:
    path = shutil.which("julia")
    if path is None:
        raise RuntimeError(
            "The Julia adapter needs a 'julia' executable on PATH. For "
            "other external simulators use ExternalModel."
        )
    return path


_DRIVER = """
import JSON
include(ARGS[1])
pars = JSON.parsefile(ARGS[3])
res = getfield(Main, Symbol(ARGS[2]))(pars)
open(ARGS[4], "w") do io
    JSON.print(io, res)
end
"""


class JuliaModel(Model):
    """One Julia function as a simulator (``sample(pars) -> dict``)."""

    def __init__(self, script: str, function_name: str = "mymodel",
                 name: str | None = None):
        super().__init__(name=name or f"Julia::{function_name}")
        self.julia = _require_julia()
        self.script = os.path.abspath(script)
        self.function_name = function_name

    def sample(self, pars):
        with tempfile.TemporaryDirectory(prefix="abc_jl_") as loc:
            fin = os.path.join(loc, "in.json")
            fout = os.path.join(loc, "out.json")
            with open(fin, "w") as fh:
                json.dump({k: float(v) for k, v in pars.items()}, fh)
            driver = os.path.join(loc, "driver.jl")
            with open(driver, "w") as fh:
                fh.write(_DRIVER)
            subprocess.run(
                [self.julia, driver, self.script, self.function_name,
                 fin, fout],
                check=True, capture_output=True, text=True,
            )
            with open(fout) as fh:
                out = json.load(fh)
            return {k: np.asarray(v, np.float64) for k, v in out.items()}
