"""External (out-of-process) models — the non-JAX escape hatch.

Reference parity: ``pyabc/external/base.py::{ExternalHandler, ExternalModel,
ExternalSumStat, ExternalDistance}`` (SURVEY.md §2.4): simulators that are
arbitrary executables (R, Julia, compiled binaries, shell scripts) talk to
the framework through a file-based contract:

    executable [script] --in <infile> --out <outfile>

- infile:  one ``name value`` pair per line (the parameters).
- outfile: one ``name value [value ...]`` row per line (the summary
  statistics; multiple values become a 1-D array). ExternalDistance's
  outfile holds a single ``distance <float>`` line.

This is the ONE place the reference's CPU-process farming genuinely cannot
be replaced by XLA collectives (SURVEY.md §5.8): external models are
host-only and force the host sampler path (SingleCore/Multicore/Mapping),
where every worker just shells out. They are intentionally NOT traceable —
`ABCSMC._check_device_capable` routes around the device kernel.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import tempfile

import numpy as np

from ..model import Model, ModelResult


class ExternalHandler:
    """Runs an executable in managed temp locations (pyabc ExternalHandler)."""

    def __init__(self, executable: str, script: str | None = None,
                 tmp_dir: str | None = None, keep_tmp: bool = False,
                 prefix: str = "abc_ext_"):
        self.executable = executable
        self.script = script
        self.tmp_dir = tmp_dir
        self.keep_tmp = keep_tmp
        self.prefix = prefix

    def create_loc(self) -> str:
        return tempfile.mkdtemp(prefix=self.prefix, dir=self.tmp_dir)

    def cleanup(self, loc: str) -> None:
        if not self.keep_tmp:
            shutil.rmtree(loc, ignore_errors=True)

    def run(self, args: list[str], loc: str | None = None) -> dict:
        cmd = [self.executable]
        if self.script:
            cmd.append(self.script)
        cmd += args
        proc = subprocess.run(
            cmd, cwd=loc, capture_output=True, text=True
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"external command {' '.join(cmd)!r} failed "
                f"(rc={proc.returncode}): {proc.stderr[-2000:]}"
            )
        return {"returncode": proc.returncode, "stdout": proc.stdout,
                "stderr": proc.stderr}


def write_parameters(path: str, par) -> None:
    with open(path, "w") as fh:
        for k, v in dict(par).items():
            fh.write(f"{k} {float(v)!r}\n")


def read_sumstats(path: str) -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    with open(path) as fh:
        for line in fh:
            parts = line.split()
            if not parts:
                continue
            name, vals = parts[0], [float(v) for v in parts[1:]]
            out[name] = (
                np.asarray(vals[0]) if len(vals) == 1 else np.asarray(vals)
            )
    return out


class ExternalModel(Model):
    """A simulator that is an external executable (pyabc ExternalModel).

    ``ExternalModel("/bin/sh", script="sim.sh")`` calls
    ``/bin/sh sim.sh --in <params> --out <sumstats>`` per evaluation.
    """

    def __init__(self, executable: str, script: str | None = None,
                 name: str | None = None, **handler_kwargs):
        super().__init__(name=name or f"ExternalModel({executable})")
        self.handler = ExternalHandler(executable, script, **handler_kwargs)

    def sample(self, pars):
        loc = self.handler.create_loc()
        try:
            fin = os.path.join(loc, "in.txt")
            fout = os.path.join(loc, "out.txt")
            write_parameters(fin, pars)
            self.handler.run(["--in", fin, "--out", fout], loc=loc)
            return read_sumstats(fout)
        finally:
            self.handler.cleanup(loc)


class ExternalSumStat:
    """sumstat-calculator executable: maps a model output dir/file to
    statistics (pyabc ExternalSumStat). Used as a ``summary_statistics``
    callable on raw ExternalModel output written to a temp file."""

    def __init__(self, executable: str, script: str | None = None,
                 **handler_kwargs):
        self.handler = ExternalHandler(executable, script, **handler_kwargs)

    def __call__(self, model_output: dict) -> dict:
        loc = self.handler.create_loc()
        try:
            fin = os.path.join(loc, "in.txt")
            fout = os.path.join(loc, "out.txt")
            with open(fin, "w") as fh:
                for k, v in model_output.items():
                    vals = " ".join(repr(float(x)) for x in np.ravel(v))
                    fh.write(f"{k} {vals}\n")
            self.handler.run(["--in", fin, "--out", fout], loc=loc)
            return read_sumstats(fout)
        finally:
            self.handler.cleanup(loc)


class ExternalDistance:
    """distance executable: reads two sum-stat files, writes
    ``distance <float>`` (pyabc ExternalDistance). Wrap with
    ``to_distance`` / pass directly as the distance callable."""

    def __init__(self, executable: str, script: str | None = None,
                 **handler_kwargs):
        self.handler = ExternalHandler(executable, script, **handler_kwargs)

    def __call__(self, x: dict, x_0: dict) -> float:
        loc = self.handler.create_loc()
        try:
            fx = os.path.join(loc, "x.txt")
            fx0 = os.path.join(loc, "x0.txt")
            fout = os.path.join(loc, "out.txt")
            for path, stats in ((fx, x), (fx0, x_0)):
                with open(path, "w") as fh:
                    for k, v in stats.items():
                        vals = " ".join(repr(float(s)) for s in np.ravel(v))
                        fh.write(f"{k} {vals}\n")
            self.handler.run(
                ["--in", fx, "--in0", fx0, "--out", fout], loc=loc
            )
            out = read_sumstats(fout)
            return float(out["distance"])
        finally:
            self.handler.cleanup(loc)
