"""R-language model adapter (gated on an ``Rscript`` binary).

Reference parity: ``pyabc/external/r/r_rpy2.py::R`` — load model /
summary-statistics / distance functions and the observation from a user's
``.R`` script. The reference binds in-process via rpy2; rpy2 (and R) are
optional here, so the adapter shells out to ``Rscript`` with a file-based
contract instead (same philosophy as ``ExternalModel``): parameters go in
as a CSV, the R function's returned named list/vector comes back as a CSV.

User script contract (names configurable):

.. code-block:: r

    myModel <- function(pars) list(x = rnorm(1, pars$theta, 0.5))
    mySumStatData <- list(x = 1.0)
"""
from __future__ import annotations

import os
import shutil
import subprocess
import tempfile

import numpy as np

from ..model import Model


def _require_rscript() -> str:
    path = shutil.which("Rscript")
    if path is None:
        raise RuntimeError(
            "The R adapter needs an 'Rscript' executable on PATH (install "
            "R). For non-R external simulators use ExternalModel."
        )
    return path


_DRIVER = r"""
args <- commandArgs(trailingOnly = TRUE)
source(args[[1]])
fin <- args[[3]]; fout <- args[[4]]
pars <- as.list(read.csv(fin))
res <- do.call(args[[2]], list(pars))
write.csv(as.data.frame(res), fout, row.names = FALSE)
"""

_EVAL_DRIVER = r"""
args <- commandArgs(trailingOnly = TRUE)
source(args[[1]])
obj <- get(args[[2]])
if (is.function(obj)) obj <- obj()
write.csv(as.data.frame(obj), args[[3]], row.names = FALSE)
"""


def _read_csv_columns(path: str) -> dict[str, np.ndarray]:
    import pandas as pd

    df = pd.read_csv(path)
    return {c: df[c].to_numpy() for c in df.columns}


class RModel(Model):
    """One R function as a simulator (``sample(pars) -> dict``)."""

    def __init__(self, script: str, function_name: str = "myModel",
                 name: str | None = None):
        super().__init__(name=name or f"R::{function_name}")
        self.rscript = _require_rscript()
        self.script = os.path.abspath(script)
        self.function_name = function_name

    def sample(self, pars):
        with tempfile.TemporaryDirectory(prefix="abc_r_") as loc:
            fin = os.path.join(loc, "in.csv")
            fout = os.path.join(loc, "out.csv")
            with open(fin, "w") as fh:
                keys = list(pars.keys())
                fh.write(",".join(keys) + "\n")
                fh.write(",".join(repr(float(pars[k])) for k in keys) + "\n")
            driver = os.path.join(loc, "driver.R")
            with open(driver, "w") as fh:
                fh.write(_DRIVER)
            subprocess.run(
                [self.rscript, driver, self.script, self.function_name,
                 fin, fout],
                check=True, capture_output=True, text=True,
            )
            return _read_csv_columns(fout)


class R:
    """Entry point mirroring the reference's ``pyabc.external.R``:
    ``R("script.R").model()`` / ``.observation()``."""

    def __init__(self, script: str):
        self.rscript = _require_rscript()
        self.script = os.path.abspath(script)

    def model(self, function_name: str = "myModel") -> RModel:
        return RModel(self.script, function_name)

    def observation(self, name: str = "mySumStatData"
                    ) -> dict[str, np.ndarray]:
        """Evaluate a variable (or 0-ary function) from the script as the
        observed summary statistics."""
        with tempfile.TemporaryDirectory(prefix="abc_r_") as loc:
            fout = os.path.join(loc, "obs.csv")
            driver = os.path.join(loc, "driver.R")
            with open(driver, "w") as fh:
                fh.write(_EVAL_DRIVER)
            subprocess.run(
                [self.rscript, driver, self.script, name, fout],
                check=True, capture_output=True, text=True,
            )
            return _read_csv_columns(fout)
