"""External-process model adapters (reference ``pyabc/external/``)."""
from .base import (
    ExternalDistance,
    ExternalHandler,
    ExternalModel,
    ExternalSumStat,
)

__all__ = [
    "ExternalHandler",
    "ExternalModel",
    "ExternalSumStat",
    "ExternalDistance",
]
