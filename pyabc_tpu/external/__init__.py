"""External-process model adapters (reference ``pyabc/external/``)."""
from .base import (
    ExternalDistance,
    ExternalHandler,
    ExternalModel,
    ExternalSumStat,
)
from .julia import JuliaModel
from .morpheus import MorpheusModel
from .r import R, RModel

__all__ = [
    "ExternalHandler",
    "ExternalModel",
    "ExternalSumStat",
    "ExternalDistance",
    "R",
    "RModel",
    "JuliaModel",
    "MorpheusModel",
]
