"""Fast weighted index draw for the host proposal path.

Reference parity: ``pyabc/random_choice.py::fast_random_choice`` — the
ancestor/model draw happens once per proposal in every host sampler
worker, and ``np.random.choice`` pays ~microseconds of validation and
normalization overhead per call. For small n an inline cumulative-sum
scan beats it by an order of magnitude; for large n ``np.searchsorted``
over the cumsum is used.

The device path never calls this (``jax.random.categorical`` draws whole
batches in-kernel); this exists for the reference-faithful scalar closure
(`inference/util.py::generate_valid_proposal`).
"""
from __future__ import annotations

import numpy as np

#: below this many weights the plain python scan wins over vectorization
_SMALL_N = 16


def fast_random_choice(weights) -> int:
    """Draw an index ~ ``weights``. Both branches normalize by the running
    total (like ``np.random.choice`` after its validation), so unnormalized
    input skews nothing — a caller bug cannot silently dump missing
    probability mass on the last index."""
    n = len(weights)
    if n <= _SMALL_N:
        total = 0.0
        for i in range(n):
            total += weights[i]
        u = np.random.uniform(high=total)
        acc = 0.0
        for i in range(n - 1):
            acc += weights[i]
            if u < acc:
                return i
        return n - 1
    cdf = np.cumsum(weights)
    u = np.random.uniform(high=cdf[-1])
    return int(np.searchsorted(cdf, u, side="right").clip(0, n - 1))
