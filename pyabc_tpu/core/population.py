"""Particle and Population containers.

Reference parity: ``pyabc/population.py::{Particle, Population}``. The host
`Population` keeps the reference's API (per-model weight normalization,
``get_model_probabilities``, ``get_distribution``, ``get_weighted_distances``,
``get_for_keys``) but is backed by dense struct-of-arrays storage — the same
arrays the device generation kernel produced — instead of a list of Python
objects. `Particle` views are materialized lazily for API compatibility.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

import numpy as np
import pandas as pd

from .parameters import Parameter, ParameterSpace
from .sumstat_spec import SumStatSpec


@dataclass
class Particle:
    """A single weighted particle (mirrors pyabc Particle).

    ``preliminary`` marks look-ahead particles whose weight still awaits
    correction (reference: redis look-ahead mode, SURVEY.md §2.3).
    """

    m: int
    parameter: Parameter
    weight: float
    sum_stat: dict
    distance: float
    accepted: bool = True
    preliminary: bool = False
    #: density of (m, parameter) under the proposal it was drawn from
    #: (prior at t=0, transition mixture at t>0); recorded for the
    #: AcceptanceRateScheme's record reweighting (reference
    #: transition_pd_prev) — NaN when not recorded
    proposal_pd: float = float("nan")
    #: repr of a simulate_one exception caught by a worker running with
    #: exception capture (reference ``abc-redis-worker --catch``): the
    #: evaluation ships as this rejected error-record instead of killing
    #: the worker loop; error particles carry no usable sum stats
    error: str | None = None


class Population:
    """A weighted generation of particles, stored struct-of-arrays.

    Total weight over all models is normalized to 1; model probability
    p(m) = sum of weights of model-m particles; within-model distribution
    weights are w / p(m) (reference semantics).
    """

    def __init__(
        self,
        *,
        ms: np.ndarray,
        thetas: np.ndarray,
        weights: np.ndarray,
        distances: np.ndarray,
        sumstats: np.ndarray | None,
        spaces: Sequence[ParameterSpace],
        sumstat_spec: SumStatSpec,
        model_names: Sequence[str] | None = None,
        proposal_ids: np.ndarray | None = None,
    ):
        n = len(ms)
        assert thetas.shape[0] == n and weights.shape[0] == n
        assert distances.shape[0] == n
        assert sumstats is None or sumstats.shape[0] == n
        self.ms = np.asarray(ms, dtype=np.int32)
        self.thetas = np.asarray(thetas, dtype=np.float64)
        w = np.asarray(weights, dtype=np.float64)
        total = w.sum()
        if not np.isfinite(total) or total <= 0:
            raise ValueError(f"population total weight invalid: {total}")
        self.weights = w / total
        self.distances = np.asarray(distances, dtype=np.float64)
        #: None when the sampler skipped the sumstat fetch
        #: (History.store_sum_stats turned it off for this generation)
        self.sumstats = (
            np.asarray(sumstats, dtype=np.float64)
            if sumstats is not None else None
        )
        self.spaces = list(spaces)
        self.sumstat_spec = sumstat_spec
        self.model_names = (
            list(model_names)
            if model_names is not None
            else [f"m{m}" for m in range(len(self.spaces))]
        )
        #: provenance slot ids from the sampler (deterministic trim order)
        self.proposal_ids = (
            np.asarray(proposal_ids) if proposal_ids is not None else None
        )

    # ------------------------------------------------------------------ sizes
    def __len__(self) -> int:
        return len(self.ms)

    @property
    def n_models(self) -> int:
        return len(self.spaces)

    # ------------------------------------------------------- reference API
    @classmethod
    def from_particles(
        cls,
        particles: Sequence[Particle],
        spaces: Sequence[ParameterSpace],
        sumstat_spec: SumStatSpec,
        model_names: Sequence[str] | None = None,
    ) -> "Population":
        d_max = max(s.dim for s in spaces)
        ms = np.asarray([p.m for p in particles], dtype=np.int32)
        thetas = np.stack(
            [
                spaces[p.m].pad_to(spaces[p.m].to_array(p.parameter), d_max)
                for p in particles
            ]
        )
        weights = np.asarray([p.weight for p in particles])
        distances = np.asarray([p.distance for p in particles])
        sumstats = np.stack(
            [np.asarray(sumstat_spec.flatten_host(p.sum_stat))
             for p in particles]
        )
        return cls(
            ms=ms, thetas=thetas, weights=weights, distances=distances,
            sumstats=sumstats, spaces=spaces, sumstat_spec=sumstat_spec,
            model_names=model_names,
        )

    def particles(self) -> list[Particle]:
        """Materialize the list-of-Particle view (reference representation)."""
        out = []
        for i in range(len(self)):
            m = int(self.ms[i])
            space = self.spaces[m]
            out.append(
                Particle(
                    m=m,
                    parameter=space.to_dict(self.thetas[i, : space.dim]),
                    weight=float(self.weights[i]),
                    sum_stat=self.sumstat_spec.unflatten(self.sumstats[i]),
                    distance=float(self.distances[i]),
                    accepted=True,
                )
            )
        return out

    def get_model_probabilities(self) -> pd.DataFrame:
        """DataFrame with column 'p' indexed by model index (reference API)."""
        probs = self.model_probabilities_array()
        alive = np.flatnonzero(probs > 0)
        return pd.DataFrame({"p": probs[alive]}, index=pd.Index(alive, name="m"))

    def model_probabilities_array(self) -> np.ndarray:
        probs = np.zeros(self.n_models)
        np.add.at(probs, self.ms, self.weights)
        return probs

    def get_alive_models(self) -> list[int]:
        return [int(m) for m in np.unique(self.ms)]

    def nr_of_models_alive(self) -> int:
        return len(np.unique(self.ms))

    def get_distribution(self, m: int = 0) -> tuple[pd.DataFrame, np.ndarray]:
        """(parameters DataFrame, within-model normalized weights) for model m."""
        mask = self.ms == m
        if not mask.any():
            raise KeyError(f"no particles for model {m}")
        space = self.spaces[m]
        df = pd.DataFrame(
            self.thetas[mask][:, : space.dim], columns=list(space.names)
        )
        w = self.weights[mask]
        return df, w / w.sum()

    def get_weighted_distances(self) -> pd.DataFrame:
        """DataFrame ['distance', 'w'] with overall-normalized weights."""
        return pd.DataFrame({"distance": self.distances, "w": self.weights})

    def get_weighted_sum_stats(self) -> tuple[np.ndarray, np.ndarray]:
        """(weights, flat sumstat matrix) — reference returns (w, list-of-dicts)."""
        return self.weights, self.sumstats

    def get_accepted_sum_stats(self) -> list[dict]:
        return [self.sumstat_spec.unflatten(s) for s in self.sumstats]

    def get_for_keys(self, keys) -> dict:
        """Subset view by keys: weight / distance / parameter / sum_stat."""
        out = {}
        for k in keys:
            if k == "weight":
                out[k] = self.weights
            elif k == "distance":
                out[k] = self.distances
            elif k == "parameter":
                out[k] = self.thetas
            elif k == "sum_stat":
                out[k] = self.sumstats
            else:
                raise KeyError(k)
        return out

    def update_weights(self, new_weights: np.ndarray) -> None:
        """Replace weights (look-ahead correction path) and renormalize."""
        w = np.asarray(new_weights, dtype=np.float64)
        total = w.sum()
        if not np.isfinite(total) or total <= 0:
            raise ValueError(f"population total weight invalid: {total}")
        self.weights = w / total

    def __repr__(self):
        return (
            f"Population(n={len(self)}, models={self.get_alive_models()}, "
            f"d_max={self.thetas.shape[1]})"
        )
