"""Summary-statistic flattening registry.

Simulators return a pytree-dict ``{name: array}`` (the analog of the
reference's sum-stat dicts, ``pyabc/model.py::Model.summary_statistics``).
Device math wants one dense vector per particle, so ``SumStatSpec`` records
shapes/offsets once and provides traceable flatten/unflatten. Per-flat-entry
labels (``"name"`` or ``"name[i]"``) give `AdaptivePNormDistance` its
per-statistic weight registry, mirroring the reference's dict-keyed weights.
"""
from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np


class SumStatSpec:
    def __init__(self, example: Mapping[str, np.ndarray | jnp.ndarray | float]):
        self.names: tuple[str, ...] = tuple(sorted(example.keys()))
        self.shapes: dict[str, tuple[int, ...]] = {}
        self.sizes: dict[str, int] = {}
        self.offsets: dict[str, int] = {}
        off = 0
        for n in self.names:
            shp = tuple(np.shape(example[n]))
            size = int(np.prod(shp)) if shp else 1
            self.shapes[n] = shp
            self.sizes[n] = size
            self.offsets[n] = off
            off += size
        self.total_size = off

    def flatten(self, stats: Mapping) -> jnp.ndarray:
        """dict of arrays -> (total_size,) f32 vector. Traceable."""
        parts = [jnp.ravel(jnp.asarray(stats[n], jnp.float32)) for n in self.names]
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def flatten_host(self, stats: Mapping) -> np.ndarray:
        """Numpy twin of flatten: NO JAX. The host sampler path runs inside
        forked multiprocess workers, where touching a JAX backend deadlocks;
        host distances/acceptors must flatten through this."""
        parts = [
            np.ravel(np.asarray(stats[n], np.float64)) for n in self.names
        ]
        return np.concatenate(parts) if len(parts) > 1 else parts[0]

    def unflatten(self, vec) -> dict[str, np.ndarray]:
        vec = np.asarray(vec)
        out = {}
        for n in self.names:
            off, size, shp = self.offsets[n], self.sizes[n], self.shapes[n]
            out[n] = vec[..., off : off + size].reshape(vec.shape[:-1] + shp)
        return out

    def unflatten_traceable(self, vec) -> dict:
        """Traceable dict view of a flat vector (jnp, keeps gradients/trace)."""
        out = {}
        for n in self.names:
            off, size, shp = self.offsets[n], self.sizes[n], self.shapes[n]
            sl = jax.lax.dynamic_slice_in_dim(vec, off, size, axis=-1)
            out[n] = sl.reshape(vec.shape[:-1] + shp)
        return out

    def labels(self) -> list[str]:
        """One label per flat entry: 'name' for scalars, 'name[i]' else."""
        out = []
        for n in self.names:
            if self.sizes[n] == 1 and self.shapes[n] == ():
                out.append(n)
            else:
                out.extend(f"{n}[{i}]" for i in range(self.sizes[n]))
        return out

    def __eq__(self, other):
        return (
            isinstance(other, SumStatSpec)
            and self.names == other.names
            and self.shapes == other.shapes
        )

    def __repr__(self):
        return f"SumStatSpec({dict(self.shapes)})"
