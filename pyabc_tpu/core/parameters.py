"""Parameter containers.

Reference parity: ``pyabc/parameters.py::Parameter`` (a dict of floats with
attribute access). TPU-first shift (SURVEY.md §7.1): the device-side
representation is a dense ``(n, d)`` array; ``ParameterSpace`` is the
name<->column registry that keeps the dict-like facade at the API boundary.
"""
from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np


class Parameter(dict):
    """A single parameter vector as a dict of floats.

    Mirrors ``pyabc/parameters.py::Parameter``: plain mapping semantics plus
    attribute access and a ``copy()`` that preserves the type.
    """

    def __getattr__(self, name: str):
        try:
            return self[name]
        except KeyError as e:  # pragma: no cover - defensive
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value):
        self[name] = value

    def copy(self) -> "Parameter":
        return Parameter(self)


class ParameterSpace:
    """Registry mapping parameter names to columns of a dense theta array.

    The device-side population stores parameters as ``theta: f32[n, dim]``;
    this class is the single source of truth for the column order, so the
    user-facing dict API (``Parameter``) and storage layer stay name-based
    while all device math stays dense.
    """

    def __init__(self, names: Iterable[str]):
        self.names: tuple[str, ...] = tuple(names)
        if len(set(self.names)) != len(self.names):
            raise ValueError(f"duplicate parameter names: {self.names}")
        self._index = {n: i for i, n in enumerate(self.names)}

    @property
    def dim(self) -> int:
        return len(self.names)

    def index(self, name: str) -> int:
        return self._index[name]

    def to_array(self, par: Mapping[str, float]) -> np.ndarray:
        """Dict -> (dim,) float array in registry column order."""
        return np.asarray([float(par[n]) for n in self.names], dtype=np.float64)

    def to_dict(self, arr) -> Parameter:
        """(dim,) array -> Parameter dict."""
        arr = np.asarray(arr)
        return Parameter({n: float(arr[i]) for i, n in enumerate(self.names)})

    def batch_to_arrays(self, pars: Iterable[Mapping[str, float]]) -> np.ndarray:
        return np.stack([self.to_array(p) for p in pars], axis=0)

    def pad_to(self, arr: np.ndarray, d_max: int) -> np.ndarray:
        """Pad the trailing parameter axis with zeros up to ``d_max``.

        Multi-model populations with heterogeneous parameter dimensions store
        theta padded to the max dim; inactive columns carry zeros and are
        masked out of all transition / pdf math.
        """
        arr = np.asarray(arr)
        if arr.shape[-1] == d_max:
            return arr
        pad = [(0, 0)] * (arr.ndim - 1) + [(0, d_max - arr.shape[-1])]
        return np.pad(arr, pad)

    def __len__(self) -> int:
        return self.dim

    def __repr__(self) -> str:
        return f"ParameterSpace({list(self.names)})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ParameterSpace) and other.names == self.names

    def __hash__(self) -> int:
        return hash(self.names)
