"""Random variables and priors, JAX-native.

Reference parity: ``pyabc/random_variables.py::{RVBase, RV, RVDecorator,
LowerBoundDecorator, Distribution}``. The reference wraps arbitrary
``scipy.stats`` frozen distributions; here each supported family has a
hand-rolled ``jax.random`` sampler and a ``jax.scipy.stats`` (or hand-written)
log-pdf so that prior sampling and density evaluation can live INSIDE the
jitted generation kernel. A scipy escape hatch (`ScipyRV`) is provided for
host-side use (it cannot be traced, and forces the host proposal path).
"""
from __future__ import annotations

import math
from abc import ABC, abstractmethod
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from .parameters import Parameter, ParameterSpace

_LOG_2PI = math.log(2.0 * math.pi)


class RVBase(ABC):
    """Abstract 1-D random variable (mirrors pyabc RVBase: rvs/pdf/cdf)."""

    #: True if the variable takes integer values only
    discrete: bool = False
    #: True if rvs/logpdf are jnp-traceable (device path eligible)
    traceable: bool = True

    @abstractmethod
    def rvs(self, key, shape=()):
        """Sample with a jax PRNG key."""

    @abstractmethod
    def logpdf(self, x):
        """Log density (or log pmf) at x — traceable jnp code."""

    def pdf(self, x):
        return jnp.exp(self.logpdf(x))

    def cdf(self, x):  # pragma: no cover - overridden where closed form exists
        raise NotImplementedError

    # -- host (fork-safe, JAX-free) path -------------------------------------
    # The multiprocess samplers fork workers; initializing a JAX backend
    # after fork deadlocks (classic fork-after-XLA-init, worse under a TPU
    # tunnel). Builtin families override these with pure scipy/numpy
    # implementations; this fallback routes through JAX and is only safe
    # in-process (documented escape hatch for custom RVBase subclasses).

    def rvs_host(self, rng=None):
        """Draw one sample using numpy RNG state (no JAX). ``rng`` is a
        ``np.random.Generator``/``RandomState`` or None (global np.random)."""
        r = rng if rng is not None else np.random
        draw = getattr(r, "integers", None) or r.randint  # Generator vs legacy
        seed = int(draw(0, 2**31 - 1))
        return np.asarray(self.rvs(jax.random.key(seed)))

    def logpdf_host(self, x) -> float:
        """Log density at x as a plain float (no JAX where overridden)."""
        return float(np.asarray(self.logpdf(x)))


class RV(RVBase):
    """Named-family random variable with jax-native sampling and log-pdf.

    ``RV("uniform", loc, scale)`` etc. — the constructor signature follows the
    reference's scipy conventions (loc/scale style args) so user code ports
    1:1. Supported families: uniform, norm, lognorm, expon, gamma, beta,
    laplace, cauchy, t (student), truncnorm, randint (discrete uniform on
    [low, high)), binom, poisson, nbinom.
    """

    def __init__(self, name: str, *args, **kwargs):
        self.name = name
        self.args = args
        self.kwargs = kwargs
        spec = _FAMILIES.get(name)
        if spec is None:
            raise ValueError(
                f"unknown RV family {name!r}; supported: {sorted(_FAMILIES)}"
            )
        self._params = spec["canon"](*args, **kwargs)
        self._spec = spec
        self.discrete = spec.get("discrete", False)

    def rvs(self, key, shape=()):
        return self._spec["rvs"](key, shape, *self._params)

    def logpdf(self, x):
        return self._spec["logpdf"](x, *self._params)

    def cdf(self, x):
        fn = self._spec.get("cdf")
        if fn is None:
            raise NotImplementedError(f"cdf for {self.name}")
        return fn(x, *self._params)

    # fork-safe host path: the canonical params follow scipy conventions by
    # design, so the scipy.stats frozen distribution of the same name is the
    # exact host twin of the jax sampler/logpdf
    def _frozen(self):
        frozen = getattr(self, "_frozen_cache", None)
        if frozen is None:
            import scipy.stats as st

            if self.name == "lognorm":
                s, scale = self._params
                frozen = st.lognorm(s, 0.0, scale)
            else:
                frozen = getattr(st, self.name)(*self._params)
            self._frozen_cache = frozen
        return frozen

    def rvs_host(self, rng=None):
        return np.asarray(self._frozen().rvs(random_state=rng))

    def logpdf_host(self, x) -> float:
        fr = self._frozen()
        return float(fr.logpmf(x) if self.discrete else fr.logpdf(x))

    def __repr__(self) -> str:
        return f"RV({self.name!r}, {', '.join(map(repr, self.args))})"


# ---------------------------------------------------------------------------
# Family definitions.  Each: canon(*args) -> tuple of floats, rvs, logpdf.
# Parameterizations follow scipy.stats so reference-user code ports directly.
# ---------------------------------------------------------------------------

def _canon_loc_scale(loc=0.0, scale=1.0):
    return (float(loc), float(scale))


def _uniform_rvs(key, shape, loc, scale):
    return jax.random.uniform(key, shape, minval=loc, maxval=loc + scale)


def _uniform_logpdf(x, loc, scale):
    inside = (x >= loc) & (x <= loc + scale)
    return jnp.where(inside, -jnp.log(scale), -jnp.inf)


def _norm_rvs(key, shape, loc, scale):
    return loc + scale * jax.random.normal(key, shape)


def _norm_logpdf(x, loc, scale):
    z = (x - loc) / scale
    return -0.5 * (z * z + _LOG_2PI) - jnp.log(scale)


def _norm_cdf(x, loc, scale):
    return 0.5 * (1.0 + jax.scipy.special.erf((x - loc) / (scale * math.sqrt(2.0))))


def _canon_lognorm(s, loc=0.0, scale=1.0):
    if loc != 0.0:
        raise ValueError("lognorm loc!=0 unsupported (non-traceable support shift)")
    return (float(s), float(scale))


def _lognorm_rvs(key, shape, s, scale):
    return scale * jnp.exp(s * jax.random.normal(key, shape))


def _lognorm_logpdf(x, s, scale):
    safe = jnp.maximum(x, 1e-300)
    z = jnp.log(safe / scale) / s
    out = -0.5 * (z * z + _LOG_2PI) - jnp.log(safe * s)
    return jnp.where(x > 0, out, -jnp.inf)


def _expon_rvs(key, shape, loc, scale):
    return loc + scale * jax.random.exponential(key, shape)


def _expon_logpdf(x, loc, scale):
    z = (x - loc) / scale
    return jnp.where(z >= 0, -z - jnp.log(scale), -jnp.inf)


def _canon_gamma(a, loc=0.0, scale=1.0):
    return (float(a), float(loc), float(scale))


def _gamma_rvs(key, shape, a, loc, scale):
    return loc + scale * jax.random.gamma(key, a, shape)


def _gamma_logpdf(x, a, loc, scale):
    z = (x - loc) / scale
    out = jax.scipy.stats.gamma.logpdf(z, a) - jnp.log(scale)
    return jnp.where(z > 0, out, -jnp.inf)


def _canon_beta(a, b, loc=0.0, scale=1.0):
    return (float(a), float(b), float(loc), float(scale))


def _beta_rvs(key, shape, a, b, loc, scale):
    return loc + scale * jax.random.beta(key, a, b, shape)


def _beta_logpdf(x, a, b, loc, scale):
    z = (x - loc) / scale
    out = jax.scipy.stats.beta.logpdf(z, a, b) - jnp.log(scale)
    return jnp.where((z > 0) & (z < 1), out, -jnp.inf)


def _laplace_rvs(key, shape, loc, scale):
    return loc + scale * jax.random.laplace(key, shape)


def _laplace_logpdf(x, loc, scale):
    return -jnp.abs(x - loc) / scale - jnp.log(2.0 * scale)


def _cauchy_rvs(key, shape, loc, scale):
    return loc + scale * jax.random.cauchy(key, shape)


def _cauchy_logpdf(x, loc, scale):
    z = (x - loc) / scale
    return -jnp.log(math.pi * scale * (1.0 + z * z))


def _canon_t(df, loc=0.0, scale=1.0):
    return (float(df), float(loc), float(scale))


def _t_rvs(key, shape, df, loc, scale):
    return loc + scale * jax.random.t(key, df, shape)


def _t_logpdf(x, df, loc, scale):
    z = (x - loc) / scale
    return jax.scipy.stats.t.logpdf(z, df) - jnp.log(scale)


def _canon_truncnorm(a, b, loc=0.0, scale=1.0):
    return (float(a), float(b), float(loc), float(scale))


def _truncnorm_rvs(key, shape, a, b, loc, scale):
    return loc + scale * jax.random.truncated_normal(key, a, b, shape)


def _truncnorm_logpdf(x, a, b, loc, scale):
    z = (x - loc) / scale
    lognorm_const = jnp.log(_norm_cdf(b, 0.0, 1.0) - _norm_cdf(a, 0.0, 1.0))
    out = _norm_logpdf(z, 0.0, 1.0) - jnp.log(scale) - lognorm_const
    return jnp.where((z >= a) & (z <= b), out, -jnp.inf)


def _canon_randint(low, high):
    return (int(low), int(high))


def _randint_rvs(key, shape, low, high):
    return jax.random.randint(key, shape, low, high)


def _randint_logpdf(x, low, high):
    inside = (x >= low) & (x < high)
    return jnp.where(inside, -jnp.log(float(high - low)), -jnp.inf)


def _canon_binom(n, p):
    return (int(n), float(p))


def _binom_rvs(key, shape, n, p):
    return jax.random.binomial(key, n, p, shape)


def _binom_logpdf(x, n, p):
    x = jnp.asarray(x)
    xf = x.astype(jnp.float32)
    logc = (
        jax.scipy.special.gammaln(n + 1.0)
        - jax.scipy.special.gammaln(xf + 1.0)
        - jax.scipy.special.gammaln(n - xf + 1.0)
    )
    # xlogy handles the p=0 / p=1 support boundaries (0*log 0 = 0, not NaN)
    out = logc + jax.scipy.special.xlogy(xf, p) + jax.scipy.special.xlog1py(
        n - xf, -p
    )
    return jnp.where((x >= 0) & (x <= n), out, -jnp.inf)


def _canon_poisson(mu):
    return (float(mu),)


def _poisson_rvs(key, shape, mu):
    return jax.random.poisson(key, mu, shape)


def _poisson_logpdf(x, mu):
    xf = jnp.asarray(x, jnp.float32)
    out = xf * jnp.log(mu) - mu - jax.scipy.special.gammaln(xf + 1.0)
    return jnp.where(xf >= 0, out, -jnp.inf)


def _canon_nbinom(n, p):
    return (float(n), float(p))


def _nbinom_rvs(key, shape, n, p):
    # Gamma-Poisson mixture: lam ~ Gamma(n, (1-p)/p), x ~ Poisson(lam)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, n, shape) * (1.0 - p) / p
    return jax.random.poisson(k2, lam)


def _nbinom_logpdf(x, n, p):
    xf = jnp.asarray(x, jnp.float32)
    logc = (
        jax.scipy.special.gammaln(xf + n)
        - jax.scipy.special.gammaln(n)
        - jax.scipy.special.gammaln(xf + 1.0)
    )
    out = logc + n * jnp.log(p) + xf * jnp.log1p(-p)
    return jnp.where(xf >= 0, out, -jnp.inf)


_FAMILIES = {
    "uniform": dict(canon=_canon_loc_scale, rvs=_uniform_rvs, logpdf=_uniform_logpdf,
                    cdf=lambda x, lo, sc: jnp.clip((x - lo) / sc, 0.0, 1.0)),
    "norm": dict(canon=_canon_loc_scale, rvs=_norm_rvs, logpdf=_norm_logpdf,
                 cdf=_norm_cdf),
    "lognorm": dict(canon=_canon_lognorm, rvs=_lognorm_rvs, logpdf=_lognorm_logpdf),
    "expon": dict(canon=_canon_loc_scale, rvs=_expon_rvs, logpdf=_expon_logpdf),
    "gamma": dict(canon=_canon_gamma, rvs=_gamma_rvs, logpdf=_gamma_logpdf),
    "beta": dict(canon=_canon_beta, rvs=_beta_rvs, logpdf=_beta_logpdf),
    "laplace": dict(canon=_canon_loc_scale, rvs=_laplace_rvs, logpdf=_laplace_logpdf),
    "cauchy": dict(canon=_canon_loc_scale, rvs=_cauchy_rvs, logpdf=_cauchy_logpdf),
    "t": dict(canon=_canon_t, rvs=_t_rvs, logpdf=_t_logpdf),
    "truncnorm": dict(canon=_canon_truncnorm, rvs=_truncnorm_rvs,
                      logpdf=_truncnorm_logpdf),
    "randint": dict(canon=_canon_randint, rvs=_randint_rvs,
                    logpdf=_randint_logpdf, discrete=True),
    "binom": dict(canon=_canon_binom, rvs=_binom_rvs, logpdf=_binom_logpdf,
                  discrete=True),
    "poisson": dict(canon=_canon_poisson, rvs=_poisson_rvs,
                    logpdf=_poisson_logpdf, discrete=True),
    "nbinom": dict(canon=_canon_nbinom, rvs=_nbinom_rvs, logpdf=_nbinom_logpdf,
                   discrete=True),
}


class RVDecorator(RVBase):
    """Base for decorators wrapping another RV (pyabc RVDecorator)."""

    def __init__(self, component: RVBase):
        self.component = component
        self.discrete = component.discrete
        self.traceable = component.traceable

    def rvs(self, key, shape=()):
        return self.component.rvs(key, shape)

    def logpdf(self, x):
        return self.component.logpdf(x)

    def cdf(self, x):
        return self.component.cdf(x)

    def rvs_host(self, rng=None):
        return self.component.rvs_host(rng)

    def logpdf_host(self, x) -> float:
        return self.component.logpdf_host(x)


class LowerBoundDecorator(RVDecorator):
    """Truncate the wrapped RV below ``bound`` (pyabc LowerBoundDecorator).

    Samples are resampled-by-clamping via inverse-cdf when available;
    the density below the bound is zero (unnormalized, as in the reference:
    the reference also does not renormalize — acceptance of the proposal
    handles it).
    """

    def __init__(self, component: RVBase, bound: float):
        super().__init__(component)
        self.bound = float(bound)

    def rvs(self, key, shape=()):
        # rejection via clamping to the bound would bias; do a few redraws
        # and fall back to reflecting at the bound (measure-zero effect for
        # continuous RVs when redraws succeed, which they almost surely do
        # for sensible bounds).
        keys = jax.random.split(key, 9)
        x = self.component.rvs(keys[0], shape)
        for i in range(1, 9):
            redraw = self.component.rvs(keys[i], shape)
            x = jnp.where(x > self.bound, x, redraw)
        return jnp.where(x > self.bound, x, 2 * self.bound - x)

    def logpdf(self, x):
        return jnp.where(x > self.bound, self.component.logpdf(x), -jnp.inf)

    def rvs_host(self, rng=None):
        x = self.component.rvs_host(rng)
        for _ in range(100):
            if np.all(x > self.bound):
                return x
            x = self.component.rvs_host(rng)
        return np.where(x > self.bound, x, 2 * self.bound - x)

    def logpdf_host(self, x) -> float:
        if np.all(np.asarray(x) > self.bound):
            return self.component.logpdf_host(x)
        return float(-np.inf)


class ScipyRV(RVBase):
    """Host-only wrapper around a frozen scipy.stats distribution.

    Escape hatch for families without a jax-native implementation. NOT
    traceable: using it in a prior forces the (slow) host proposal path.
    """

    traceable = False

    def __init__(self, frozen):
        self.frozen = frozen
        self.discrete = not hasattr(frozen, "pdf")

    def rvs(self, key, shape=()):
        seed = int(np.asarray(jax.random.key_data(key)).ravel()[-1]) & 0x7FFFFFFF
        return np.asarray(self.frozen.rvs(size=shape, random_state=seed))

    def logpdf(self, x):
        if self.discrete:
            return np.asarray(self.frozen.logpmf(np.asarray(x)))
        return np.asarray(self.frozen.logpdf(np.asarray(x)))

    def cdf(self, x):
        return np.asarray(self.frozen.cdf(np.asarray(x)))

    def rvs_host(self, rng=None):
        return np.asarray(self.frozen.rvs(random_state=rng))

    def logpdf_host(self, x) -> float:
        return float(np.asarray(self.logpdf(x)))


class Distribution:
    """A named product distribution over parameters (pyabc Distribution).

    ``Distribution(a=RV("uniform", 0, 1), b=RV("norm", 0, 2))`` — sampling
    returns a `Parameter`; density is the product over components.  The dense
    interface (`rvs_array` / `logpdf_array`) is what the jitted generation
    kernel uses; columns follow `self.space.names` (insertion order).
    """

    def __init__(self, **rvs: RVBase):
        if not rvs:
            raise ValueError("Distribution needs at least one RV")
        self.rv_map: dict[str, RVBase] = dict(rvs)
        self.space = ParameterSpace(self.rv_map.keys())

    @classmethod
    def from_dictionary(cls, d: Mapping[str, RVBase]) -> "Distribution":
        return cls(**dict(d))

    @property
    def dim(self) -> int:
        return self.space.dim

    @property
    def traceable(self) -> bool:
        return all(rv.traceable for rv in self.rv_map.values())

    def get_parameter_names(self) -> list[str]:
        return list(self.space.names)

    # -- dict-style API (host) ------------------------------------------------
    def rvs(self, key) -> Parameter:
        arr = np.asarray(self.rvs_array(key))
        return self.space.to_dict(arr)

    def pdf(self, par: Mapping[str, float]):
        return float(np.exp(self.logpdf_array(self.space.to_array(par))))

    # -- fork-safe host API (no JAX; multiprocess sampler workers) -----------
    def rvs_host(self, rng=None) -> Parameter:
        vals = np.asarray(
            [np.asarray(rv.rvs_host(rng)).item() for rv in self.rv_map.values()]
        )
        return self.space.to_dict(vals)

    def logpdf_host(self, par: Mapping[str, float]) -> float:
        return float(
            sum(rv.logpdf_host(par[k]) for k, rv in self.rv_map.items())
        )

    def pdf_host(self, par: Mapping[str, float]) -> float:
        return float(np.exp(self.logpdf_host(par)))

    # -- dense API (device, traceable) ---------------------------------------
    def rvs_array(self, key):
        """Sample a (dim,) theta vector."""
        keys = jax.random.split(key, self.dim)
        cols = [rv.rvs(k) for k, rv in zip(keys, self.rv_map.values())]
        return jnp.stack([jnp.asarray(c, jnp.float32) for c in cols])

    def logpdf_array(self, theta):
        """Log density of a (dim,) or (..., dim) padded theta vector.

        Only the first `dim` columns are read, so padded thetas are fine.
        """
        theta = jnp.asarray(theta)
        parts = [
            rv.logpdf(theta[..., i]) for i, rv in enumerate(self.rv_map.values())
        ]
        return sum(parts[1:], parts[0])

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v!r}" for k, v in self.rv_map.items())
        return f"Distribution({inner})"

    def __eq__(self, other):
        return (
            isinstance(other, Distribution)
            and list(self.rv_map) == list(other.rv_map)
            and all(repr(a) == repr(b) for a, b in
                    zip(self.rv_map.values(), other.rv_map.values()))
        )
