"""PRNG key discipline.

The reference reseeds numpy per worker process (``pyabc/sampler/multicore.py``);
the TPU-native design derives every random draw from a single root key via
fold_in over (generation, round, lane) so runs are reproducible regardless of
batch sizes, device counts, or refill round counts.
"""
from __future__ import annotations

import jax


def root_key(seed: int = 0):
    return jax.random.key(seed)


def generation_key(key, t: int):
    """Key for generation t (t = -1 is the calibration generation)."""
    return jax.random.fold_in(key, t + 1)


def round_key(gen_key, round_idx: int):
    return jax.random.fold_in(gen_key, round_idx)
