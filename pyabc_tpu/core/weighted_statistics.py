"""Weighted statistics over particle populations (host, numpy float64).

Reference parity: ``pyabc/weighted_statistics.py`` — weighted_quantile,
weighted_median, weighted_mean, weighted_std, effective_sample_size, resample.
Device-side (jnp) versions live in ``pyabc_tpu.ops.stats``; these host versions
run once per generation on gathered arrays where float64 is free.
"""
from __future__ import annotations

import numpy as np


def weighted_quantile(points, weights=None, alpha: float = 0.5) -> float:
    """The alpha-quantile of weighted ``points``.

    Matches the reference semantics (``pyabc/weighted_statistics.py::
    weighted_quantile``): sort points, take the first point whose cumulative
    normalized weight reaches ``alpha`` (a step-function / lower quantile,
    no interpolation).
    """
    points = np.asarray(points, dtype=np.float64)
    if weights is None:
        weights = np.ones_like(points)
    weights = np.asarray(weights, dtype=np.float64)
    if points.shape != weights.shape:
        raise ValueError("points and weights must have identical shape")
    order = np.argsort(points, kind="stable")
    points = points[order]
    cum = np.cumsum(weights[order])
    total = cum[-1]
    if not np.isfinite(total) or total <= 0:
        raise ValueError("weights must sum to a positive finite value")
    idx = int(np.searchsorted(cum / total, alpha))
    idx = min(idx, len(points) - 1)
    return float(points[idx])


def weighted_median(points, weights=None) -> float:
    return weighted_quantile(points, weights, alpha=0.5)


def weighted_mean(points, weights=None) -> float:
    points = np.asarray(points, dtype=np.float64)
    if weights is None:
        return float(points.mean())
    weights = np.asarray(weights, dtype=np.float64)
    return float(np.sum(points * weights) / np.sum(weights))


def weighted_var(points, weights=None) -> float:
    points = np.asarray(points, dtype=np.float64)
    mu = weighted_mean(points, weights)
    if weights is None:
        return float(np.mean((points - mu) ** 2))
    weights = np.asarray(weights, dtype=np.float64)
    return float(np.sum(weights * (points - mu) ** 2) / np.sum(weights))


def weighted_std(points, weights=None) -> float:
    return float(np.sqrt(weighted_var(points, weights)))


def effective_sample_size(weights) -> float:
    """ESS = (sum w)^2 / sum w^2 (reference: effective_sample_size)."""
    w = np.asarray(weights, dtype=np.float64)
    s = w.sum()
    return float(s * s / np.sum(w * w))


def resample(points, weights, n: int, rng=None) -> np.ndarray:
    """Draw n points iid from the weighted empirical distribution."""
    rng = np.random.default_rng(rng)
    points = np.asarray(points)
    w = np.asarray(weights, dtype=np.float64)
    w = w / w.sum()
    idx = rng.choice(len(points), size=n, p=w)
    return points[idx]


def resample_deterministic(points, weights, n: int) -> np.ndarray:
    """Systematic (low-variance) resampling — deterministic given weights.

    Used where the reference resamples for bootstrap-CV estimation; the
    systematic variant reduces estimator noise for the adaptive population
    size machinery.
    """
    points = np.asarray(points)
    w = np.asarray(weights, dtype=np.float64)
    w = w / w.sum()
    positions = (np.arange(n) + 0.5) / n
    idx = np.searchsorted(np.cumsum(w), positions)
    idx = np.clip(idx, 0, len(points) - 1)
    return points[idx]
