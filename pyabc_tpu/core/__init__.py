from .parameters import Parameter, ParameterSpace
from .population import Particle, Population
from .random import generation_key, root_key, round_key
from .random_choice import fast_random_choice
from .random_variables import (
    RV,
    Distribution,
    LowerBoundDecorator,
    RVBase,
    RVDecorator,
    ScipyRV,
)
from .sumstat_spec import SumStatSpec
from .weighted_statistics import (
    effective_sample_size,
    resample,
    weighted_mean,
    weighted_median,
    weighted_quantile,
    weighted_std,
    weighted_var,
)

__all__ = [
    "Parameter", "ParameterSpace", "Particle", "Population",
    "RV", "Distribution", "RVBase", "RVDecorator", "LowerBoundDecorator",
    "ScipyRV", "SumStatSpec",
    "root_key", "generation_key", "round_key", "fast_random_choice",
    "weighted_quantile", "weighted_median", "weighted_mean", "weighted_std",
    "weighted_var", "effective_sample_size", "resample",
]
