"""pyabc_tpu — TPU-native ABC-SMC likelihood-free inference.

Same capabilities as the reference (chrhck/pyABC, a fork of icb-dcm/pyabc),
re-designed TPU-first: the propose→simulate→distance→accept→weight loop runs
as batched, jit-compiled XLA generations over a device-resident particle
population instead of pickled per-particle closures over worker processes.
"""
from .core import (
    RV,
    Distribution,
    LowerBoundDecorator,
    Parameter,
    ParameterSpace,
    Particle,
    Population,
    RVBase,
    RVDecorator,
    ScipyRV,
    SumStatSpec,
)

__version__ = "0.1.0"
