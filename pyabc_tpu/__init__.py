"""pyabc_tpu — TPU-native ABC-SMC likelihood-free inference.

Same capabilities as the reference (chrhck/pyABC, a fork of icb-dcm/pyabc),
re-designed TPU-first: the propose→simulate→distance→accept→weight loop runs
as batched, jit-compiled XLA generations over a device-resident particle
population instead of pickled per-particle closures over worker processes.
"""
from .acceptor import (
    Acceptor,
    AcceptorResult,
    ScaledPDFNorm,
    SimpleFunctionAcceptor,
    StochasticAcceptor,
    UniformAcceptor,
    pdf_norm_from_kernel,
    pdf_norm_max_found,
)
from .core import (
    RV,
    Distribution,
    LowerBoundDecorator,
    Parameter,
    ParameterSpace,
    Particle,
    Population,
    RVBase,
    RVDecorator,
    ScipyRV,
    SumStatSpec,
    fast_random_choice,
)
from .settings import set_figure_params
from .distance import (
    AcceptAllDistance,
    AdaptiveAggregatedDistance,
    AdaptivePNormDistance,
    AggregatedDistance,
    BinomialKernel,
    Distance,
    DistanceWithMeasureList,
    IdentityFakeDistance,
    IndependentLaplaceKernel,
    IndependentNormalKernel,
    MinMaxDistance,
    NegativeBinomialKernel,
    NoDistance,
    NormalKernel,
    PCADistance,
    PercentileDistance,
    PNormDistance,
    PoissonKernel,
    RangeEstimatorDistance,
    SimpleFunctionDistance,
    StochasticKernel,
    ZScoreDistance,
    to_distance,
)
from .epsilon import (
    AcceptanceRateScheme,
    ConstantEpsilon,
    DalyScheme,
    Epsilon,
    EssScheme,
    ExpDecayFixedIterScheme,
    ExpDecayFixedRatioScheme,
    FrielPettittScheme,
    ListEpsilon,
    ListTemperature,
    MedianEpsilon,
    NoEpsilon,
    PolynomialDecayFixedIterScheme,
    QuantileEpsilon,
    Temperature,
    TemperatureScheme,
)
from .inference import ABCSMC
from .model import IntegratedModel, JaxModel, Model, ModelResult, SimpleModel
from .ops.segment import SegmentedSim
from .populationstrategy import (
    AdaptivePopulationSize,
    ConstantPopulationSize,
    ListPopulationSize,
    PopulationStrategy,
)
from .sampler import (
    BatchedSampler,
    ConcurrentFutureSampler,
    MappingSampler,
    MulticoreEvalParallelSampler,
    MulticoreParticleParallelSampler,
    Sample,
    Sampler,
    SingleCoreSampler,
)
from .broker import ElasticSampler
from .predictor import (
    GPPredictor,
    LassoPredictor,
    LinearPredictor,
    MLPPredictor,
    ModelSelectionPredictor,
    Predictor,
)
from .observability import (
    ClockOffsetEstimator,
    JsonlTraceExporter,
    MetricsRegistry,
    NullTracer,
    Tracer,
    VirtualClock,
    coverage_report,
    device_busy_spans,
    elastic_gap_attribution,
    interval_intersection,
    prometheus_text,
    read_trace,
    worker_trace_spans,
)
from .resilience import (
    CheckpointCorruptError,
    CheckpointManager,
    DegenerateRunError,
    FaultPlan,
    FaultRule,
    RetryPolicy,
    RunSupervisor,
    install_fault_plan,
    uninstall_fault_plan,
)
from .storage import History, create_sqlite_db_id
from .sumstat import IdentitySumstat, PredictorSumstat, Sumstat
from .transition import (
    AggregatedTransition,
    DiscreteJumpTransition,
    DiscreteRandomWalkTransition,
    GridSearchCV,
    LocalTransition,
    ModelPerturbationKernel,
    MultivariateNormalTransition,
    NotEnoughParticles,
    Transition,
)
from . import visualization  # noqa: E402  (pt.visualization.plot_* UX)

__version__ = "0.1.0"
