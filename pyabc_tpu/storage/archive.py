"""Whole-db archival for terminal tenants (serving lifecycle, ISSUE 16).

A terminal tenant's History — the sqlite db plus its ``.columnar/``
generation-file sidecar — is packed into ONE ``.tar.gz`` so the serving
base_dir holds a single compact artifact per archived tenant instead of
a db + WAL + N Parquet files. ``restore`` unpacks it back in place and
the restored History answers ``get_distribution`` / ``get_all_populations``
bit-identically (the tar round-trip never rewrites file contents).

Layout inside the archive (names are fixed, not caller paths, so an
archive restores into any directory)::

    db                     the sqlite file (WAL checkpointed first)
    columnar/run<id>/t<t>.parquet   the sidecar tree, if present

Pure-stdlib (tarfile); no pyarrow dependency — archiving a columnar
tenant just streams its Parquet files as opaque bytes.
"""
from __future__ import annotations

import os
import sqlite3
import tarfile
from pathlib import Path

from .history import _db_path, _parse_store_url

#: archive file suffix next to the tenant db ("<tid>.db" -> "<tid>.tar.gz")
ARCHIVE_SUFFIX = ".tar.gz"


def archive_paths(db_url: str) -> tuple[Path, Path, Path]:
    """(sqlite path, columnar sidecar dir, archive path) for a db url."""
    sql_path = Path(_db_path(_parse_store_url(db_url)[0]))
    return sql_path, Path(str(sql_path) + ".columnar"), \
        sql_path.with_suffix("").with_name(
            sql_path.with_suffix("").name + ARCHIVE_SUFFIX)


def _checkpoint_wal(sql_path: Path) -> None:
    """Fold the -wal file into the main db so the archive is one file's
    truth (a tar of db+wal would need sqlite to replay on restore)."""
    conn = sqlite3.connect(sql_path)
    try:
        conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
        conn.commit()
    finally:
        conn.close()


def archive_tenant_db(db_url: str, *, remove_original: bool = True) -> Path:
    """Pack a tenant History (db + columnar sidecar) into one tar.gz.

    Returns the archive path. With ``remove_original`` (the default, the
    compaction use) the db, WAL droppings, and sidecar tree are deleted
    after the archive is written tmp + ``os.replace`` — a crash mid-pack
    leaves the originals untouched and only a ``.tmp`` orphan.
    """
    sql_path, col_dir, out = archive_paths(db_url)
    if not sql_path.is_file():
        raise FileNotFoundError(f"no tenant db at {sql_path}")
    _checkpoint_wal(sql_path)
    tmp = out.with_suffix(out.suffix + ".tmp")
    with tarfile.open(tmp, "w:gz") as tar:
        tar.add(sql_path, arcname="db")
        if col_dir.is_dir():
            tar.add(col_dir, arcname="columnar")
    os.replace(tmp, out)
    if remove_original:
        sql_path.unlink()
        for side in ("-wal", "-shm"):
            Path(str(sql_path) + side).unlink(missing_ok=True)
        if col_dir.is_dir():
            import shutil

            shutil.rmtree(col_dir)
    return out


def restore_tenant_db(db_url: str, *, remove_archive: bool = False) -> Path:
    """Unpack ``archive_tenant_db``'s artifact back to the live layout.

    Returns the restored sqlite path; ``History(db_url)`` then reads the
    run exactly as before archival.
    """
    sql_path, col_dir, archive = archive_paths(db_url)
    if not archive.is_file():
        raise FileNotFoundError(f"no tenant archive at {archive}")
    with tarfile.open(archive, "r:gz") as tar:
        for member in tar.getmembers():
            # defensive extraction: fixed top-level names only
            if not (member.name == "db" or member.name == "columnar"
                    or member.name.startswith("columnar/")):
                raise ValueError(
                    f"unexpected member {member.name!r} in {archive}")
        db_member = tar.extractfile("db")
        assert db_member is not None
        sql_path.parent.mkdir(parents=True, exist_ok=True)
        with open(sql_path, "wb") as fh:
            fh.write(db_member.read())
        for member in tar.getmembers():
            if member.isfile() and member.name.startswith("columnar/"):
                rel = Path(member.name).relative_to("columnar")
                dest = col_dir / rel
                dest.parent.mkdir(parents=True, exist_ok=True)
                src = tar.extractfile(member)
                assert src is not None
                with open(dest, "wb") as fh:
                    fh.write(src.read())
    if remove_archive:
        archive.unlink()
    return sql_path
