from .archive import archive_tenant_db, restore_tenant_db
from .bytes_storage import df_from_bytes, df_to_bytes, np_from_bytes, np_to_bytes
from .columnar import ColumnarStore, GenerationBatch
from .history import (
    PRE_TIME,
    History,
    PooledWriter,
    WriterPool,
    create_sqlite_db_id,
)

__all__ = [
    "History", "PRE_TIME", "create_sqlite_db_id",
    "WriterPool", "PooledWriter",
    "ColumnarStore", "GenerationBatch",
    "archive_tenant_db", "restore_tenant_db",
    "np_to_bytes", "np_from_bytes", "df_to_bytes", "df_from_bytes",
]
