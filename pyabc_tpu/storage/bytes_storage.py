"""BLOB (de)serialization for numpy arrays and pandas DataFrames.

Reference parity: ``pyabc/storage/numpy_bytes_storage.py`` and
``pyabc/storage/dataframe_bytes_storage.py`` — sum stats and parameter
frames are stored as BLOBs in the SQL database.
"""
from __future__ import annotations

import io

import numpy as np
import pandas as pd


def np_to_bytes(arr) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def np_from_bytes(b: bytes) -> np.ndarray:
    return np.load(io.BytesIO(b), allow_pickle=False)


def df_to_bytes(df: pd.DataFrame) -> bytes:
    buf = io.BytesIO()
    df.to_parquet(buf) if _has_parquet() else df.to_pickle(buf)
    return buf.getvalue()


def df_from_bytes(b: bytes) -> pd.DataFrame:
    buf = io.BytesIO(b)
    if _has_parquet():
        try:
            return pd.read_parquet(buf)
        except Exception:
            buf.seek(0)
    return pd.read_pickle(buf)


def _has_parquet() -> bool:
    try:
        import pyarrow  # noqa: F401

        return True
    except ImportError:
        return False
