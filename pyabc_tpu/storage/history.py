"""History — SQL persistence of the full experiment record.

Reference parity: ``pyabc/storage/history.py::History`` +
``pyabc/storage/db_model.py`` (table/column names follow the reference ORM:
abc_smc -> populations -> models -> particles -> parameters, samples for
sum stats) so reference analysis idioms port. Implemented on the DB-API
seam in ``storage/backend.py``: stdlib ``sqlite3`` by default (the db IS
the per-generation checkpoint, and ``ABCSMC.load`` resumes from it,
SURVEY.md §5.4); ``postgresql://`` urls ride a translating psycopg2
adapter for shared cluster databases (reference: SQLAlchemy multi-dialect
History, SURVEY.md §2.4).

Observed data is stored at pseudo-generation t = PRE_TIME = -1
(reference ``History.store_initial_data``).
"""
from __future__ import annotations

import datetime
import json
import os
import sqlite3
from pathlib import Path

import numpy as np
import pandas as pd

from ..observability import NULL_METRICS, NULL_TRACER
from .bytes_storage import np_from_bytes, np_to_bytes

PRE_TIME = -1

_SCHEMA = """
CREATE TABLE IF NOT EXISTS abc_smc (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    start_time TEXT,
    json_parameters TEXT,
    distance_function TEXT,
    epsilon_function TEXT,
    population_strategy TEXT
);
CREATE TABLE IF NOT EXISTS populations (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    abc_smc_id INTEGER REFERENCES abc_smc(id),
    t INTEGER,
    population_end_time TEXT,
    nr_samples INTEGER,
    epsilon REAL,
    telemetry TEXT
);
CREATE TABLE IF NOT EXISTS models (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    population_id INTEGER REFERENCES populations(id),
    m INTEGER,
    name TEXT,
    p_model REAL
);
CREATE TABLE IF NOT EXISTS particles (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    model_id INTEGER REFERENCES models(id),
    w REAL,
    distance REAL
);
CREATE TABLE IF NOT EXISTS parameters (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    particle_id INTEGER REFERENCES particles(id),
    name TEXT,
    value REAL
);
CREATE TABLE IF NOT EXISTS samples (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    particle_id INTEGER REFERENCES particles(id),
    name TEXT,
    value BLOB
);
CREATE INDEX IF NOT EXISTS ix_pop_abc ON populations(abc_smc_id, t);
CREATE INDEX IF NOT EXISTS ix_model_pop ON models(population_id);
CREATE INDEX IF NOT EXISTS ix_part_model ON particles(model_id);
CREATE INDEX IF NOT EXISTS ix_param_part ON parameters(particle_id);
CREATE INDEX IF NOT EXISTS ix_sample_part ON samples(particle_id);
"""


def create_sqlite_db_id(dir_: str | None = None,
                        file_: str = "pyabc_tpu.db") -> str:
    """Convenience sqlite URL in a temp dir (reference create_sqlite_db_id)."""
    import tempfile

    dir_ = dir_ or tempfile.gettempdir()
    return "sqlite:///" + str(Path(dir_) / file_)


def _db_path(db: str) -> str:
    if db == "sqlite://" or db == ":memory:":
        return ":memory:"
    if db.startswith("sqlite:///"):
        return db[len("sqlite:///"):]
    return db


def _parse_store_url(db: str) -> tuple[str, str | None]:
    """(sql url, store hint) for a History db url.

    ``columnar:///x.db`` and ``sqlite+columnar:///x.db`` select the
    hybrid columnar store (SQL metadata + one Parquet file per
    generation); everything else carries no hint (row store unless
    ``History(store=...)`` overrides)."""
    for prefix in ("sqlite+columnar:", "columnar:"):
        if db.startswith(prefix):
            return "sqlite:" + db[len(prefix):], "columnar"
    return db, None


def _locked(fn):
    """Serialize a History method against the shared sqlite connection.

    With an async writer active, reads from other threads must not observe
    a half-written generation (the writer's explicit transaction is visible
    connection-wide); every public read/write entry point takes the lock.
    """
    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


def _execute_with_retry(fn, args, kwargs, *, tracer, metrics, clock,
                        retry, transient_types, backlog: int) -> None:
    """One queued db write under the bounded transient-retry policy —
    shared by the per-History :class:`_AsyncWriter` thread and the
    multi-tenant :class:`WriterPool` workers (round 14), so the retry /
    span / counter semantics cannot drift between the two."""
    import time as _time

    from ..observability.metrics import PERSIST_RETRIES_TOTAL
    from ..resilience.faults import maybe_fault

    for attempt in range(retry.attempts):
        try:
            maybe_fault("history.persist", attempt=attempt)
            with tracer.span("db.write", backlog=backlog, attempt=attempt):
                fn(*args, **kwargs)
            return
        except transient_types:
            if attempt >= retry.attempts - 1:
                raise
            delay = retry.delay_s(attempt)
            metrics.counter(
                PERSIST_RETRIES_TOTAL,
                "transient History persist failures retried before "
                "sticky latching",
            ).inc()
            t0 = clock.now()
            _time.sleep(delay)
            tracer.record_span(
                "recovery.persist_retry", t0, clock.now(),
                thread="recovery", attempt=attempt,
            )


class _AsyncWriter:
    """Single background thread draining queued db writes in order.

    sqlite's serialized threading mode (sqlite3.threadsafety == 3) makes a
    shared connection safe; History additionally locks multi-statement
    transactions. Worker exceptions are re-raised on the next submit/flush
    so a failed persist cannot pass silently.

    Transient-failure retry (round 9): a persist failing with a
    TRANSIENT error (``transient_types`` — the dialect's
    OperationalError, e.g. sqlite "database is locked", plus the fault
    plan's injected transient) retries under a bounded
    :class:`~pyabc_tpu.resilience.retry.RetryPolicy` before anything
    latches. The append_population path rolls back before re-raising,
    so each retry starts from a clean transaction. Only exhausted
    retries or a NON-transient error (genuinely broken db state) latch
    the writer sticky-dead — from then on queued work drains without
    executing and every submit/flush/close re-raises, exactly the old
    semantics.
    """

    def __init__(self, tracer=None, metrics=None,
                 transient_types: tuple = (), retry=None, clock=None):
        import queue
        import threading

        from ..resilience.retry import DEFAULT_PERSIST_RETRY_POLICY

        self._queue: "queue.Queue" = queue.Queue()
        self._error: BaseException | None = None
        # observability: spans attribute the writer thread's wall clock
        # (db.write per queued append); the backlog gauge exposes how far
        # persistence trails the compute that produced the populations
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._metrics = metrics if metrics is not None else NULL_METRICS
        self._transient_types = tuple(transient_types)
        self._retry = (retry if retry is not None
                       else DEFAULT_PERSIST_RETRY_POLICY)
        self._clock = clock if clock is not None else self._tracer.clock
        self._backlog_gauge = self._metrics.gauge(
            "pyabc_tpu_db_writer_backlog",
            "queued population appends awaiting the writer thread",
        )
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _write_with_retry(self, fn, args, kwargs):
        _execute_with_retry(
            fn, args, kwargs, tracer=self._tracer, metrics=self._metrics,
            clock=self._clock, retry=self._retry,
            transient_types=self._transient_types,
            backlog=self._queue.qsize(),
        )

    def _run(self):
        while True:
            item = self._queue.get()
            if item is None:
                self._queue.task_done()
                return
            fn, args, kwargs = item
            try:
                # after a failure, drain without executing: later appends
                # must not commit on top of a possibly broken db state
                if self._error is None:
                    self._write_with_retry(fn, args, kwargs)
            except BaseException as exc:  # noqa: BLE001 - surfaced later
                self._error = exc
            finally:
                self._queue.task_done()
                self._backlog_gauge.set(self._queue.qsize())

    def _check(self):
        # the error stays STICKY: once a persist failed beyond the
        # transient-retry budget, the writer is dead (queued work drains
        # without executing) and every later submit/flush/close
        # re-raises — a caller that swallows one raise cannot
        # accidentally resume committing on a broken db state
        if self._error is not None:
            raise self._error

    def submit(self, fn, *args, **kwargs):
        self._check()
        self._queue.put((fn, args, kwargs))
        self._backlog_gauge.set(self._queue.qsize())

    def flush(self):
        """Block until everything queued so far is written."""
        self._queue.join()
        self._check()

    def close(self):
        self._queue.join()
        self._queue.put(None)
        self._thread.join(timeout=30)
        self._check()


class PooledWriter:
    """One History's write stream on a shared :class:`WriterPool`.

    Same contract as :class:`_AsyncWriter` (FIFO order per History,
    bounded transient retry, sticky error re-raised on submit/flush/
    close) but the draining thread comes from the pool — a 32-tenant
    serving process runs a handful of writer threads instead of 32.

    Fault ISOLATION is per handle: a persist failure latches only THIS
    handle sticky-dead (its queued work drains unexecuted, its owner's
    submit/flush re-raise); every other tenant's handle keeps writing.
    Ordering: the ``_scheduled`` flag guarantees at most one pool worker
    drains a handle at a time, so one History's appends never interleave
    or reorder; fairness comes from draining ONE item per scheduling
    turn and re-enqueueing the handle behind other tenants' work.
    """

    def __init__(self, pool: "WriterPool", tracer=None, metrics=None,
                 transient_types: tuple = (), retry=None, clock=None,
                 scope_tag: str = ""):
        import collections
        import threading

        from ..resilience.retry import DEFAULT_PERSIST_RETRY_POLICY

        self._pool = pool
        #: fault-domain tag: pool workers execute this handle's writes
        #: inside ``fault_scope(scope_tag)``, so a history.persist fault
        #: rule matched to one tenant fires only on THAT tenant's
        #: stream even though the threads are shared
        self._scope_tag = str(scope_tag)
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._metrics = metrics if metrics is not None else NULL_METRICS
        self._transient_types = tuple(transient_types)
        self._retry = (retry if retry is not None
                       else DEFAULT_PERSIST_RETRY_POLICY)
        self._clock = clock if clock is not None else self._tracer.clock
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._items: "collections.deque" = collections.deque()  # abc-lint: guarded-by=_lock
        self._scheduled = False  # abc-lint: guarded-by=_lock
        self._error: BaseException | None = None
        self._backlog_gauge = self._metrics.gauge(
            "pyabc_tpu_db_writer_backlog",
            "queued population appends awaiting a writer thread",
        )

    def _check(self):
        if self._error is not None:
            raise self._error

    def submit(self, fn, *args, **kwargs):
        self._check()
        with self._lock:
            self._items.append((fn, args, kwargs))
            self._backlog_gauge.set(len(self._items))
            if not self._scheduled:
                self._scheduled = True
                self._pool._enqueue(self)

    def flush(self):
        """Block until everything queued so far is written."""
        # _idle shares _lock, so holding the lock is what wait() needs
        with self._lock:
            while self._items or self._scheduled:
                self._idle.wait(timeout=0.5)
        self._check()

    def close(self):
        # unlike _AsyncWriter there is no private thread to retire; a
        # drained handle simply stops being scheduled
        self.flush()

    # ------------------------------------------------- pool-worker side
    def _drain_one(self) -> None:
        """Execute the oldest queued write; called by a pool worker
        holding this handle's scheduling turn. Reschedules the handle if
        more work remains, else signals idle."""
        with self._lock:
            if not self._items:
                self._scheduled = False
                self._idle.notify_all()
                return
            fn, args, kwargs = self._items.popleft()
            backlog = len(self._items)
        from ..resilience.faults import fault_scope

        try:
            # after a failure, drain without executing (sticky-dead):
            # later appends must not commit on top of broken db state
            if self._error is None:
                with fault_scope(self._scope_tag):
                    _execute_with_retry(
                        fn, args, kwargs, tracer=self._tracer,
                        metrics=self._metrics, clock=self._clock,
                        retry=self._retry,
                        transient_types=self._transient_types,
                        backlog=backlog,
                    )
        except BaseException as exc:  # noqa: BLE001 - surfaced later
            self._error = exc
        finally:
            with self._lock:
                self._backlog_gauge.set(len(self._items))
                if self._items:
                    self._pool._enqueue(self)
                else:
                    self._scheduled = False
                    self._idle.notify_all()


class WriterPool:
    """Shared async-History-writer threads for a multi-tenant process.

    The serving layer gives every tenant its own History database, but
    one dedicated writer thread per tenant (the per-run
    :class:`_AsyncWriter`) multiplies idle threads by the tenant count.
    The pool runs ``n_threads`` workers draining all tenants' queued
    appends round-robin (one item per handle per turn), with each
    tenant's ordering, transient-retry and sticky-error semantics kept
    in its own :class:`PooledWriter` handle — one tenant's dead db
    never stalls or poisons another's stream. ``History.writer_pool``
    opts a History in; ``start_async_writer`` then hands out a pooled
    handle instead of spawning a thread.
    """

    def __init__(self, n_threads: int = 2, name: str = "abc-writer"):
        import queue
        import threading

        self._ready: "queue.Queue" = queue.Queue()
        self._closed = False
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"{name}-{i}")
            for i in range(max(int(n_threads), 1))
        ]
        for th in self._threads:
            th.start()

    def handle(self, tracer=None, metrics=None, transient_types: tuple = (),
               retry=None, clock=None, scope_tag: str = "") -> PooledWriter:
        """A new per-History write stream on this pool."""
        if self._closed:
            raise RuntimeError("WriterPool is closed")
        return PooledWriter(self, tracer=tracer, metrics=metrics,
                            transient_types=transient_types, retry=retry,
                            clock=clock, scope_tag=scope_tag)

    def _enqueue(self, handle: PooledWriter) -> None:
        self._ready.put(handle)

    def _work(self) -> None:
        while True:
            handle = self._ready.get()
            if handle is None:
                return
            handle._drain_one()

    def close(self) -> None:
        """Stop the workers (handles should be flushed first)."""
        self._closed = True
        for _ in self._threads:
            self._ready.put(None)
        for th in self._threads:
            th.join(timeout=10)


class History:
    """Experiment record over one sqlite database; multiple runs per db.

    Writes may be moved off the caller's thread with
    :meth:`start_async_writer` + :meth:`append_population_async` (used by
    the fused-chunk loop so sqlite persistence overlaps device compute);
    :meth:`done` flushes, so post-run reads always see every generation.
    """

    def __init__(self, db: str, _id: int | None = None,
                 store_sum_stats: bool | int = True, *,
                 tracer=None, metrics=None, store: str | None = None,
                 wal: bool = True):
        import threading

        #: the ORIGINAL url (scheme preserved): serving/tests re-open
        #: tenant db paths verbatim and the scheme is self-describing
        self.db = db
        db, url_store = _parse_store_url(db)
        if store is None:
            store = url_store or "rows"
        if store not in ("rows", "columnar"):
            raise ValueError(
                f"History store must be 'rows' or 'columnar', got {store!r}")
        #: "rows" = everything in SQL (reference layout); "columnar" =
        #: hybrid (SQL metadata, one Parquet record batch per generation
        #: written straight from the packed-fetch arrays)
        self.store = store
        #: per-particle summary-statistic retention policy: ``True`` stores
        #: every generation (reference behavior), ``False`` stores none, an
        #: int k stores every k-th generation (t % k == 0). Skipping sum
        #: stats cuts the device->host fetch and the db size by ~10x per
        #: generation; the trade-off is that sumstat-based analysis
        #: (get_weighted_sum_stats, KDE-on-stats plots) and adaptive-distance
        #: resume only work for stored generations.
        self.store_sum_stats = store_sum_stats
        # check_same_thread=False: the async writer thread shares this
        # connection; sqlite serialized mode + self._lock make it safe.
        # Non-sqlite urls go through the backend seam (storage/backend.py)
        from .backend import open_database

        #: observability sinks; pass them at construction so the schema
        #: DDL below is attributed (per-run host setup is part of the
        #: wall clock between back-to-back runs — round 6); ABCSMC also
        #: rebinds these to the run's tracer/registry after load()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = metrics if metrics is not None else NULL_METRICS
        self._lock = threading.RLock()
        self._writer: _AsyncWriter | PooledWriter | None = None
        #: last append's ingest accounting ({rows, s, rows_per_sec,
        #: bytes_on_disk}); None until the first append lands
        self.last_ingest: dict | None = None
        #: opt-in shared writer threads (round 14, multi-tenant serving):
        #: set to a :class:`WriterPool` BEFORE the first
        #: ``start_async_writer`` call and queued appends drain on the
        #: pool's workers (per-History ordering and sticky-error
        #: isolation preserved) instead of a dedicated thread per run;
        #: ``writer_scope`` tags the stream's fault domain (tenant id)
        self.writer_pool: WriterPool | None = None
        self.writer_scope: str = ""
        with self.tracer.span("db.setup", db=db):
            self._conn, self._dialect = open_database(db, _db_path)
            sqlite_path = (_db_path(db) if self._dialect.name == "sqlite"
                           else None)
            if (wal and sqlite_path is not None
                    and sqlite_path != ":memory:"):
                # WAL + synchronous=NORMAL: appends no longer rewrite
                # the rollback journal and fsync once per commit instead
                # of twice — measured in the bench `storage` lane;
                # guarded to the sqlite dialect (postgres has its own
                # WAL and rejects these pragmas)
                self._conn.execute("PRAGMA journal_mode=WAL")
                self._conn.execute("PRAGMA synchronous=NORMAL")
            # the columnar sidecar: ACTIVE (written) when store=columnar;
            # otherwise a read-only prober so a plain History(db) opened
            # on a columnar-written db still reads every generation
            from .columnar import ColumnarStore, require_pyarrow

            if self.store == "columnar":
                require_pyarrow("History(store='columnar')")
                if sqlite_path is None:
                    raise ValueError(
                        "the columnar store keeps metadata in a run-local "
                        "sqlite file; postgres metadata urls are not "
                        "supported (use store='rows')")
                if sqlite_path == ":memory:":
                    import tempfile

                    sqlite_path = tempfile.mkdtemp(
                        prefix="pyabc_tpu_columnar_") + "/mem.db"
            self._colstore = (
                ColumnarStore(sqlite_path + ".columnar")
                if sqlite_path is not None and sqlite_path != ":memory:"
                else None
            )
            self._conn.executescript(_SCHEMA)
            # schema migration for dbs created before the telemetry column
            cols = self._dialect.table_columns(self._conn, "populations")
            if "telemetry" not in cols:
                self._conn.execute(
                    "ALTER TABLE populations ADD COLUMN telemetry TEXT"
                )
            self._conn.commit()
            self.id = _id if _id is not None else self._latest_id()

    # ------------------------------------------------------- async writing
    def start_async_writer(self) -> "_AsyncWriter | PooledWriter":
        if self._writer is None:
            from ..resilience.faults import InjectedTransientError

            # transient = the dialect's OperationalError family (sqlite
            # "database is locked"/"busy", a dropped pg connection that
            # reconnects) + the fault plan's injected transient; schema /
            # integrity / programming errors stay immediately sticky
            transient = (self._dialect.OperationalError,
                         InjectedTransientError)
            if self.writer_pool is not None:
                self._writer = self.writer_pool.handle(
                    tracer=self.tracer, metrics=self.metrics,
                    transient_types=transient,
                    scope_tag=self.writer_scope,
                )
            else:
                self._writer = _AsyncWriter(
                    self.tracer, self.metrics,
                    transient_types=transient,
                )
        return self._writer

    def append_population_async(self, *args, **kwargs) -> None:
        """Queue an append on the writer thread (falls back to synchronous
        when no writer is active)."""
        if self._writer is None:
            self.append_population(*args, **kwargs)
            return
        self._writer.submit(self.append_population, *args, **kwargs)

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    @property
    def columnar(self) -> bool:
        """True when appends land as columnar generation batches — the
        fused loop checks this to hand the packed-fetch arrays through
        (a :class:`~pyabc_tpu.storage.columnar.GenerationBatch`) instead
        of materializing a Population for persistence."""
        return self.store == "columnar"

    def wants_sum_stats(self, t: int) -> bool:
        """Whether generation t's per-particle sum stats should be stored
        (see ``store_sum_stats``)."""
        if self.store_sum_stats is True:
            return True
        if self.store_sum_stats is False:
            return False
        k = int(self.store_sum_stats)
        return k > 0 and t % k == 0

    def _latest_id(self) -> int | None:
        row = self._conn.execute("SELECT MAX(id) FROM abc_smc").fetchone()
        return row[0]

    def _wall_iso(self) -> str:
        """Civil timestamp for db rows — from the INJECTED clock
        (CLOCK001), so VirtualClock-driven tests write deterministic rows
        and a run never mixes two wall sources."""
        return datetime.datetime.fromtimestamp(
            self.tracer.clock.wall()).isoformat()

    # ------------------------------------------------------------- creation
    @_locked
    def store_initial_data(self, ground_truth_model: int | None,
                           options: dict, observed_summary_statistics: dict,
                           ground_truth_parameter: dict,
                           model_names: list[str],
                           distance_function_json: str,
                           eps_function_json: str,
                           population_strategy_json: str) -> int:
        """Open a new run; store observed data at t = PRE_TIME."""
        cur = self._conn.cursor()
        cur.execute(
            "INSERT INTO abc_smc (start_time, json_parameters, "
            "distance_function, epsilon_function, population_strategy) "
            "VALUES (?,?,?,?,?)",
            (
                self._wall_iso(),
                json.dumps(options),
                distance_function_json,
                eps_function_json,
                population_strategy_json,
            ),
        )
        self.id = cur.lastrowid
        cur.execute(
            "INSERT INTO populations (abc_smc_id, t, population_end_time, "
            "nr_samples, epsilon) VALUES (?,?,?,?,?)",
            (self.id, PRE_TIME, self._wall_iso(), 0, 0.0),
        )
        pop_id = cur.lastrowid
        gt_m = ground_truth_model if ground_truth_model is not None else 0
        cur.execute(
            "INSERT INTO models (population_id, m, name, p_model) "
            "VALUES (?,?,?,?)",
            (pop_id, gt_m, model_names[gt_m] if model_names else "m0", 1.0),
        )
        model_id = cur.lastrowid
        cur.execute(
            "INSERT INTO particles (model_id, w, distance) VALUES (?,?,?)",
            (model_id, 1.0, 0.0),
        )
        particle_id = cur.lastrowid
        for name, value in (ground_truth_parameter or {}).items():
            cur.execute(
                "INSERT INTO parameters (particle_id, name, value) "
                "VALUES (?,?,?)",
                (particle_id, name, float(value)),
            )
        for name, value in observed_summary_statistics.items():
            cur.execute(
                "INSERT INTO samples (particle_id, name, value) VALUES (?,?,?)",
                (particle_id, name, np_to_bytes(value)),
            )
        self._conn.commit()
        return self.id

    # ------------------------------------------------------------ appending
    def append_population(self, t: int, current_epsilon: float, population,
                          nr_simulations: int, model_names: list[str],
                          telemetry: dict | None = None) -> None:
        from .columnar import GenerationBatch

        if callable(population):
            # deferred construction: the fused loop ships raw device-fetched
            # arrays and a builder; normalization + Population construction
            # then run HERE — on the async writer thread when one is active —
            # instead of on the latency-critical chunk-processing thread
            population = population()
        if isinstance(population, GenerationBatch):
            # same deferral for the columnar path: slot-order sort +
            # weight normalization run on the writer thread, and the
            # narrow fetch dtypes ride through to disk untouched
            population = population.materialize()
        with self._lock:
            try:
                self._append_population_locked(
                    t, current_epsilon, population, nr_simulations,
                    model_names, telemetry,
                )
            except BaseException:
                # never leave the shared connection inside a broken
                # transaction: a later append's commit would otherwise
                # durably persist this generation's partial rows
                try:
                    self._conn.rollback()
                except self._dialect.Error:
                    pass
                raise

    def _append_population_locked(self, t, current_epsilon, population,
                                  nr_simulations, model_names,
                                  telemetry) -> None:
        t_in0 = self.tracer.clock.now()
        cur = self._conn.cursor()
        try:
            # grab the write lock up front: the batched particle insert
            # allocates explicit ids from SELECT MAX(id), which would race
            # with another process appending to the same file
            cur.execute("BEGIN IMMEDIATE")
        except self._dialect.OperationalError:
            pass  # already inside a transaction
        cur.execute(
            "INSERT INTO populations (abc_smc_id, t, population_end_time, "
            "nr_samples, epsilon, telemetry) VALUES (?,?,?,?,?,?)",
            (self.id, int(t), self._wall_iso(),
             int(nr_simulations), float(current_epsilon),
             json.dumps(telemetry) if telemetry else None),
        )
        pop_id = cur.lastrowid
        probs = population.model_probabilities_array()
        for m in population.get_alive_models():
            cur.execute(
                "INSERT INTO models (population_id, m, name, p_model) "
                "VALUES (?,?,?,?)",
                (pop_id, int(m),
                 model_names[m] if m < len(model_names) else f"m{m}",
                 float(probs[m])),
            )
        if self.columnar:
            # hybrid mode: model/population metadata rows above stay in
            # SQL; the particle payload lands as ONE Parquet record
            # batch, written (tmp + rename) BEFORE the metadata commit
            # so a generation is visible iff file and row both exist
            n_rows, _ = self._colstore.write_generation(
                self.id, int(t), population,
                store_sumstats=(population.sumstats is not None
                                and self.wants_sum_stats(t)),
            )
            self._conn.commit()
            self._note_ingest(n_rows, t_in0)
            return
        self._append_particle_rows_locked(cur, pop_id, t, population, probs)
        self._conn.commit()
        self._note_ingest(len(population.ms), t_in0)

    def _append_particle_rows_locked(self, cur, pop_id, t, population,
                                     probs) -> None:
        """The row-store particle fan-out (reference ORM layout)."""
        # one id allocation per append (NOT per alive model): the
        # explicit-id insert below only needs a base the whole append's
        # rows build on — re-running MAX(id) inside the loop re-scanned
        # the table once per model inside the write transaction
        base = cur.execute(
            "SELECT COALESCE(MAX(id), 0) FROM particles"
        ).fetchone()[0]
        # models rows were just inserted in alive-model order; recover
        # their ids for the particle foreign keys
        model_ids = {
            int(m): mid for mid, m in cur.execute(
                "SELECT id, m FROM models WHERE population_id=?", (pop_id,)
            ).fetchall()
        }
        for m in population.get_alive_models():
            model_id = model_ids[int(m)]
            mask = population.ms == m
            idxs = np.flatnonzero(mask)
            space = population.spaces[m]
            # within-model normalized weights (reference stores these)
            w_model = population.weights[mask] / probs[m]
            # batched inserts with explicit particle ids: one executemany per
            # table instead of 2+d statements per particle (at pop sizes of
            # 10^3-10^5 the per-row Python round-trips dominate persistence)
            pids = range(base + 1, base + 1 + len(idxs))
            base += len(idxs)
            cur.executemany(
                "INSERT INTO particles (id, model_id, w, distance) "
                "VALUES (?,?,?,?)",
                [(pid, model_id, float(w), float(population.distances[i]))
                 for pid, w, i in zip(pids, w_model, idxs)],
            )
            cur.executemany(
                "INSERT INTO parameters (particle_id, name, value) "
                "VALUES (?,?,?)",
                [(pid, nm, float(v))
                 for pid, i in zip(pids, idxs)
                 for nm, v in zip(space.names,
                                  population.thetas[i, : space.dim])],
            )
            if population.sumstats is not None and self.wants_sum_stats(t):
                cur.executemany(
                    "INSERT INTO samples (particle_id, name, value) "
                    "VALUES (?,?,?)",
                    [(pid, "__flat__", np_to_bytes(population.sumstats[i]))
                     for pid, i in zip(pids, idxs)],
                )

    def _note_ingest(self, n_rows: int, t_in0: float) -> None:
        """Export the append's ingest accounting (round 17): rows/sec of
        the write that just committed + this run's bytes on disk."""
        from ..observability.metrics import (
            HISTORY_BYTES_ON_DISK_GAUGE,
            HISTORY_INGEST_ROWS_PER_SEC_GAUGE,
        )

        dt = self.tracer.clock.now() - t_in0
        rate = (float(n_rows) / dt) if dt > 0 else 0.0
        on_disk = 0
        if self.columnar and self._colstore is not None:
            on_disk = self._colstore.bytes_on_disk(self.id)
        else:
            path = _db_path(_parse_store_url(self.db)[0])
            if path != ":memory:" and os.path.exists(path):
                on_disk = os.path.getsize(path)
                wal = path + "-wal"
                if os.path.exists(wal):
                    on_disk += os.path.getsize(wal)
        self.metrics.gauge(
            HISTORY_INGEST_ROWS_PER_SEC_GAUGE,
            "accepted particles persisted per second by the last "
            "History append",
        ).set(rate)
        self.metrics.gauge(
            HISTORY_BYTES_ON_DISK_GAUGE,
            "bytes on disk for this History's run after the last append",
        ).set(float(on_disk))
        #: last-append accounting for the bench `storage` lane (reading
        #: the gauges back is registry-dependent; this is the direct tap)
        self.last_ingest = {
            "rows": int(n_rows), "s": dt, "rows_per_sec": rate,
            "bytes_on_disk": int(on_disk),
        }

    @_locked
    def prune_from(self, t: int) -> int:
        """Delete this run's populations with generation >= ``t`` (and
        their models/particles/parameters/samples). Returns the number
        of populations removed.

        Resume seam for the mid-chunk checkpoint (resilience subsystem):
        an orchestrator killed between a checkpoint save and its death
        may have persisted generations PAST the checkpoint's resume
        point; re-running them from the restored carry would otherwise
        append duplicate population rows for the same ``t``. The
        checkpoint is the canonical state — rows past it are trimmed
        before the re-run."""
        return self._prune_where(
            "t>=?", (int(t),),
            lambda: self._colstore.prune(self.id, int(t)))

    @_locked
    def prune_before(self, t: int) -> int:
        """Delete this run's populations with 0 <= generation < ``t``
        (and their models/particles/parameters/samples). Returns the
        number of populations removed.

        Retention-GC seam (serving lifecycle, keep-last-k / TTL): drops
        the OLDEST generations while :meth:`prune_from` drops the newest.
        The PRE_TIME observed-data row is never touched — ``load()`` +
        requeue-resume need only that row, the checkpoint, and ``max_t``,
        all of which survive any ``prune_before`` cut. Note
        ``total_nr_simulations`` shrinks accordingly (the dropped
        generations' sample counts are gone with their rows)."""
        return self._prune_where(
            "t>=0 AND t<?", (int(t),),
            lambda: self._colstore.prune_before(self.id, int(t)))

    def _prune_where(self, cond: str, params: tuple, colstore_prune) -> int:
        cur = self._conn.cursor()
        pop_ids = [r[0] for r in cur.execute(
            f"SELECT id FROM populations WHERE abc_smc_id=? AND {cond}",
            (self.id, *params),
        ).fetchall()]
        if not pop_ids:
            return 0
        ph = ",".join("?" * len(pop_ids))
        cur.execute(
            f"DELETE FROM samples WHERE particle_id IN ("
            f"SELECT particles.id FROM particles JOIN models "
            f"ON particles.model_id = models.id "
            f"WHERE models.population_id IN ({ph}))", pop_ids)
        cur.execute(
            f"DELETE FROM parameters WHERE particle_id IN ("
            f"SELECT particles.id FROM particles JOIN models "
            f"ON particles.model_id = models.id "
            f"WHERE models.population_id IN ({ph}))", pop_ids)
        cur.execute(
            f"DELETE FROM particles WHERE model_id IN ("
            f"SELECT id FROM models WHERE population_id IN ({ph}))",
            pop_ids)
        cur.execute(
            f"DELETE FROM models WHERE population_id IN ({ph})", pop_ids)
        cur.execute(
            f"DELETE FROM populations WHERE id IN ({ph})", pop_ids)
        self._conn.commit()
        # columnar generation files go AFTER the metadata commit: rows
        # are the visibility truth, so a crash between commit and unlink
        # leaves only invisible orphan files (overwritten on re-append)
        if self._colstore is not None:
            colstore_prune()
        return len(pop_ids)

    @_locked
    def vacuum(self) -> None:
        """Reclaim the pages freed by pruning (sqlite keeps them in the
        freelist otherwise — a pruned db's file size would never shrink).
        Sqlite-only; a no-op on other dialects."""
        if self._dialect.name == "sqlite":
            self._conn.commit()
            self._conn.execute("VACUUM")

    def update_telemetry(self, t: int, telemetry: dict) -> None:
        """Merge keys into the telemetry json of generation t (adaptation
        timings only exist after the row is first written)."""
        with self._lock:
            pop_id = self._pop_id(t)
            if pop_id is None:
                return
            row = self._conn.execute(
                "SELECT telemetry FROM populations WHERE id=?", (pop_id,)
            ).fetchone()
            merged = dict(json.loads(row[0]) if row and row[0] else {})
            merged.update(telemetry)
            self._conn.execute(
                "UPDATE populations SET telemetry=? WHERE id=?",
                (json.dumps(merged), pop_id),
            )
            self._conn.commit()

    @_locked
    def get_telemetry(self, t: int | None = None) -> dict:
        """Per-generation timing/telemetry json (empty dict if none)."""
        pop_id = self._pop_id(self._resolve_t(t))
        if pop_id is None:
            return {}
        row = self._conn.execute(
            "SELECT telemetry FROM populations WHERE id=?", (pop_id,)
        ).fetchone()
        return json.loads(row[0]) if row and row[0] else {}

    # ------------------------------------------------------------- queries
    def _pop_id(self, t: int) -> int | None:
        t = self._resolve_t(t)
        row = self._conn.execute(
            "SELECT id FROM populations WHERE abc_smc_id=? AND t=?",
            (self.id, t),
        ).fetchone()
        return row[0] if row else None

    def _resolve_t(self, t: int | None) -> int:
        if t is None or t < 0 and t != PRE_TIME:
            return self.max_t
        return t

    def _columnar_gen(self, t: int) -> bool:
        """Whether generation t's particles live in a columnar file.

        Checked per generation (not per store mode) so a plain
        ``History(db)`` opened on a columnar-written db — or a hybrid db
        holding runs of both kinds — reads every generation correctly."""
        return self._colstore is not None and self._colstore.has(self.id, t)

    def _p_by_m(self, pop_id: int) -> dict[int, float]:
        """{m: p_model} from the (always-SQL) models metadata rows."""
        return {
            int(m): float(p) for m, p in self._conn.execute(
                "SELECT m, p_model FROM models WHERE population_id=?",
                (pop_id,),
            ).fetchall()
        }

    @property
    @_locked
    def max_t(self) -> int:
        row = self._conn.execute(
            "SELECT MAX(t) FROM populations WHERE abc_smc_id=?", (self.id,)
        ).fetchone()
        return row[0] if row and row[0] is not None else PRE_TIME

    @property
    @_locked
    def n_populations(self) -> int:
        row = self._conn.execute(
            "SELECT COUNT(*) FROM populations WHERE abc_smc_id=? AND t>=0",
            (self.id,),
        ).fetchone()
        return int(row[0])

    @_locked
    def all_runs(self) -> pd.DataFrame:
        return pd.read_sql_query(
            "SELECT * FROM abc_smc", self._conn
        )

    @_locked
    def get_distribution(self, m: int = 0, t: int | None = None
                         ) -> tuple[pd.DataFrame, np.ndarray]:
        """(parameter DataFrame, within-model weights) for model m at t."""
        t = self._resolve_t(t)
        pop_id = self._pop_id(t)
        if pop_id is None:
            raise KeyError(f"no population t={t}")
        if self._columnar_gen(t):
            return self._colstore.distribution(self.id, t, int(m))
        df = pd.read_sql_query(
            """
            SELECT particles.id AS pid, particles.w AS w,
                   parameters.name AS name, parameters.value AS value
            FROM models
            JOIN particles ON particles.model_id = models.id
            JOIN parameters ON parameters.particle_id = particles.id
            WHERE models.population_id = ? AND models.m = ?
            """,
            self._conn, params=(pop_id, int(m)),
        )
        if df.empty:
            raise KeyError(f"no particles for model {m} at t={t}")
        wide = df.pivot(index="pid", columns="name", values="value")
        w = df.drop_duplicates("pid").set_index("pid")["w"].loc[wide.index]
        w = np.asarray(w, np.float64)
        w = w / w.sum()
        wide.columns.name = None
        return wide.reset_index(drop=True), w

    @_locked
    def get_parameter_names(self, m: int = 0, t: int | None = None
                            ) -> list[str]:
        """Parameter names of model m at generation t (cheap DISTINCT query
        — no particle data is loaded)."""
        t = self._resolve_t(t)
        pop_id = self._pop_id(t)
        if pop_id is None:
            raise KeyError(f"no population t={t}")
        if self._columnar_gen(t):
            return self._colstore.parameter_names(self.id, t, int(m))
        rows = self._conn.execute(
            """
            SELECT DISTINCT parameters.name
            FROM models
            JOIN particles ON particles.model_id = models.id
            JOIN parameters ON parameters.particle_id = particles.id
            WHERE models.population_id = ? AND models.m = ?
            ORDER BY parameters.name
            """,
            (pop_id, int(m)),
        ).fetchall()
        return [r[0] for r in rows]

    @_locked
    def get_model_probabilities(self, t: int | None = None) -> pd.DataFrame:
        if t is None:
            df = pd.read_sql_query(
                """
                SELECT populations.t AS t, models.m AS m, models.p_model AS p
                FROM models JOIN populations
                  ON models.population_id = populations.id
                WHERE populations.abc_smc_id = ? AND populations.t >= 0
                """,
                self._conn, params=(self.id,),
            )
            return df.pivot(index="t", columns="m", values="p").fillna(0.0)
        t = self._resolve_t(t)
        pop_id = self._pop_id(t)
        df = pd.read_sql_query(
            "SELECT m, p_model AS p FROM models WHERE population_id=?",
            self._conn, params=(pop_id,),
        )
        return df.set_index("m")

    @_locked
    def get_all_populations(self) -> pd.DataFrame:
        df = pd.read_sql_query(
            "SELECT t, population_end_time, nr_samples AS samples, epsilon "
            "FROM populations WHERE abc_smc_id=? AND t>=? ORDER BY t",
            self._conn, params=(self.id, PRE_TIME),
        )
        return df

    @_locked
    def get_nr_particles_per_population(self) -> pd.Series:
        df = pd.read_sql_query(
            """
            SELECT populations.t AS t, COUNT(particles.id) AS n
            FROM populations
            LEFT JOIN models ON models.population_id = populations.id
            LEFT JOIN particles ON particles.model_id = models.id
            WHERE populations.abc_smc_id = ?
            GROUP BY populations.t ORDER BY populations.t
            """,
            self._conn, params=(self.id,),
        )
        s = df.set_index("t")["n"]
        # columnar generations have no particle rows in SQL — their
        # counts come from the Parquet footer (a metadata-only read)
        for t in s.index:
            if t >= 0 and s[t] == 0 and self._columnar_gen(int(t)):
                s[t] = self._colstore.n_particles(self.id, int(t))
        return s

    @_locked
    def get_weighted_distances(self, t: int | None = None) -> pd.DataFrame:
        """['distance', 'w'] with overall-normalized weights (ref API)."""
        t = self._resolve_t(t)
        pop_id = self._pop_id(t)
        if self._columnar_gen(t):
            return self._colstore.weighted_distances(
                self.id, t, self._p_by_m(pop_id))
        df = pd.read_sql_query(
            """
            SELECT particles.distance AS distance,
                   particles.w * models.p_model AS w
            FROM models JOIN particles ON particles.model_id = models.id
            WHERE models.population_id = ?
            """,
            self._conn, params=(pop_id,),
        )
        return df

    @_locked
    def get_weighted_sum_stats(self, t: int | None = None
                               ) -> tuple[np.ndarray, np.ndarray]:
        t = self._resolve_t(t)
        pop_id = self._pop_id(t)
        if self._columnar_gen(t):
            res = self._colstore.weighted_sum_stats(
                self.id, t, self._p_by_m(pop_id))
            if res is None:
                raise ValueError(
                    f"no sum stats stored for generation {t}: the run was "
                    f"written with store_sum_stats disabled for this "
                    f"generation (this handle has store_sum_stats="
                    f"{self.store_sum_stats!r})"
                )
            return res
        df = pd.read_sql_query(
            """
            SELECT particles.id AS pid,
                   particles.w * models.p_model AS w, samples.value AS blob
            FROM models
            JOIN particles ON particles.model_id = models.id
            JOIN samples ON samples.particle_id = particles.id
            WHERE models.population_id = ? AND samples.name = '__flat__'
            """,
            self._conn, params=(pop_id,),
        )
        if len(df) == 0:
            # populations always have particles, so an empty join means the
            # sum stats were skipped at write time (store_sum_stats policy —
            # possibly of the History instance that WROTE the run; the
            # policy is not persisted in the db)
            raise ValueError(
                f"no sum stats stored for generation {t}: the run was "
                f"written with store_sum_stats disabled for this generation "
                f"(this handle has store_sum_stats={self.store_sum_stats!r})"
            )
        weights = np.asarray(df["w"], np.float64)
        stats = np.stack([np_from_bytes(b) for b in df["blob"]])
        return weights, stats

    @_locked
    def get_population_extended(self, t: int | None = None) -> pd.DataFrame:
        t = self._resolve_t(t)
        pop_id = self._pop_id(t)
        if self._columnar_gen(t):
            names = {
                int(m): nm for m, nm in self._conn.execute(
                    "SELECT m, name FROM models WHERE population_id=?",
                    (pop_id,),
                ).fetchall()
            }
            return self._colstore.population_extended(self.id, t, names)
        return pd.read_sql_query(
            """
            SELECT models.m AS m, models.name AS model_name,
                   particles.w AS w, particles.distance AS distance,
                   parameters.name AS par_name, parameters.value AS par_value
            FROM models
            JOIN particles ON particles.model_id = models.id
            JOIN parameters ON parameters.particle_id = particles.id
            WHERE models.population_id = ?
            """,
            self._conn, params=(pop_id,),
        )

    @_locked
    def alive_models(self, t: int | None = None) -> list[int]:
        t = self._resolve_t(t)
        pop_id = self._pop_id(t)
        rows = self._conn.execute(
            "SELECT m FROM models WHERE population_id=? AND p_model>0",
            (pop_id,),
        ).fetchall()
        return [r[0] for r in rows]

    @_locked
    def n_alive_models(self, t: int | None = None) -> int:
        return len(self.alive_models(t))

    @property
    @_locked
    def total_nr_simulations(self) -> int:
        row = self._conn.execute(
            "SELECT SUM(nr_samples) FROM populations WHERE abc_smc_id=?",
            (self.id,),
        ).fetchone()
        return int(row[0] or 0)

    @_locked
    def get_observed_sum_stat(self) -> dict[str, np.ndarray]:
        pop_id = self._pop_id(PRE_TIME)
        df = pd.read_sql_query(
            """
            SELECT samples.name AS name, samples.value AS blob
            FROM models
            JOIN particles ON particles.model_id = models.id
            JOIN samples ON samples.particle_id = particles.id
            WHERE models.population_id = ?
            """,
            self._conn, params=(pop_id,),
        )
        return {r["name"]: np_from_bytes(r["blob"]) for _, r in df.iterrows()}

    @_locked
    def get_ground_truth_parameter(self) -> dict[str, float]:
        pop_id = self._pop_id(PRE_TIME)
        df = pd.read_sql_query(
            """
            SELECT parameters.name AS name, parameters.value AS value
            FROM models
            JOIN particles ON particles.model_id = models.id
            JOIN parameters ON parameters.particle_id = particles.id
            WHERE models.population_id = ?
            """,
            self._conn, params=(pop_id,),
        )
        return dict(zip(df["name"], df["value"]))

    @_locked
    def get_json_parameters(self) -> dict:
        row = self._conn.execute(
            "SELECT json_parameters FROM abc_smc WHERE id=?", (self.id,)
        ).fetchone()
        return json.loads(row[0]) if row and row[0] else {}

    def done(self) -> None:
        # drain AND retire the writer: long-lived processes (dashboard,
        # notebooks) would otherwise leak one idle thread per run;
        # start_async_writer lazily recreates it on a resumed run
        if self._writer is not None:
            writer, self._writer = self._writer, None
            writer.close()  # re-raises a deferred persist error
        with self._lock:
            self._conn.commit()

    def close(self) -> None:
        try:
            if self._writer is not None:
                writer, self._writer = self._writer, None
                writer.close()  # may re-raise a deferred persist error
        finally:
            self._conn.close()

    def __repr__(self):
        return f"History(db={self.db!r}, id={self.id})"
