"""Database backend seam for :class:`History`.

Reference parity: the reference History is SQLAlchemy over any dialect —
in practice SQLite for single-host runs and PostgreSQL for shared cluster
databases (SURVEY.md §2.4). Here the seam is a thin DB-API adapter layer
instead of an ORM:

- ``sqlite:...`` URLs return the raw stdlib ``sqlite3`` connection —
  zero overhead, identical behavior to the pre-seam code, and the path
  every test exercises.
- ``postgresql://...`` URLs return a translating adapter over psycopg2
  that maps the sqlite idioms History speaks (``?`` placeholders,
  ``AUTOINCREMENT`` DDL, ``BLOB``, ``BEGIN IMMEDIATE``, ``executescript``,
  ``lastrowid``) onto PostgreSQL. psycopg2 is optional; without it the
  URL raises an informative error at construction (the gating contract
  shared by all optional integrations). The translation layer itself is
  unit-tested against a recording fake DB-API driver
  (``tests/test_backend.py``) — the same stub-contract pattern used for
  the SGE/R/Julia adapters.

The TPU-pod scope note: a pod's hosts do NOT share one History — only the
primary process persists (``parallel.distributed.primary_db``), so sqlite
is fully sufficient for on-pod runs; postgres matters when many SEPARATE
studies feed one shared lab database, which is exactly the adapter's use
case.
"""
from __future__ import annotations

import re
import sqlite3


def translate_sql(sql: str) -> str:
    """sqlite-idiom SQL -> postgres: qmark params to %s (string literals in
    our schema contain no '?')."""
    return sql.replace("?", "%s")


def translate_ddl(schema: str) -> str:
    """Schema DDL rewrite for postgres."""
    out = schema.replace(
        "INTEGER PRIMARY KEY AUTOINCREMENT", "BIGSERIAL PRIMARY KEY"
    )
    out = out.replace(" BLOB", " BYTEA")
    return out


def split_script(script: str) -> list[str]:
    """executescript emulation: split on ';' (our schema has no literals
    or triggers containing semicolons)."""
    return [s.strip() for s in script.split(";") if s.strip()]


_INSERT_RE = re.compile(r"^\s*INSERT\b", re.IGNORECASE)
_EXPLICIT_ID_RE = re.compile(r"\(\s*id\s*[,)]", re.IGNORECASE)
_INSERT_TABLE_RE = re.compile(r"^\s*INSERT\s+INTO\s+(\w+)", re.IGNORECASE)


def wants_returning_id(sql: str) -> bool:
    """lastrowid emulation: append RETURNING id to INSERTs that rely on
    autoincrement (not to executemany-style inserts with explicit ids)."""
    return bool(_INSERT_RE.match(sql)) and not _EXPLICIT_ID_RE.search(sql)


def explicit_id_insert_table(sql: str) -> str | None:
    """Table name of an INSERT carrying explicit ids, else None.

    Postgres sequences do NOT advance on explicit-id inserts (unlike
    sqlite AUTOINCREMENT, which tracks max id), so the adapter must
    resynchronize the table's sequence afterwards or the next
    autoincrement insert collides with an existing id."""
    if not _EXPLICIT_ID_RE.search(sql):
        return None
    m = _INSERT_TABLE_RE.match(sql)
    return m.group(1) if m else None


class PgCursor:
    """DB-API cursor adapter translating History's sqlite idioms."""

    def __init__(self, cur):
        self._cur = cur
        self.lastrowid = None

    def execute(self, sql, params=()):
        if sql.strip().upper().startswith("BEGIN IMMEDIATE"):
            # sqlite's BEGIN IMMEDIATE takes the db write lock up front
            # (History allocates explicit ids from SELECT MAX(id) under
            # it); the postgres equivalent is a transaction-scoped
            # advisory lock serializing all History appenders
            self._cur.execute("BEGIN")
            self._cur.execute(
                "SELECT pg_advisory_xact_lock(hashtext('pyabc_tpu_history'))"
            )
            return self
        sql_t = translate_sql(sql)
        if wants_returning_id(sql):
            self._cur.execute(sql_t + " RETURNING id", params)
            self.lastrowid = self._cur.fetchone()[0]
            return self
        self._cur.execute(sql_t, params)
        return self

    def executemany(self, sql, seq_of_params):
        self._cur.executemany(translate_sql(sql), list(seq_of_params))
        table = explicit_id_insert_table(sql)
        if table is not None:
            # keep the BIGSERIAL sequence ahead of explicitly-inserted ids
            self._cur.execute(
                f"SELECT setval(pg_get_serial_sequence('{table}', 'id'), "
                f"(SELECT COALESCE(MAX(id), 1) FROM {table}))"
            )
        return self

    def fetchone(self):
        return self._cur.fetchone()

    def fetchall(self):
        return self._cur.fetchall()

    @property
    def description(self):
        return self._cur.description

    def close(self):
        self._cur.close()


class PgConnection:
    """DB-API connection adapter with the sqlite3 convenience surface
    History uses (``.execute`` shortcut, ``executescript``)."""

    def __init__(self, conn):
        self._conn = conn

    def cursor(self):
        return PgCursor(self._conn.cursor())

    def execute(self, sql, params=()):
        cur = self.cursor()
        cur.execute(sql, params)
        return cur

    def executescript(self, script):
        cur = self._conn.cursor()
        for stmt in split_script(translate_ddl(script)):
            cur.execute(translate_sql(stmt))
        self._conn.commit()

    def table_columns(self, table: str) -> list[str]:
        cur = self._conn.cursor()
        cur.execute(
            "SELECT column_name FROM information_schema.columns "
            "WHERE table_name = %s", (table,),
        )
        return [r[0] for r in cur.fetchall()]

    def commit(self):
        self._conn.commit()

    def rollback(self):
        self._conn.rollback()

    def close(self):
        self._conn.close()


class Dialect:
    """Per-backend behavior History depends on."""

    name = "sqlite"
    Error = sqlite3.Error
    OperationalError = sqlite3.OperationalError

    @staticmethod
    def table_columns(conn, table: str) -> list[str]:
        return [r[1] for r in conn.execute(f"PRAGMA table_info({table})")]


class PostgresDialect(Dialect):
    name = "postgresql"

    def __init__(self):
        import psycopg2

        self.Error = psycopg2.Error
        self.OperationalError = psycopg2.OperationalError

    @staticmethod
    def table_columns(conn, table: str) -> list[str]:
        return conn.table_columns(table)


def open_database(db: str, sqlite_path_fn):
    """(connection, dialect) for a History db url.

    sqlite URLs return the RAW sqlite3 connection (the default, fully
    tested path); postgresql URLs return the translating psycopg2 adapter.
    ``sqlite_path_fn``: lazy url->filesystem-path resolver (only invoked
    for sqlite urls).
    """
    if db.startswith("postgresql:") or db.startswith("postgres:"):
        try:
            import psycopg2
        except ImportError as err:
            raise ImportError(
                "postgresql History urls need the optional 'psycopg2' "
                "package (pip install psycopg2-binary); sqlite urls work "
                "without any extra dependency"
            ) from err
        conn = PgConnection(psycopg2.connect(db))
        return conn, PostgresDialect()
    conn = sqlite3.connect(sqlite_path_fn(db), check_same_thread=False)
    return conn, Dialect()
