"""Columnar generation-batch storage: Arrow-native History ingest.

The dual-basis gap this closes (ROADMAP "Columnar History"): at
scenario-zoo scale (10^5-10^6 particles x generations x tenants) every
accepted particle used to fan out into per-row ``particles`` /
``parameters`` / ``samples`` SQL inserts — the async writer, not the
fused kernel, became the throughput ceiling. The wire format is already
columnar: the packed fetch ships ONE narrowed ``[theta|distance|
log_weight]`` buffer per chunk. This module lands each
``append_population`` as one Parquet record batch written straight from
those arrays — no per-particle Python round-trips, narrow fetch dtypes
(float16/bfloat16->float32 upcast only where Parquet requires) preserved
on disk instead of widened to REAL.

Layout (hybrid store, selected by ``History(store="columnar")`` or a
``sqlite+columnar:///`` / ``columnar:///`` db URL): run/population/model
METADATA stays in the SQL store (``abc_smc``/``populations``/``models``
rows, observed data at PRE_TIME), while per-particle payloads
(particles/parameters/sumstats) land as one file per generation under a
sidecar directory next to the sqlite file::

    <db>.columnar/run<abc_id>/t<t>.parquet

Durability contracts carried over verbatim from the row store:

- files are written tmp + ``os.replace`` BEFORE the metadata commit, so
  a generation is visible iff both the file and its ``populations`` row
  exist (an orphan file without a row is invisible and overwritten on
  re-append);
- ``prune_from`` deletes metadata rows first (commit), then the
  generation files — the resume seam sees row-truth either way;
- reads auto-detect per generation (file present -> columnar), so a
  plain ``History(db)`` opened on a columnar-written db — the serving
  parity helpers do exactly this — reads it transparently.

pyarrow is OPTIONAL (the ``bytes_storage._has_parquet`` gating
contract): selecting the columnar store without it raises an informative
ImportError at construction; the default row store never imports it.
"""
from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

#: file-level schema version (bump on layout changes; readers reject
#: newer versions loudly instead of misparsing)
SCHEMA_VERSION = 1

#: key the run metadata rides under in the Parquet key-value metadata
_META_KEY = b"pyabc_tpu"


def has_pyarrow() -> bool:
    """Single gating predicate (mirrors ``bytes_storage._has_parquet``)."""
    from .bytes_storage import _has_parquet

    return _has_parquet()


def require_pyarrow(context: str):
    """Import pyarrow or raise the informative gating error."""
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet  # noqa: F401

        return pyarrow
    except ImportError as err:
        raise ImportError(
            f"{context} needs the optional 'pyarrow' package "
            f"(pip install pyarrow); the default row store "
            f"(History(store='rows'), plain sqlite:/// urls) works "
            f"without it"
        ) from err


def _storage_dtype(dt: np.dtype) -> np.dtype:
    """The on-disk dtype for a fetched array: narrow dtypes are kept
    (float16 round-trips through Parquet), except bfloat16 — which
    Parquet has no physical type for — upcast to float32 (exact)."""
    dt = np.dtype(dt)
    if dt.kind == "V" or dt.name == "bfloat16":  # ml_dtypes.bfloat16
        return np.dtype(np.float32)
    return dt


class GenerationBatch:
    """One generation's accepted particles as raw packed-fetch arrays.

    The fused chunk loop hands THIS to ``History.append_population`` for
    columnar-store runs instead of a deferred ``Population`` builder:
    normalization (slot-order sort + stable exp of log weights) runs in
    :meth:`materialize` on the async writer thread, and the narrow fetch
    dtypes survive all the way to disk. The normalization pipeline
    replicates ``Sample.set_accepted`` + ``Population.__init__`` bit for
    bit, so a columnar run's stored posterior is IDENTICAL to the same
    seed's row-store posterior.

    A materialized batch also quacks enough like a ``Population``
    (``ms``/``weights``/``distances``/``thetas``/``sumstats``/``spaces``/
    ``model_probabilities_array``/``get_alive_models``) for the ROW store
    to persist it — the bench's apples-to-apples ingest comparison feeds
    the same batches to both stores.
    """

    def __init__(self, *, ms, thetas, weights, distances, sumstats,
                 param_names, log_weights=None, slots=None):
        #: per-model parameter names (column order of the theta matrix)
        self.param_names = [list(names) for names in param_names]
        self._ms = ms
        self._thetas = thetas
        self._log_weights = log_weights
        self._weights = weights
        self._distances = distances
        self._sumstats = sumstats
        self._slots = slots
        self._materialized = log_weights is None and slots is None
        self._f64 = {}

    # ------------------------------------------------------ construction
    @classmethod
    def from_fetch(cls, *, ms, thetas, log_weights, distances, sumstats,
                   slots, param_names) -> "GenerationBatch":
        """Wrap raw packed-fetch slices (narrow dtypes, proposal-slot
        order pending); normalization is deferred to the writer thread."""
        return cls(ms=ms, thetas=thetas, weights=None, distances=distances,
                   sumstats=sumstats, param_names=param_names,
                   log_weights=log_weights, slots=slots)

    @classmethod
    def from_population(cls, pop) -> "GenerationBatch":
        """Adapt an already-normalized Population (host sampler paths)."""
        return cls(
            ms=pop.ms, thetas=pop.thetas, weights=pop.weights,
            distances=pop.distances, sumstats=pop.sumstats,
            param_names=[list(s.names) for s in pop.spaces],
        )

    def materialize(self) -> "GenerationBatch":
        """Sort by eval-slot id and normalize weights — the exact
        ``Sample.set_accepted`` -> ``Population.__init__`` pipeline, so
        the stored arrays are bit-identical to the row-store path's."""
        if self._materialized:
            return self
        from ..sampler.base import exp_normalize_log_weights

        order = np.argsort(np.asarray(self._slots), kind="stable")
        self._ms = np.asarray(self._ms)[order]
        self._thetas = np.asarray(self._thetas)[order]
        self._distances = np.asarray(self._distances)[order]
        if self._sumstats is not None:
            self._sumstats = np.asarray(self._sumstats)[order]
        log_w = np.asarray(self._log_weights)[order]
        w = exp_normalize_log_weights(log_w)
        total = w.sum()
        if not np.isfinite(total) or total <= 0:
            raise ValueError(f"population total weight invalid: {total}")
        self._weights = w / total
        self._log_weights = self._slots = None
        self._materialized = True
        return self

    # ------------------------------------- raw (narrow-dtype) accessors
    @property
    def ms(self) -> np.ndarray:
        self.materialize()
        return np.asarray(self._ms, np.int32)

    @property
    def weights(self) -> np.ndarray:
        self.materialize()
        return np.asarray(self._weights, np.float64)

    @property
    def thetas_raw(self) -> np.ndarray:
        self.materialize()
        return np.asarray(self._thetas)

    @property
    def distances_raw(self) -> np.ndarray:
        self.materialize()
        return np.asarray(self._distances)

    @property
    def sumstats_raw(self) -> np.ndarray | None:
        self.materialize()
        return (np.asarray(self._sumstats)
                if self._sumstats is not None else None)

    # ------------------------- Population-compatible (row-store) surface
    def _widened(self, name, raw):
        if name not in self._f64:
            self._f64[name] = np.asarray(raw, np.float64)
        return self._f64[name]

    @property
    def thetas(self) -> np.ndarray:
        return self._widened("thetas", self.thetas_raw)

    @property
    def distances(self) -> np.ndarray:
        return self._widened("distances", self.distances_raw)

    @property
    def sumstats(self) -> np.ndarray | None:
        raw = self.sumstats_raw
        return None if raw is None else self._widened("sumstats", raw)

    @property
    def spaces(self):
        from ..core.parameters import ParameterSpace

        return [ParameterSpace(names) for names in self.param_names]

    def model_probabilities_array(self) -> np.ndarray:
        probs = np.zeros(len(self.param_names))
        np.add.at(probs, self.ms, self.weights)
        return probs

    def get_alive_models(self) -> list[int]:
        return [int(m) for m in np.unique(self.ms)]

    def __len__(self) -> int:
        self.materialize()
        return len(np.asarray(self._ms))


class ColumnarStore:
    """One-file-per-generation Parquet persistence under a run directory.

    Owned by a :class:`~pyabc_tpu.storage.history.History`; all calls run
    under the History's lock (no locking here). Pure storage: the
    within-model weight normalization written to disk is computed with
    the SAME float64 operations the row store applies, so every read
    path is bit-compatible across stores.
    """

    def __init__(self, root: str | Path):
        self.root = Path(root)

    # ------------------------------------------------------------- paths
    def run_dir(self, abc_id: int) -> Path:
        return self.root / f"run{int(abc_id)}"

    def gen_path(self, abc_id: int, t: int) -> Path:
        return self.run_dir(abc_id) / f"t{int(t)}.parquet"

    def has(self, abc_id: int | None, t: int) -> bool:
        return abc_id is not None and self.gen_path(abc_id, t).is_file()

    def bytes_on_disk(self, abc_id: int) -> int:
        d = self.run_dir(abc_id)
        if not d.is_dir():
            return 0
        return sum(p.stat().st_size for p in d.glob("t*.parquet"))

    # ------------------------------------------------------------- write
    def write_generation(self, abc_id: int, t: int, pop,
                         store_sumstats: bool) -> tuple[int, int]:
        """Persist one generation's particles as a single record batch.

        ``pop`` is a Population or materialized GenerationBatch. Rows are
        grouped by alive model in ascending-m order, within a model in
        slot order — exactly the row store's particle-id order. Returns
        ``(n_rows, file_bytes)``.
        """
        pa = require_pyarrow("the columnar History store")
        import pyarrow.parquet as pq

        ms = np.asarray(pop.ms)
        weights = np.asarray(pop.weights, np.float64)
        thetas = np.asarray(getattr(pop, "thetas_raw", pop.thetas))
        dists = np.asarray(getattr(pop, "distances_raw", pop.distances))
        ss = getattr(pop, "sumstats_raw", pop.sumstats) \
            if store_sumstats else None
        probs = pop.model_probabilities_array()
        alive = pop.get_alive_models()

        # per-model grouping + within-model weights, float-op-identical
        # to the row store's inserted values
        idx = np.concatenate(
            [np.flatnonzero(ms == m) for m in alive]
        ) if alive else np.zeros(0, np.int64)
        w_model = np.concatenate(
            [weights[ms == m] / probs[m] for m in alive]
        ) if alive else np.zeros(0, np.float64)

        theta_dt = _storage_dtype(thetas.dtype)
        dist_dt = _storage_dtype(dists.dtype)
        n, d_max = thetas.shape
        cols = {
            "m": pa.array(ms[idx].astype(np.int32), pa.int32()),
            "w": pa.array(w_model, pa.float64()),
            "distance": pa.array(dists[idx].astype(dist_dt, copy=False)),
        }
        theta_flat = np.ascontiguousarray(
            thetas[idx].astype(theta_dt, copy=False)).reshape(-1)
        cols["theta"] = pa.FixedSizeListArray.from_arrays(
            pa.array(theta_flat), d_max)
        meta = {
            "v": SCHEMA_VERSION,
            "abc_id": int(abc_id),
            "t": int(t),
            "n": int(n),
            "param_names": [list(names) for names in pop.param_names]
            if hasattr(pop, "param_names")
            else [list(s.names) for s in pop.spaces],
            "theta_dtype": theta_dt.name,
            "distance_dtype": dist_dt.name,
        }
        if ss is not None:
            ss = np.asarray(ss)
            ss_dt = _storage_dtype(ss.dtype)
            ss_flat = np.ascontiguousarray(
                ss[idx].astype(ss_dt, copy=False)).reshape(-1)
            cols["sumstat"] = pa.FixedSizeListArray.from_arrays(
                pa.array(ss_flat), int(ss.shape[1]))
            meta["sumstat_dtype"] = ss_dt.name
        table = pa.table(cols).replace_schema_metadata(
            {_META_KEY: json.dumps(meta).encode()})

        path = self.gen_path(abc_id, t)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        # accepted thetas/distances are high-entropy floats: compression
        # buys little and costs writer-thread CPU — store plain pages
        pq.write_table(table, tmp, compression="none")
        os.replace(tmp, path)
        return int(n), path.stat().st_size

    # -------------------------------------------------------------- read
    def _load(self, abc_id: int, t: int) -> tuple[dict, dict]:
        """(columns as numpy arrays, run metadata) for one generation."""
        require_pyarrow("reading a columnar-store History generation")
        import pyarrow.parquet as pq

        table = pq.read_table(self.gen_path(abc_id, t))
        raw_meta = (table.schema.metadata or {}).get(_META_KEY)
        meta = json.loads(raw_meta.decode()) if raw_meta else {}
        if meta.get("v", SCHEMA_VERSION) > SCHEMA_VERSION:
            raise ValueError(
                f"columnar generation file {self.gen_path(abc_id, t)} has "
                f"schema v{meta['v']} > supported v{SCHEMA_VERSION}"
            )
        n = table.num_rows
        cols = {
            "m": table["m"].combine_chunks().to_numpy(),
            "w": table["w"].combine_chunks().to_numpy(),
            "distance": np.asarray(
                table["distance"].combine_chunks().to_numpy(
                    zero_copy_only=False)),
        }
        for name in ("theta", "sumstat"):
            if name in table.column_names:
                fsl = table[name].combine_chunks()
                width = fsl.type.list_size
                flat = np.asarray(
                    fsl.values.to_numpy(zero_copy_only=False))
                cols[name] = flat.reshape(n, width)
        return cols, meta

    def n_particles(self, abc_id: int, t: int) -> int:
        """Row count from the Parquet footer (no data pages read)."""
        require_pyarrow("reading a columnar-store History generation")
        import pyarrow.parquet as pq

        return pq.ParquetFile(
            self.gen_path(abc_id, t)).metadata.num_rows

    def distribution(self, abc_id: int, t: int, m: int):
        """(parameter DataFrame, within-model normalized weights) —
        the row store's ``get_distribution`` contract (columns sorted by
        name, rows in particle-id order)."""
        import pandas as pd

        cols, meta = self._load(abc_id, t)
        mask = cols["m"] == int(m)
        if not mask.any():
            raise KeyError(f"no particles for model {m} at t={t}")
        names = list(meta["param_names"][int(m)])
        theta = np.asarray(cols["theta"][mask], np.float64)
        # the SQL read path pivots on parameter name, which sorts
        # columns alphabetically — match it so transition refits see the
        # same column order either way
        order = sorted(range(len(names)), key=lambda i: names[i])
        df = pd.DataFrame(
            {names[i]: theta[:, i] for i in order})
        w = np.asarray(cols["w"][mask], np.float64)
        return df, w / w.sum()

    def parameter_names(self, abc_id: int, t: int, m: int) -> list[str]:
        _, meta = self._load(abc_id, t)
        try:
            return sorted(meta["param_names"][int(m)])
        except (KeyError, IndexError):
            raise KeyError(f"no particles for model {m} at t={t}")

    def weighted_distances(self, abc_id: int, t: int,
                           p_by_m: dict[int, float]):
        """['distance', 'w'] with overall-normalized weights — the
        ``particles.w * models.p_model`` join, computed in float64."""
        import pandas as pd

        cols, _ = self._load(abc_id, t)
        p = np.asarray([p_by_m.get(int(m), 0.0) for m in cols["m"]],
                       np.float64)
        return pd.DataFrame({
            "distance": np.asarray(cols["distance"], np.float64),
            "w": cols["w"] * p,
        })

    def weighted_sum_stats(self, abc_id: int, t: int,
                           p_by_m: dict[int, float]):
        """(overall weights, float64 sumstat matrix) or None when the
        generation was stored without sum stats."""
        cols, _ = self._load(abc_id, t)
        if "sumstat" not in cols:
            return None
        p = np.asarray([p_by_m.get(int(m), 0.0) for m in cols["m"]],
                       np.float64)
        return cols["w"] * p, np.asarray(cols["sumstat"], np.float64)

    def population_extended(self, abc_id: int, t: int,
                            model_names: dict[int, str]):
        """The row store's ``get_population_extended`` melt: one row per
        (particle, parameter)."""
        import pandas as pd

        cols, meta = self._load(abc_id, t)
        frames = []
        for m in np.unique(cols["m"]):
            mask = cols["m"] == m
            names = list(meta["param_names"][int(m)])
            theta = np.asarray(cols["theta"][mask], np.float64)
            k = len(names)
            frames.append(pd.DataFrame({
                "m": np.repeat(cols["m"][mask], k),
                "model_name": model_names.get(int(m), f"m{int(m)}"),
                "w": np.repeat(cols["w"][mask], k),
                "distance": np.repeat(
                    np.asarray(cols["distance"][mask], np.float64), k),
                "par_name": np.tile(np.asarray(names, object), mask.sum()),
                "par_value": theta[:, :k].reshape(-1),
            }))
        return (pd.concat(frames, ignore_index=True) if frames
                else pd.DataFrame(columns=[
                    "m", "model_name", "w", "distance",
                    "par_name", "par_value"]))

    # ------------------------------------------------------------- prune
    def prune(self, abc_id: int, t_from: int) -> int:
        """Delete this run's generation files with t >= ``t_from``.

        Called AFTER the metadata-row delete committed: rows are the
        visibility truth, so a crash between the commit and the unlink
        leaves only invisible orphans (overwritten on re-append)."""
        return self._prune(abc_id, lambda t: t >= int(t_from))

    def prune_before(self, abc_id: int, t_before: int) -> int:
        """Delete this run's generation files with t < ``t_before`` —
        the retention-GC direction (keep-last-k / TTL sweeps drop the
        OLDEST generations). Same row-truth ordering contract as
        :meth:`prune`: call only after the metadata-row delete
        committed."""
        return self._prune(abc_id, lambda t: t < int(t_before))

    def _prune(self, abc_id: int, drop) -> int:
        d = self.run_dir(abc_id)
        if not d.is_dir():
            return 0
        removed = 0
        for p in d.glob("t*.parquet"):
            try:
                t = int(p.stem[1:])
            except ValueError:
                continue
            if drop(t):
                p.unlink(missing_ok=True)
                removed += 1
        return removed
