"""Model abstraction: wraps the user simulator.

Reference parity: ``pyabc/model.py::{Model, SimpleModel, ModelResult,
IntegratedModel}``. The reference splits a forward evaluation into
``sample -> summary_statistics -> distance -> accept`` so subclasses can
short-circuit; that split is preserved here. The TPU-first addition is
`JaxModel`: a simulator expressed as a traceable function
``sim(key, theta: f32[dim]) -> {name: array}`` which the batched generation
kernel vmaps and jit-compiles over whole proposal rounds (SURVEY.md §7.1).
Host-only simulators (arbitrary Python) remain supported through `Model` /
`SimpleModel` and run on the host path of the sampler.
"""
from __future__ import annotations

from typing import Callable, Mapping

import jax.numpy as jnp
import numpy as np

from .core.parameters import Parameter, ParameterSpace
from .core.sumstat_spec import SumStatSpec


class ModelResult:
    """Result of a (partial) model evaluation (pyabc ModelResult).

    Carries whichever of sum_stat / distance / accepted have been computed.
    """

    def __init__(self, sum_stat=None, distance=None, accepted=None, weight=1.0):
        self.sum_stat = sum_stat if sum_stat is not None else {}
        self.distance = distance
        self.accepted = accepted
        self.weight = weight


class Model:
    """Base model: subclass and override ``sample`` (pyabc Model).

    ``sample(par) -> raw data``; ``summary_statistics`` defaults to passing
    the raw data through (the reference treats data dicts as sum stats
    unless a sumstat calculator intervenes).
    """

    def __init__(self, name: str = "model"):
        self.name = name

    def __repr__(self):
        return f"{type(self).__name__}({self.name!r})"

    def sample(self, pars: Parameter):
        raise NotImplementedError

    def summary_statistics(self, t, pars, sum_stat_calculator) -> ModelResult:
        raw = self.sample(pars)
        stats = sum_stat_calculator(raw) if sum_stat_calculator else raw
        return ModelResult(sum_stat=stats)

    def distance(self, t, pars, sum_stat_calculator, distance_calculator,
                 x_0) -> ModelResult:
        result = self.summary_statistics(t, pars, sum_stat_calculator)
        result.distance = distance_calculator(result.sum_stat, x_0)
        return result

    def accept(self, t, pars, sum_stat_calculator, distance_calculator, eps,
               acceptor, x_0) -> ModelResult:
        result = self.summary_statistics(t, pars, sum_stat_calculator)
        acc_res = acceptor(
            distance_function=distance_calculator, eps=eps,
            x=result.sum_stat, x_0=x_0, t=t, par=pars,
        )
        result.distance = acc_res.distance
        result.accepted = bool(acc_res.accept)
        result.weight = float(acc_res.weight)
        return result


class SimpleModel(Model):
    """Wrap a plain function ``f(par_dict) -> sum_stat_dict`` (pyabc SimpleModel)."""

    def __init__(self, sample_function: Callable, name: str | None = None):
        super().__init__(name or getattr(sample_function, "__name__", "model"))
        self.sample_function = sample_function

    def sample(self, pars: Parameter):
        return self.sample_function(pars)

    @staticmethod
    def assert_model(model) -> "Model":
        """Coerce a callable into a SimpleModel (pyabc SimpleModel.assert_model)."""
        if isinstance(model, Model):
            return model
        if callable(model):
            return SimpleModel(model)
        raise TypeError(f"cannot coerce {model!r} into a Model")


class IntegratedModel(Model):
    """Model that integrates the accept step into the simulation
    (pyabc IntegratedModel): ``integrated_simulate`` may early-reject a
    too-distant trajectory without finishing it. On TPU the analog is a
    simulator that returns an explicit reject flag; the batched kernel honors
    it as ``accepted=False`` for the lane.
    """

    def integrated_simulate(self, pars, eps) -> ModelResult:
        raise NotImplementedError

    def accept(self, t, pars, sum_stat_calculator, distance_calculator, eps,
               acceptor, x_0) -> ModelResult:
        result = self.integrated_simulate(pars, eps(t))
        if result.accepted is None:
            return super().accept(
                t, pars, sum_stat_calculator, distance_calculator, eps,
                acceptor, x_0,
            )
        return result


class JaxModel(Model):
    """A TPU-native model: traceable batched simulator.

    ``sim(key, theta: f32[dim]) -> {name: jnp array}`` must be jittable with
    static shapes. The generation kernel calls ``vmap(sim)`` over a whole
    proposal round and fuses simulate/distance/accept into one XLA program —
    the TPU inversion of the reference's per-particle pickled closure
    (SURVEY.md §7.1).

    Parameters
    ----------
    sim: the traceable simulator. May be None when ``segmented`` is
        given — the full simulator is then synthesized from the segment
        chain (``ops/segment.py::full_sim_from_segments``), so the
        classic kernel, the host path and the early-reject engine all
        execute the identical per-step math.
    space: parameter name->column registry (order of theta entries).
    name: model display name.
    segmented: optional :class:`~pyabc_tpu.ops.segment.SegmentedSim`
        protocol (carry + fixed-length segment step + per-segment
        partial sum stats). Declaring it makes the model eligible for
        the fused kernel's segmented early-reject execution mode, which
        retires provably-rejected lanes between segments instead of
        paying the full trajectory (ISSUE 15).
    """

    def __init__(self, sim: Callable | None,
                 space: ParameterSpace | list[str],
                 name: str = "jax_model", segmented=None):
        super().__init__(name)
        if not isinstance(space, ParameterSpace):
            space = ParameterSpace(space)
        if sim is None:
            if segmented is None:
                raise ValueError("JaxModel needs sim or segmented")
            from .ops.segment import full_sim_from_segments

            sim = full_sim_from_segments(segmented)
        self.sim = sim
        self.space = space
        #: optional segmented-simulation protocol (early-reject mode)
        self.segmented = segmented
        self._sumstat_spec: SumStatSpec | None = None
        self._jitted_sim = None

    def sumstat_spec(self, key=None) -> SumStatSpec:
        """Infer the flat sum-stat layout by one example evaluation."""
        if self._sumstat_spec is None:
            import jax

            key = key if key is not None else jax.random.key(0)
            theta = jnp.zeros((self.space.dim,), jnp.float32)
            example = jax.eval_shape(self.sim, key, theta)
            self._sumstat_spec = SumStatSpec(
                {k: np.zeros(v.shape, np.float32) for k, v in example.items()}
            )
        return self._sumstat_spec

    def sample(self, pars: Parameter):
        """Host-path escape hatch: single evaluation with a fresh key."""
        import jax

        if self._jitted_sim is None:
            self._jitted_sim = jax.jit(self.sim)
        key = jax.random.key(np.random.randint(0, 2**31 - 1))
        theta = jnp.asarray(self.space.to_array(pars), jnp.float32)
        out = self._jitted_sim(key, theta)
        return {k: np.asarray(v) for k, v in out.items()}

    def content_hash(self) -> str:
        """Identity of the TRACED computation, not the display name.

        Digests the simulator's code object plus every value its
        closure cells and defaults capture (recursing through nested
        functions), so two models built under the same name but closing
        over different constants — e.g. a builder-parameterized noise
        scale — hash differently. The serving kernel cache keys
        compiled programs on this: a name-only key would hand tenant B
        tenant A's kernels and silently compute the wrong posterior.
        """
        import hashlib

        h = hashlib.sha256()
        h.update(self.name.encode())
        h.update("|".join(self.space.names).encode())
        _digest_callable(self.sim, h, set())
        if self.segmented is not None:
            # the segmented twin is part of the traced identity: two
            # models with equal full sims but different segment chains
            # compile different early-reject programs
            h.update(str(self.segmented.n_segments).encode())
            h.update(repr(self.segmented.layout).encode())
            _digest_callable(self.segmented.init, h, set())
            _digest_callable(self.segmented.step, h, set())
        return h.hexdigest()

    @staticmethod
    def from_function(space, name="jax_model"):
        """Decorator form: ``@JaxModel.from_function(["a","b"])``."""
        def wrap(fn):
            return JaxModel(fn, space, name=name)
        return wrap


def _digest_value(v, h, seen: set) -> None:
    """Feed one captured value into ``h``: functions recurse, numerics
    go in as dtype/shape/bytes, everything else as repr."""
    if callable(v) and hasattr(v, "__code__"):
        _digest_callable(v, h, seen)
        return
    try:
        arr = np.asarray(v)
    except Exception:
        arr = None  # unconvertible capture: repr is its identity
    if arr is not None and arr.dtype != object:
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
        return
    h.update(repr(v).encode())


def _digest_callable(fn, h, seen: set) -> None:
    import types

    if id(fn) in seen:
        return
    seen.add(id(fn))
    code = getattr(fn, "__code__", None)
    if code is None:
        h.update(repr(fn).encode())
        return
    h.update(code.co_code)
    h.update("|".join(code.co_names).encode())
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            h.update(const.co_code)
        else:
            h.update(repr(const).encode())
    for cell in fn.__closure__ or ():
        try:
            _digest_value(cell.cell_contents, h, seen)
        except ValueError:  # empty cell
            h.update(b"<empty-cell>")
    for default in fn.__defaults__ or ():
        _digest_value(default, h, seen)


def assert_models(models) -> list[Model]:
    """Coerce a model or list of models/callables into a list of Models."""
    if not isinstance(models, (list, tuple)):
        models = [models]
    return [SimpleModel.assert_model(m) for m in models]
