"""Multi-host (multi-process) execution over DCN.

The reference scales beyond one node with a Redis broker + elastic workers
(``pyabc/sampler/redis_eps/sampler.py``, SURVEY.md §2.3/§5.8). The
TPU-native replacement is the JAX multi-controller runtime: every host runs
the SAME ABCSMC program (SPMD), the particle axis is sharded over a global
``Mesh`` spanning all hosts' devices, and the per-generation barrier that
the reference implements with Redis counters is simply the collective at
the end of the fused generation kernel — XLA moves data over ICI within a
slice and DCN across slices.

Usage (one process per host, identical code on each)::

    from pyabc_tpu.parallel import distributed as dist

    dist.initialize()                     # env-driven (or pass args)
    mesh = dist.global_mesh()
    abc = pt.ABCSMC(model, prior, ..., mesh=mesh, seed=0)
    abc.new(dist.primary_db("sqlite:///run.db"), obs)
    abc.run(max_nr_populations=10)

Determinism contract: every host must construct ABCSMC with the SAME seed
and configuration. All device work is collective; all host-side adaptation
is replicated deterministically (numpy on identical inputs), so the hosts
stay in lock-step without any broker. Only the primary host persists to a
real database (``primary_db``); the others write to throwaway in-memory
stores.

Elasticity note (honest deviation): TPU slices are gang-scheduled — worker
join/leave mid-generation (the Redis sampler's elasticity) does not exist
here; recovery is checkpoint/resume via the History db (SURVEY.md §5.3/§5.4).
"""
from __future__ import annotations

import os

import numpy as np


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None, *,
               platform: str | None = None,
               num_cpu_devices: int | None = None,
               cpu_collectives: str = "gloo") -> None:
    """``jax.distributed.initialize`` with env-var defaults.

    Env fallbacks: ``PYABC_TPU_COORDINATOR``, ``PYABC_TPU_NUM_PROCESSES``,
    ``PYABC_TPU_PROCESS_ID`` — or, on real multi-host TPU pods, pass nothing
    and let JAX's cluster auto-detection fill everything in.

    ``platform='cpu'`` + ``num_cpu_devices=N`` force an N-virtual-device CPU
    backend per process (the multi-host-as-multi-process-on-localhost test
    rig, mirroring the reference's localhost Redis tests); CPU cross-process
    collectives use ``cpu_collectives`` ('gloo').
    """
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
    if num_cpu_devices is not None:
        try:
            jax.config.update("jax_num_cpu_devices", int(num_cpu_devices))
        except AttributeError:
            # older jax (< 0.5) has no jax_num_cpu_devices config; the
            # XLA flag is the portable spelling of the same knob and must
            # land BEFORE the backend initializes (we're pre-initialize
            # by contract here)
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{int(num_cpu_devices)}"
                ).strip()
    if platform == "cpu" or num_cpu_devices is not None:
        jax.config.update(
            "jax_cpu_collectives_implementation", cpu_collectives
        )
    coordinator_address = coordinator_address or os.environ.get(
        "PYABC_TPU_COORDINATOR"
    )
    if num_processes is None and "PYABC_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["PYABC_TPU_NUM_PROCESSES"])
    if process_id is None and "PYABC_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["PYABC_TPU_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id,
    )


def global_mesh(axis_name: str = "particles"):
    """1-D mesh over ALL devices of ALL processes (DCN + ICI)."""
    import jax
    from jax.sharding import Mesh

    # abc-lint: disable=SYNC001 np.asarray reshapes the host-side Device LIST for Mesh; no array leaves a device
    return Mesh(np.asarray(jax.devices()), axis_names=(axis_name,))


def local_mesh(n_devices: int | None = None, axis_name: str = "particles"):
    """Single-process 1-D mesh over THIS process's devices — the mesh
    the sharded fused path (``ABCSMC(mesh=..., sharded=...)``) shards
    the population axis over. On CPU hosts the standard test rig forces
    virtual devices first (``XLA_FLAGS=--xla_force_host_platform_device_
    count=8``); pass ``n_devices`` to cap the width (power-of-two widths
    divide the power-of-two lane/reservoir buckets evenly)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.local_devices()
    if n_devices is not None:
        devs = devs[: int(n_devices)]
    # abc-lint: disable=SYNC001 np.asarray reshapes the host-side Device LIST for Mesh; no array leaves a device
    return Mesh(np.asarray(devs), axis_names=(axis_name,))


def is_primary() -> bool:
    import jax

    return jax.process_index() == 0


def primary_db(db: str) -> str:
    """The real db url on the primary host, a throwaway in-memory store on
    the others (the History is written identically everywhere; one copy is
    enough and sqlite files must not be shared over NFS)."""
    return db if is_primary() else "sqlite://"


def barrier(name: str = "pyabc_tpu_barrier") -> None:
    """Explicit cross-host sync point (rarely needed: every generation's
    collective already synchronizes)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
