"""Multi-host (multi-process) execution over DCN.

The reference scales beyond one node with a Redis broker + elastic workers
(``pyabc/sampler/redis_eps/sampler.py``, SURVEY.md §2.3/§5.8). The
TPU-native replacement is the JAX multi-controller runtime: every host runs
the SAME ABCSMC program (SPMD), the particle axis is sharded over a global
``Mesh`` spanning all hosts' devices, and the per-generation barrier that
the reference implements with Redis counters is simply the collective at
the end of the fused generation kernel — XLA moves data over ICI within a
slice and DCN across slices.

Usage (one process per host, identical code on each)::

    from pyabc_tpu.parallel import distributed as dist

    dist.initialize()                     # env-driven (or pass args)
    mesh = dist.global_mesh()
    abc = pt.ABCSMC(model, prior, ..., mesh=mesh, seed=0)
    abc.new(dist.primary_db("sqlite:///run.db"), obs)
    abc.run(max_nr_populations=10)

Determinism contract: every host must construct ABCSMC with the SAME seed
and configuration. All device work is collective; all host-side adaptation
is replicated deterministically (numpy on identical inputs), so the hosts
stay in lock-step without any broker. Only the primary host persists to a
real database (``primary_db``); the others write to throwaway in-memory
stores.

Elasticity note (honest deviation): TPU slices are gang-scheduled — worker
join/leave mid-generation (the Redis sampler's elasticity) does not exist
here; recovery is checkpoint/resume via the History db (SURVEY.md §5.3/§5.4).
"""
from __future__ import annotations

import os

import numpy as np


class DistributedConfigError(RuntimeError):
    """A multi-host configuration error caught BEFORE it reaches the JAX
    runtime: partial env (coordinator without a process count, or the
    reverse) and conflicting re-initialization both used to surface as
    opaque late failures inside ``jax.distributed.initialize``."""


#: the config of the one successful :func:`initialize` call (None until
#: then). The JAX distributed runtime cannot be re-initialized, so a
#: second call with the SAME config is a no-op and a second call with a
#: DIFFERENT config is a typed error instead of a runtime crash.
_INIT_CONFIG: dict | None = None


def _resolve_init_config(coordinator_address, num_processes, process_id, *,
                         platform, num_cpu_devices,
                         cpu_collectives) -> dict:
    """Merge explicit args with the PYABC_TPU_* env fallbacks and reject
    partial configurations with a typed error."""
    coordinator_address = coordinator_address or os.environ.get(
        "PYABC_TPU_COORDINATOR"
    )
    if num_processes is None and "PYABC_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["PYABC_TPU_NUM_PROCESSES"])
    if process_id is None and "PYABC_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["PYABC_TPU_PROCESS_ID"])
    # explicit coordination needs the full triple: a coordinator without a
    # process count (or the reverse) dies deep inside the JAX client with
    # a timeout/assert long after the real mistake — fail here, named.
    explicit = {
        "coordinator_address": coordinator_address,
        "num_processes": num_processes,
        "process_id": process_id,
    }
    given = {k for k, v in explicit.items() if v is not None}
    if given and given != set(explicit):
        missing = sorted(set(explicit) - given)
        raise DistributedConfigError(
            "partial multi-host configuration: "
            f"{sorted(given)} set but {missing} missing — pass all of "
            "coordinator_address/num_processes/process_id (env: "
            "PYABC_TPU_COORDINATOR / PYABC_TPU_NUM_PROCESSES / "
            "PYABC_TPU_PROCESS_ID), or none of them for TPU-pod "
            "auto-detection"
        )
    return dict(explicit, platform=platform,
                num_cpu_devices=num_cpu_devices,
                cpu_collectives=cpu_collectives)


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None, *,
               platform: str | None = None,
               num_cpu_devices: int | None = None,
               cpu_collectives: str = "gloo") -> None:
    """``jax.distributed.initialize`` with env-var defaults.

    Env fallbacks: ``PYABC_TPU_COORDINATOR``, ``PYABC_TPU_NUM_PROCESSES``,
    ``PYABC_TPU_PROCESS_ID`` — or, on real multi-host TPU pods, pass nothing
    and let JAX's cluster auto-detection fill everything in. A partial
    config (coordinator without a process count, or the reverse) raises
    :class:`DistributedConfigError` here instead of timing out inside the
    JAX client.

    Idempotent: a second call with the SAME resolved config is a no-op;
    a second call with a DIFFERENT config raises
    :class:`DistributedConfigError` (the runtime cannot re-initialize).
    Both guards run BEFORE any ``jax.config`` mutation, so a rejected
    call leaves the process untouched.

    ``platform='cpu'`` + ``num_cpu_devices=N`` force an N-virtual-device CPU
    backend per process (the multi-host-as-multi-process-on-localhost test
    rig, mirroring the reference's localhost Redis tests); CPU cross-process
    collectives use ``cpu_collectives`` ('gloo').
    """
    global _INIT_CONFIG
    config = _resolve_init_config(
        coordinator_address, num_processes, process_id,
        platform=platform, num_cpu_devices=num_cpu_devices,
        cpu_collectives=cpu_collectives,
    )
    if _INIT_CONFIG is not None:
        if config == _INIT_CONFIG:
            return  # already initialized with this exact config
        raise DistributedConfigError(
            "jax.distributed is already initialized with a different "
            f"config: first {_INIT_CONFIG!r}, now {config!r} — the "
            "runtime cannot be re-initialized; restart the process to "
            "change the mesh topology"
        )
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
    if num_cpu_devices is not None:
        try:
            jax.config.update("jax_num_cpu_devices", int(num_cpu_devices))
        except AttributeError:
            # older jax (< 0.5) has no jax_num_cpu_devices config; the
            # XLA flag is the portable spelling of the same knob and must
            # land BEFORE the backend initializes (we're pre-initialize
            # by contract here)
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{int(num_cpu_devices)}"
                ).strip()
    if platform == "cpu" or num_cpu_devices is not None:
        jax.config.update(
            "jax_cpu_collectives_implementation", cpu_collectives
        )
    jax.distributed.initialize(
        coordinator_address=config["coordinator_address"],
        num_processes=config["num_processes"],
        process_id=config["process_id"],
    )
    _INIT_CONFIG = config


def global_mesh(axis_name: str = "particles"):
    """1-D mesh over ALL devices of ALL processes (DCN + ICI)."""
    import jax
    from jax.sharding import Mesh

    # abc-lint: disable=SYNC001 np.asarray reshapes the host-side Device LIST for Mesh; no array leaves a device
    return Mesh(np.asarray(jax.devices()), axis_names=(axis_name,))


def local_mesh(n_devices: int | None = None, axis_name: str = "particles"):
    """Single-process 1-D mesh over THIS process's devices — the mesh
    the sharded fused path (``ABCSMC(mesh=..., sharded=...)``) shards
    the population axis over. On CPU hosts the standard test rig forces
    virtual devices first (``XLA_FLAGS=--xla_force_host_platform_device_
    count=8``); pass ``n_devices`` to cap the width (power-of-two widths
    divide the power-of-two lane/reservoir buckets evenly)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.local_devices()
    if n_devices is not None:
        devs = devs[: int(n_devices)]
    # abc-lint: disable=SYNC001 np.asarray reshapes the host-side Device LIST for Mesh; no array leaves a device
    return Mesh(np.asarray(devs), axis_names=(axis_name,))


def is_primary() -> bool:
    import jax

    return jax.process_index() == 0


def process_count() -> int:
    """Number of processes in the distributed runtime (1 if
    single-process)."""
    import jax

    return jax.process_count()


def primary_db(db: str) -> str:
    """The real db url on the primary host, a throwaway in-memory store on
    the others (the History is written identically everywhere; one copy is
    enough and sqlite files must not be shared over NFS)."""
    return db if is_primary() else "sqlite://"


def resume_db(db: str) -> str:
    """The db url to ``load()`` from when RESUMING a preempted multi-host
    run.

    Checkpoint adoption validates ``abc_id`` + run fingerprint against the
    History, so every process must rebuild IDENTICAL resume state — but
    only the primary may keep writing the real file (sqlite files must not
    be written concurrently). On the primary this is just ``db``; every
    other process gets a private COPY of the primary's sqlite file
    (``<path>.proc<i>``), read at load time and thrown away with the
    process. Non-file urls (including in-memory) have nothing to copy and
    fall back to the throwaway store."""
    import jax

    if jax.process_index() == 0:
        return db
    prefix = "sqlite:///"
    if not db.startswith(prefix):
        return "sqlite://"
    path = db[len(prefix):]
    if not os.path.exists(path):
        return "sqlite://"
    import sqlite3

    copy = f"{path}.proc{jax.process_index()}"
    # the backup API folds the -wal sidecar in; a bare file copy would
    # silently drop every commit still living in the WAL
    src = sqlite3.connect(path)
    try:
        dst = sqlite3.connect(copy)
        try:
            src.backup(dst)
        finally:
            dst.close()
    finally:
        src.close()
    return prefix + copy


def barrier(name: str = "pyabc_tpu_barrier") -> None:
    """Explicit cross-host sync point (rarely needed: every generation's
    collective already synchronizes)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


# --------------------------------------------------------- host clocks
#
# Span timestamps are per-process monotonic readings with no shared
# epoch, so merging a secondary host's trace onto the coordinator's
# timeline needs a measured offset. The rig is the ClockOffsetEstimator's
# NTP exchange over a bare TCP socket: each probe sends one byte at local
# t1, the remote replies with its clock at t2, the reply lands at t4 —
# offset = t2 - (t1+t4)/2, uncertainty = RTT/2 (clock.py). No JAX
# involved: the exchange must work before (and independent of) the
# distributed runtime.

def serve_clock(port: int = 0, clock=None):
    """Serve this process's monotonic clock over TCP for offset probes.

    Returns ``(port, stop)``: the bound port and a zero-argument callable
    that shuts the server down. Each connection answers any number of
    1-byte probes, each with the 8-byte big-endian float ``clock.now()``.
    """
    import socket
    import struct
    import threading

    from ..observability.clock import SYSTEM_CLOCK

    clock = clock or SYSTEM_CLOCK
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", int(port)))
    srv.listen(8)
    stopping = threading.Event()

    def _handle(conn):
        with conn:
            while not stopping.is_set():
                try:
                    if not conn.recv(1):
                        return
                    conn.sendall(struct.pack("!d", clock.now()))
                except OSError:
                    return

    def _accept_loop():
        while not stopping.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(
                target=_handle, args=(conn,), daemon=True
            ).start()

    bound_port = srv.getsockname()[1]
    thread = threading.Thread(target=_accept_loop, daemon=True)
    thread.start()

    def stop():
        stopping.set()
        try:
            srv.close()
        except OSError:
            pass

    return bound_port, stop


def measure_clock_offset(address: str, *, host: str | None = None,
                         n_samples: int = 16, clock=None):
    """Measure a remote host's clock offset against the local clock.

    ``address`` is ``"host:port"`` of a :func:`serve_clock` endpoint.
    Runs ``n_samples`` NTP-style exchanges through a
    :class:`~pyabc_tpu.observability.ClockOffsetEstimator` (min-RTT
    sample wins) and returns the estimator. When ``host`` is given the
    summary is also recorded into the process-wide observability
    snapshot's per-host clock table
    (:func:`~pyabc_tpu.observability.record_host_clock_offset`).
    """
    import socket
    import struct

    from .. import observability
    from ..observability.clock import ClockOffsetEstimator, SYSTEM_CLOCK

    clock = clock or SYSTEM_CLOCK
    hostname, _, port = address.rpartition(":")
    est = ClockOffsetEstimator()
    with socket.create_connection((hostname, int(port)), timeout=30) as s:
        for _ in range(int(n_samples)):
            t1 = clock.now()
            s.sendall(b"p")
            buf = b""
            while len(buf) < 8:
                chunk = s.recv(8 - len(buf))
                if not chunk:
                    raise ConnectionError(
                        f"clock server at {address} closed mid-probe")
                buf += chunk
            t4 = clock.now()
            (t2_remote,) = struct.unpack("!d", buf)
            est.add_sample(t1, t2_remote, t4)
    if host is not None:
        observability.record_host_clock_offset(host, est.summary())
    return est


# ------------------------------------------------------ span federation
#
# A PR-18 pod run leaves every non-primary process's spans stranded in
# that process: the gap accountant and the flight recorder only saw the
# primary's share of the wall clock. Federation ships bounded span
# summaries to the primary over the same kind of bare-TCP side channel
# as the clock rig — pure host-side I/O piggybacked on the
# per-generation cadence (the dispatch engine fires the ship hook next
# to its chunk-event callback), so it adds ZERO blocking host<->device
# round trips: nothing here may touch a device or the SyncLedger, and
# the strict sync budget asserts federation on/off identical.
#
# Batch wire format: 4-byte big-endian length + JSON object
# {"host": str, "process_id": int, "spans": [span dicts]}. The primary
# merges via observability.ingest_remote_spans, which offset-corrects
# each span with the measured host-clock table onto host:<p>
# pseudo-threads.

def serve_span_sink(port: int = 0, *, tracer=None, on_batch=None):
    """Primary-side federation sink; returns ``(port, stop)``.

    Each received batch merges into the process-wide federated span
    buffer (offset-corrected — see
    :func:`~pyabc_tpu.observability.ingest_remote_spans`); ``tracer``
    overrides the mirror target, ``on_batch(batch_dict)`` is an
    optional test/bench tap. Malformed batches drop the connection,
    never the server."""
    import json
    import socket
    import struct
    import threading

    from .. import observability

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", int(port)))
    srv.listen(8)
    stopping = threading.Event()

    def _recv_exact(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _handle(conn):
        with conn:
            while not stopping.is_set():
                try:
                    head = _recv_exact(conn, 4)
                    if head is None:
                        return
                    (length,) = struct.unpack("!I", head)
                    body = _recv_exact(conn, length)
                    if body is None:
                        return
                    batch = json.loads(body)
                    observability.ingest_remote_spans(
                        str(batch.get("host", "?")),
                        int(batch.get("process_id", -1)),
                        batch.get("spans") or (),
                        tracer=tracer,
                    )
                    if on_batch is not None:
                        on_batch(batch)
                except (OSError, ValueError, KeyError):
                    return

    def _accept_loop():
        while not stopping.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(
                target=_handle, args=(conn,), daemon=True
            ).start()

    bound_port = srv.getsockname()[1]
    threading.Thread(target=_accept_loop, daemon=True).start()

    def stop():
        stopping.set()
        try:
            srv.close()
        except OSError:
            pass

    return bound_port, stop


class SpanShipper:
    """Ships a tracer's new finished spans to a federation sink.

    Owned by a NON-primary process; ``ship()`` collects spans finished
    since the last call (cursor by span id, so the tracer's bounded
    buffer dropping old spans can't replay), truncates to
    ``max_spans_per_batch`` newest, and sends one batch over the
    persistent TCP connection. Plain host-side I/O on the generation
    cadence: a ship failure DISABLES the shipper (federation is
    best-effort observability) and never propagates into the run.

    ``install()`` registers ``ship`` with the observability-layer ship
    hook the dispatch engine fires per processed chunk; ``close()``
    unregisters and drops the socket.
    """

    def __init__(self, address: str, *, host: str, process_id: int,
                 tracer, max_spans_per_batch: int = 256):
        self.address = str(address)
        self.host = str(host)
        self.process_id = int(process_id)
        self._tracer = tracer
        self._max_batch = int(max_spans_per_batch)
        self._cursor = 0
        self._sock = None
        self._dead = False
        self.n_shipped = 0

    @classmethod
    def from_env(cls, tracer, *, process_id: int | None = None,
                 host: str | None = None):
        """A shipper targeting ``PYABC_TPU_SPAN_SINK`` (``host:port``),
        or None when the env var is unset — the opt-in production
        spelling; tests/bench construct explicitly."""
        address = os.environ.get("PYABC_TPU_SPAN_SINK")
        if not address:
            return None
        if process_id is None:
            import jax

            process_id = jax.process_index()
        return cls(address, host=host or f"proc{process_id}",
                   process_id=process_id, tracer=tracer)

    def _connect(self):
        import socket

        if self._sock is None:
            hostname, _, port = self.address.rpartition(":")
            self._sock = socket.create_connection(
                (hostname, int(port)), timeout=30)
        return self._sock

    def ship(self) -> int:
        """Send spans finished since the last ship; returns the count
        (0 after a failure has disabled the shipper)."""
        import json
        import struct

        if self._dead:
            return 0
        fresh = [sp for sp in self._tracer.spans()
                 if sp.span_id > self._cursor
                 and not str(sp.thread).startswith("host:")]
        if not fresh:
            return 0
        self._cursor = max(sp.span_id for sp in fresh)
        fresh = fresh[-self._max_batch:]
        body = json.dumps({
            "host": self.host,
            "process_id": self.process_id,
            "spans": [sp.to_dict() for sp in fresh],
        }).encode("utf-8")
        try:
            sock = self._connect()
            sock.sendall(struct.pack("!I", len(body)) + body)
        except OSError:
            self._dead = True
            self._sock = None
            return 0
        self.n_shipped += len(fresh)
        return len(fresh)

    def install(self) -> "SpanShipper":
        from .. import observability

        observability.install_span_ship_hook(self.ship)
        return self

    def close(self) -> None:
        from .. import observability

        observability.uninstall_span_ship_hook(self.ship)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
