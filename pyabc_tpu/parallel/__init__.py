"""Multi-device / multi-host execution utilities."""
from . import distributed

__all__ = ["distributed"]
