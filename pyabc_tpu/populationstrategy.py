"""Population-size strategies.

Reference parity: ``pyabc/populationstrategy.py::{PopulationStrategy,
ConstantPopulationSize, AdaptivePopulationSize, ListPopulationSize}`` and the
bootstrap-CV machinery of ``pyabc/cv/bootstrap.py::calc_cv``.
"""
from __future__ import annotations

import logging
from typing import Callable

import numpy as np

logger = logging.getLogger("ABC.PopulationSize")


class PopulationStrategy:
    """Decides the number of particles per generation (pyabc PopulationStrategy)."""

    def __init__(self, nr_calibration_particles: int | None = None):
        self.nr_calibration_particles = nr_calibration_particles

    def update(self, transitions, model_weights, t: int | None = None) -> None:
        """Adapt using the fitted transitions of generation t."""

    def __call__(self, t: int | None = None) -> int:
        raise NotImplementedError

    def get_config(self) -> dict:
        return {"name": type(self).__name__}


class ConstantPopulationSize(PopulationStrategy):
    """Same n every generation (pyabc ConstantPopulationSize)."""

    def __init__(self, nr_particles: int,
                 nr_calibration_particles: int | None = None):
        super().__init__(nr_calibration_particles)
        self.nr_particles = int(nr_particles)

    def __call__(self, t: int | None = None) -> int:
        return self.nr_particles

    def get_config(self):
        return {"name": type(self).__name__, "nr_particles": self.nr_particles}


class ListPopulationSize(PopulationStrategy):
    """Pre-specified n per generation (pyabc ListPopulationSize)."""

    def __init__(self, values, nr_calibration_particles: int | None = None):
        super().__init__(nr_calibration_particles)
        self.values = [int(v) for v in values]

    def __call__(self, t: int | None = None) -> int:
        return self.values[t]


def calc_cv(t_nr_particles: int, model_weights: np.ndarray,
            nr_bootstrap: int, transitions) -> float:
    """Mean bootstrap CV of the KDE density estimate at size ``t_nr_particles``
    (reference ``pyabc/cv/bootstrap.py::calc_cv``), weighted over models."""
    cvs = []
    for trans in transitions:
        old = trans.NR_BOOTSTRAP
        trans.NR_BOOTSTRAP = nr_bootstrap
        try:
            cvs.append(trans.mean_cv(t_nr_particles))
        finally:
            trans.NR_BOOTSTRAP = old
    mw = np.asarray(model_weights, np.float64)
    mw = mw / mw.sum()
    return float(np.sum(mw[: len(cvs)] * np.asarray(cvs)))


class AdaptivePopulationSize(PopulationStrategy):
    """Choose the next n so the bootstrap CV of the KDE stays at
    ``mean_cv`` (pyabc AdaptivePopulationSize): bisection over n using
    bootstrap replicates of the fitted transitions."""

    def __init__(self, start_nr_particles: int, mean_cv: float = 0.05,
                 max_population_size: int = np.inf,
                 min_population_size: int = 10,
                 nr_samples_per_parameter: int = 1,
                 n_bootstrap: int = 10,
                 nr_calibration_particles: int | None = None):
        super().__init__(nr_calibration_particles)
        self.start_nr_particles = int(start_nr_particles)
        self.mean_cv = float(mean_cv)
        self.max_population_size = max_population_size
        self.min_population_size = int(min_population_size)
        self.n_bootstrap = int(n_bootstrap)
        self.nr_particles = int(start_nr_particles)

    def __call__(self, t: int | None = None) -> int:
        return self.nr_particles

    def update(self, transitions, model_weights, t: int | None = None) -> None:
        reference_nr = self.nr_particles
        lo = self.min_population_size
        hi = (
            int(self.max_population_size)
            if np.isfinite(self.max_population_size)
            else max(10 * reference_nr, 1000)
        )

        def cv_at(n):
            return calc_cv(n, model_weights, self.n_bootstrap, transitions)

        try:
            if cv_at(hi) > self.mean_cv:
                self.nr_particles = hi
            else:
                while lo < hi:
                    mid = (lo + hi) // 2
                    if cv_at(mid) <= self.mean_cv:
                        hi = mid
                    else:
                        lo = mid + 1
                self.nr_particles = int(
                    np.clip(hi, self.min_population_size,
                            self.max_population_size
                            if np.isfinite(self.max_population_size)
                            else hi)
                )
        except Exception as e:  # transitions may be degenerate early on
            logger.warning("AdaptivePopulationSize update failed: %s", e)
        logger.info(
            "Adapted population size from %d to %d", reference_nr,
            self.nr_particles,
        )

    def get_config(self):
        return {
            "name": type(self).__name__,
            "start_nr_particles": self.start_nr_particles,
            "mean_cv": self.mean_cv,
        }
