"""COPASI biochemical-model adapter (reference parity: ``pyabc/copasi``:
the BasiCO-based ``BasicoModel``).

In-process via the optional ``basico`` package (COPASI's python
bindings), mirroring the reference: load a .cps/.sbml file, set the
sampled parameters, run a time course, return named trajectories.
``basico`` is not installed in minimal environments; construction raises
an informative error (the gating contract shared by all external
adapters). For COPASI models exported to SBML without python bindings,
drive them through :class:`pyabc_tpu.external.ExternalModel` with a
wrapper script instead.
"""
from __future__ import annotations

import os

import numpy as np

from ..model import Model


class BasicoModel(Model):
    """A COPASI model file as a simulator via basico (reference
    ``pyabc.copasi.BasicoModel``).

    ``sample(pars)`` applies each named parameter (trying reaction
    parameters first, then global quantities — COPASI models expose
    tunables as either), runs a time course of ``duration`` with
    ``n_points`` outputs, and returns ``{column: trajectory}``.
    """

    def __init__(self, model_file: str, duration: float = 100.0,
                 n_points: int = 100, method: str = "deterministic",
                 outputs: list[str] | None = None, name: str | None = None):
        super().__init__(
            name=name or f"BasicoModel({os.path.basename(model_file)})"
        )
        try:
            import basico  # noqa: F401
        except ImportError as err:
            raise ImportError(
                "BasicoModel needs the optional 'basico' package (COPASI "
                "python bindings; pip install copasi-basico). For COPASI "
                "models without python bindings wrap CopasiSE in an "
                "ExternalModel script."
            ) from err
        self.model_file = os.path.abspath(model_file)
        self.duration = float(duration)
        self.n_points = int(n_points)
        self.method = method
        self.outputs = outputs

    @staticmethod
    def _apply_parameter(basico, dm, key: str, value: float) -> None:
        """Set a tunable by name: reaction/local parameter OR global
        quantity (silently targeting only one class loses the other —
        the parameter would keep its file default for every particle)."""
        applied = False
        params = basico.get_parameters(key, model=dm)
        if params is not None and len(params) > 0:
            basico.set_parameters(key, initial_value=value, model=dm)
            applied = True
        quants = basico.get_global_quantities(key, model=dm)
        if quants is not None and len(quants) > 0:
            basico.set_global_quantities(key, initial_value=value, model=dm)
            applied = True
        if not applied:
            raise KeyError(
                f"parameter {key!r} matches neither a reaction parameter "
                f"nor a global quantity of the COPASI model"
            )

    def sample(self, pars):  # exercised against a mock basico in tests
        import basico

        dm = basico.load_model(self.model_file)
        try:
            for k, v in dict(pars).items():
                self._apply_parameter(basico, dm, k, float(v))
            tc = basico.run_time_course(
                duration=self.duration, intervals=self.n_points - 1,
                method=self.method, model=dm,
            )
            cols = self.outputs or list(tc.columns)
            return {c: tc[c].to_numpy(np.float64) for c in cols}
        finally:
            basico.remove_datamodel(dm)
