"""COPASI model adapter (reference parity: ``pyabc/copasi``)."""
from .model import BasicoModel

__all__ = ["BasicoModel"]
