"""Committed baseline of grandfathered abc-lint findings.

The baseline exists so the engine could land with zero tolerance for NEW
violations while the handful of pre-existing, deliberate sites were
recorded rather than churned. Contract:

- every entry carries a non-empty ``reason`` (same bar as inline
  suppressions);
- an entry matches findings by ``(rule, path, code)`` — the stripped
  source text of the offending line — NOT by line number, so unrelated
  edits don't invalidate it but touching the offending line re-opens it;
- **the baseline only shrinks**: an entry that matches no live finding
  is STALE and fails the lint, so a fixed violation must be deleted from
  the file (grandfathering can't silently accumulate).
"""
from __future__ import annotations

import json
from pathlib import Path

from .engine import AnalysisResult, Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = ".abc-lint-baseline.json"


class BaselineError(ValueError):
    """Malformed baseline file (bad schema or entry without a reason)."""


def load(path: Path) -> list[dict]:
    data = json.loads(Path(path).read_text())
    if not isinstance(data, dict) or data.get("version") != BASELINE_VERSION:
        raise BaselineError(
            f"{path}: expected a baseline object with version="
            f"{BASELINE_VERSION}")
    entries = data.get("entries", [])
    for i, e in enumerate(entries):
        missing = {"rule", "path", "code", "reason"} - set(e)
        if missing:
            raise BaselineError(f"{path}: entry {i} missing {sorted(missing)}")
        if not str(e["reason"]).strip():
            raise BaselineError(
                f"{path}: entry {i} ({e['rule']} {e['path']}) has an empty "
                "reason — every baselined finding must say why it stays")
    return entries


def apply(result: AnalysisResult, entries: list[dict]) -> None:
    """Mark open findings matched by ``entries`` as baselined; record
    stale entries (zero matches) on the result. One entry covers every
    finding with the same (rule, path, code) triple — identical
    offending lines in one file share a single entry by design."""
    stale: list[dict] = []
    for e in entries:
        key = (e["rule"], e["path"], e["code"])
        matched = False
        for f in result.findings:
            if f.status == "open" and f.key() == key:
                f.status = "baselined"
                f.reason = e["reason"]
                matched = True
        if not matched:
            stale.append(dict(e))
    result.stale_baseline = stale


def write(findings: list[Finding], path: Path,
          default_reason: str = "grandfathered at abc-lint adoption "
                                "(round 11) — review before relying on") \
        -> int:
    """Serialize ``findings`` (typically ``result.open``) as a baseline.

    Intended for the initial adoption only; the committed file's reasons
    should then be hand-edited per entry. Deduplicates by entry key."""
    seen: set[tuple[str, str, str]] = set()
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if f.key() in seen:
            continue
        seen.add(f.key())
        entries.append({"rule": f.rule, "path": f.path, "code": f.code,
                        "reason": f.reason or default_reason})
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION, "entries": entries}, indent=1) + "\n")
    return len(entries)
