"""abc-lint core: AST analysis over the repo's discipline contracts.

PRs 1-6 established hard invariants — every blocking device round trip
recorded into a :class:`~pyabc_tpu.observability.sync.SyncLedger`, every
host timestamp on the injected clock, no silently swallowed broad
exceptions, PRNG keys never consumed twice, shared mutable state touched
only under its lock. Until round 11 those were guarded by a
hand-maintained regex lint with a pinned module list, so a violation in
an unpinned module regressed silently. This engine makes the invariants
*statically checked, repo-wide*:

- a :class:`FileContext` per file: parsed AST, tokenize-accurate
  comment-stripped code lines, import-alias resolution (``import time as
  _time`` still resolves to ``time.monotonic``), and abc-lint directives;
- plugin :class:`Rule` objects produce :class:`Finding` s with
  ``file:line``, a message, and a fix hint;
- inline suppressions ``# abc-lint: disable=RULE[,RULE] <reason>`` that
  REQUIRE a reason (a reasonless suppression is itself a finding);
- contract directives ``# abc-lint: guarded-by=<lock>`` (field-level,
  consumed by LOCK001) and ``# abc-lint: holds=<lock>`` (method-level:
  the caller provides the lock);
- a committed JSON baseline for grandfathered findings (see
  :mod:`.baseline`) that may only shrink.

The engine is stdlib-only (``ast`` + ``tokenize``) so it can run at test
collection time and as a console script in any CI step.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

#: directive grammar: everything after the "abc-lint:" marker
_DIRECTIVE_RE = re.compile(r"#\s*abc-lint:\s*(?P<body>.*)$")
_DISABLE_RE = re.compile(r"^disable=(?P<rules>[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)"
                         r"(?P<reason>.*)$")
_GUARDED_RE = re.compile(r"^guarded-by=(?P<lock>[\w.]+)\s*$")
_HOLDS_RE = re.compile(r"^holds=(?P<lock>[\w.]+)\s*$")

#: engine-level meta findings
META_BAD_DIRECTIVE = "LINT001"   # malformed / reasonless directive
META_PARSE_ERROR = "LINT002"     # file failed to parse
META_MISSING_INSTRUMENTED = "LINT003"  # pinned kernel-layer file absent

#: the INSTRUMENTED set: kernel-layer modules the discipline contracts
#: were written FOR — collectives confinement (MESH001), the sync
#: ledger accounting, the dispatch single-door. A default (repo-root)
#: scan that cannot find one of these produces a finding instead of
#: silently linting a tree where the file was renamed away — a pinned
#: module must never drop out of the scan unnoticed.
INSTRUMENTED = frozenset({
    "pyabc_tpu/inference/util.py",
    "pyabc_tpu/inference/dispatch.py",
    "pyabc_tpu/inference/smc.py",
    "pyabc_tpu/ops/pack.py",
    "pyabc_tpu/ops/shard.py",
    "pyabc_tpu/ops/scale_reduce.py",
    "pyabc_tpu/ops/select.py",
    "pyabc_tpu/ops/segment.py",
    "pyabc_tpu/ops/health.py",
    # round 19: the traffic/lifecycle layer measures latency and ages
    # tenants — every timestamp must ride the injected clock (CLOCK001)
    "pyabc_tpu/traffic/specs.py",
    "pyabc_tpu/traffic/generator.py",
    "pyabc_tpu/serving/lifecycle.py",
    # round 18: the ONE sanctioned multi-process runtime module
    # (DIST001's allow-list target) must stay in the scan
    "pyabc_tpu/parallel/distributed.py",
    # round 22: the flight recorder and SLO engine timestamp every
    # entry/sample on the injected clock (CLOCK001) and recorder.py is
    # REC001's allow-list target — both must stay in the scan
    "pyabc_tpu/observability/recorder.py",
    "pyabc_tpu/observability/slo.py",
})


@dataclass
class Suppression:
    """One ``disable=`` directive, resolved to the code line it covers."""

    target_line: int
    rules: tuple[str, ...]
    reason: str
    comment_line: int
    used: bool = False


@dataclass
class Finding:
    """One rule violation (or engine meta-finding) at a source location."""

    rule: str
    path: str          # repo-relative posix path
    line: int
    col: int
    message: str
    hint: str = ""
    code: str = ""     # stripped source text of `line` (baseline identity)
    status: str = "open"   # open | suppressed | baselined
    reason: str = ""       # why suppressed / baselined

    def key(self) -> tuple[str, str, str]:
        """Line-number-free identity used for baseline matching: a
        baselined finding survives unrelated edits shifting it up or
        down, but changing the offending line itself re-opens it."""
        return (self.rule, self.path, self.code)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message, "hint": self.hint,
            "code": self.code, "status": self.status, "reason": self.reason,
        }


class FileContext:
    """Everything a rule needs to know about one source file."""

    def __init__(self, path: Path, rel: str, source: str | None = None):
        self.path = Path(path)
        self.rel = rel
        self.source = (self.path.read_text() if source is None else source)
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        #: local name -> canonical dotted module path, from every
        #: Import/ImportFrom anywhere in the file (scope-insensitive —
        #: good enough for lint, and it catches function-local imports)
        self.import_aliases = self._collect_import_aliases(self.tree)
        self.suppressions: list[Suppression] = []
        #: lineno -> lock name for `guarded-by=` field declarations
        self.guarded: dict[int, str] = {}
        #: lineno -> lock name for `holds=` method contracts
        self.holds: dict[int, str] = {}
        self.meta_findings: list[Finding] = []
        #: comment-stripped source lines (1-based access via code_line())
        self.code_lines: list[str] = list(self.lines)
        self._parse_comments()

    # ------------------------------------------------------------ helpers
    def code_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.code_lines):
            return self.code_lines[lineno - 1].strip()
        return ""

    def raw_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def dotted_name(self, node: ast.AST) -> str | None:
        """Canonical dotted path of a Name/Attribute chain with import
        aliases resolved (``_time.monotonic`` -> ``time.monotonic``,
        ``datetime.now`` after ``from datetime import datetime`` ->
        ``datetime.datetime.now``)."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.import_aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def find_suppression(self, rule: str, lineno: int) -> Suppression | None:
        for sup in self.suppressions:
            if sup.target_line == lineno and rule in sup.rules:
                sup.used = True
                return sup
        return None

    # ------------------------------------------------------- construction
    @staticmethod
    def _collect_import_aliases(tree: ast.AST) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:      # relative import: not an stdlib alias
                    continue
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def _parse_comments(self) -> None:
        comments: list[tuple[int, int, str]] = []   # (row, col, text)
        code_rows: set[int] = set()
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.start[1], tok.string))
                    # strip the comment out of the code-line view
                    row = tok.start[0] - 1
                    if 0 <= row < len(self.code_lines):
                        self.code_lines[row] = self.lines[row][: tok.start[1]]
                elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                                      tokenize.INDENT, tokenize.DEDENT,
                                      tokenize.ENDMARKER):
                    for row in range(tok.start[0], tok.end[0] + 1):
                        code_rows.add(row)
        except tokenize.TokenError:
            # fall back: treat every line as code, parse comments naively
            for i, line in enumerate(self.lines, 1):
                code_rows.add(i)
                if "#" in line:
                    col = line.index("#")
                    comments.append((i, col, line[col:]))

        for row, col, text in comments:
            m = _DIRECTIVE_RE.search(text)
            if not m:
                continue
            body = m.group("body").strip()
            target = row if row in code_rows else self._next_code_row(
                row, code_rows)
            dm = _DISABLE_RE.match(body)
            if dm:
                rules = tuple(r.strip()
                              for r in dm.group("rules").split(","))
                reason = dm.group("reason").strip()
                if not reason:
                    self.meta_findings.append(Finding(
                        rule=META_BAD_DIRECTIVE, path=self.rel, line=row,
                        col=col,
                        message=(f"suppression of {', '.join(rules)} has no "
                                 "reason — `# abc-lint: disable=RULE "
                                 "<why this site is exempt>`"),
                        hint="every suppression must say why",
                        code=self.raw_line(row),
                    ))
                    continue
                self.suppressions.append(Suppression(
                    target_line=target, rules=rules, reason=reason,
                    comment_line=row,
                ))
                continue
            gm = _GUARDED_RE.match(body)
            if gm:
                self.guarded[target] = gm.group("lock").removeprefix("self.")
                continue
            hm = _HOLDS_RE.match(body)
            if hm:
                self.holds[target] = hm.group("lock").removeprefix("self.")
                continue
            self.meta_findings.append(Finding(
                rule=META_BAD_DIRECTIVE, path=self.rel, line=row, col=col,
                message=f"unrecognized abc-lint directive: {body!r}",
                hint="known: disable=RULE <reason> | guarded-by=<lock> | "
                     "holds=<lock>",
                code=self.raw_line(row),
            ))

    @staticmethod
    def _next_code_row(row: int, code_rows: set[int]) -> int:
        later = [r for r in code_rows if r > row]
        return min(later) if later else row


class Rule:
    """Base class for abc-lint rules (subclass per rule id)."""

    name = "RULE000"
    summary = ""
    hint = ""

    def applies_to(self, rel: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> list[Finding]:
        raise NotImplementedError

    # convenience for subclasses
    def finding(self, ctx: FileContext, node: ast.AST | int, message: str,
                hint: str | None = None) -> Finding:
        if isinstance(node, int):
            line, col = node, 0
        else:
            line, col = node.lineno, getattr(node, "col_offset", 0)
        return Finding(
            rule=self.name, path=ctx.rel, line=line, col=col,
            message=message, hint=self.hint if hint is None else hint,
            code=ctx.raw_line(line),
        )


@dataclass
class AnalysisResult:
    """All findings from one run, pre- and post-suppression/baseline."""

    findings: list[Finding] = field(default_factory=list)
    #: baseline entries that matched no live finding (the baseline may
    #: only shrink: a fixed finding must leave the baseline file)
    stale_baseline: list[dict] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def open(self) -> list[Finding]:
        return [f for f in self.findings if f.status == "open"]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.status == "suppressed"]

    @property
    def baselined(self) -> list[Finding]:
        return [f for f in self.findings if f.status == "baselined"]

    def by_rule(self, status: str | None = None) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            if status is None or f.status == status:
                out[f.rule] = out.get(f.rule, 0) + 1
        return out

    @property
    def ok(self) -> bool:
        return not self.open and not self.stale_baseline


def iter_python_files(targets: list[Path]) -> list[Path]:
    out: list[Path] = []
    for t in targets:
        if t.is_dir():
            out.extend(p for p in sorted(t.rglob("*.py"))
                       if "__pycache__" not in p.parts)
        elif t.suffix == ".py":
            out.append(t)
    return out


def run_analysis(root: Path, files: list[Path], rules: list[Rule],
                 select: set[str] | None = None,
                 ignore: set[str] | None = None) -> AnalysisResult:
    """Run ``rules`` over ``files``; apply inline suppressions.

    Baseline application is a separate step (:func:`.baseline.apply`)
    so callers can decide whether a baseline participates.
    """
    result = AnalysisResult()
    active = [r for r in rules
              if (select is None or r.name in select)
              and (ignore is None or r.name not in ignore)]
    for path in files:
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        try:
            ctx = FileContext(path, rel)
        except (SyntaxError, UnicodeDecodeError) as err:
            result.findings.append(Finding(
                rule=META_PARSE_ERROR, path=rel,
                line=getattr(err, "lineno", 1) or 1, col=0,
                message=f"file failed to parse: {err}",
            ))
            continue
        result.files_scanned += 1
        # reasonless/malformed directives are findings in their own right
        # and can NOT be suppressed (a suppression can't excuse itself)
        result.findings.extend(ctx.meta_findings)
        for rule in active:
            if not rule.applies_to(rel):
                continue
            for f in rule.check(ctx):
                sup = ctx.find_suppression(f.rule, f.line)
                if sup is not None:
                    f.status = "suppressed"
                    f.reason = sup.reason
                result.findings.append(f)
    return result
