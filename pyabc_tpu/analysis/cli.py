"""``abc-lint`` console entry point.

Drops into any CI step as-is: exit 0 when the tree is clean (zero
unbaselined findings, no stale baseline entries, no reasonless
suppressions), exit 1 otherwise, exit 2 on usage errors.

    abc-lint                          # whole repo, default rules+baseline
    abc-lint pyabc_tpu/broker/        # just one subtree
    abc-lint --format json            # machine-readable
    abc-lint --select SYNC001,LOCK001 # only these rules
    abc-lint --ignore TELEM001        # all but this rule
    abc-lint --no-baseline            # pretend the baseline is empty
    abc-lint --write-baseline         # (re)grandfather current findings
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import baseline as baseline_mod
from .engine import iter_python_files, run_analysis
from .reporters import format_json, format_text
from .rules import all_rules, rule_ids

#: default scan set, relative to the repo root
DEFAULT_TARGETS = ("pyabc_tpu", "bench.py", "profile_gen.py")


def find_repo_root(start: Path | None = None) -> Path:
    """Nearest ancestor with a pyproject.toml; falls back to the package
    checkout this module lives in."""
    for cand in [start or Path.cwd(), *(start or Path.cwd()).parents]:
        if (cand / "pyproject.toml").exists():
            return cand
    return Path(__file__).resolve().parents[2]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="abc-lint",
        description="AST lint for the pyabc_tpu discipline contracts "
                    f"(rules: {', '.join(rule_ids())})")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: "
                        f"{' '.join(DEFAULT_TARGETS)} under the repo root)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", metavar="PATH", default=None,
                   help="baseline file (default: <root>/"
                        f"{baseline_mod.DEFAULT_BASELINE_NAME} if present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--select", metavar="RULES", default=None,
                   help="comma-separated rule ids to run (only these)")
    p.add_argument("--ignore", metavar="RULES", default=None,
                   help="comma-separated rule ids to skip")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current unbaselined findings to the "
                        "baseline file and exit 0 (initial adoption; "
                        "hand-edit the reasons afterwards)")
    p.add_argument("--verbose", action="store_true",
                   help="also list suppressed/baselined findings (text)")
    return p


def _parse_rule_set(spec: str | None, known: list[str],
                    parser: argparse.ArgumentParser) -> set[str] | None:
    if spec is None:
        return None
    rules = {r.strip() for r in spec.split(",") if r.strip()}
    unknown = rules - set(known)
    if unknown:
        parser.error(f"unknown rule id(s): {', '.join(sorted(unknown))} "
                     f"(known: {', '.join(known)})")
    return rules


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    root = find_repo_root()
    targets = ([Path(p) for p in args.paths] if args.paths
               else [root / t for t in DEFAULT_TARGETS])
    targets = [t for t in targets if t.exists()]
    if not targets:
        parser.error("no existing paths to scan")

    known = rule_ids()
    select = _parse_rule_set(args.select, known, parser)
    ignore = _parse_rule_set(args.ignore, known, parser)

    files = iter_python_files(targets)
    result = run_analysis(root, files, all_rules(),
                          select=select, ignore=ignore)
    if not args.paths:
        # whole-repo scan: every INSTRUMENTED kernel-layer file must be
        # present — a rename must not silently un-lint a pinned module
        from .engine import INSTRUMENTED, META_MISSING_INSTRUMENTED, Finding

        scanned = {
            p.resolve().relative_to(root.resolve()).as_posix()
            for p in files
        }
        for pinned in sorted(INSTRUMENTED - scanned):
            result.findings.append(Finding(
                rule=META_MISSING_INSTRUMENTED, path=pinned, line=1,
                col=0,
                message=(f"pinned INSTRUMENTED module {pinned} missing "
                         f"from the scan — renamed or deleted without "
                         f"updating analysis/engine.py"),
                hint="update INSTRUMENTED alongside the move",
            ))

    baseline_path = Path(args.baseline) if args.baseline else \
        root / baseline_mod.DEFAULT_BASELINE_NAME
    if args.write_baseline:
        n = baseline_mod.write(result.open, baseline_path)
        print(f"abc-lint: wrote {n} baseline entr(y/ies) to "
              f"{baseline_path} — edit the reasons before committing")
        return 0
    if not args.no_baseline and baseline_path.exists():
        try:
            entries = baseline_mod.load(baseline_path)
        except baseline_mod.BaselineError as err:
            print(f"abc-lint: {err}", file=sys.stderr)
            return 2
        baseline_mod.apply(result, entries)

    print(format_text(result, verbose=args.verbose)
          if args.format == "text" else format_json(result))
    return 0 if result.ok else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
