"""abc-lint: static enforcement of the repo's discipline contracts.

Public surface:

- :func:`run_analysis` + :func:`iter_python_files` — run rules over files
- :class:`Rule`, :class:`Finding`, :class:`FileContext`,
  :class:`AnalysisResult` — the plugin framework
- :mod:`~pyabc_tpu.analysis.baseline` — grandfathered-finding handling
- :func:`~pyabc_tpu.analysis.rules.all_rules` — the production rule set
  (SYNC001, CLOCK001, RNG001, EXC001, LOCK001, TELEM001)
- :func:`~pyabc_tpu.analysis.cli.main` — the ``abc-lint`` console script

Stdlib-only by design: importable at test collection time and in CI
without touching JAX.
"""
from . import baseline
from .cli import DEFAULT_TARGETS, find_repo_root, main
from .engine import (
    AnalysisResult,
    FileContext,
    Finding,
    Rule,
    Suppression,
    iter_python_files,
    run_analysis,
)
from .rules import RULE_CLASSES, all_rules, rule_ids

__all__ = [
    "AnalysisResult", "FileContext", "Finding", "Rule", "Suppression",
    "run_analysis", "iter_python_files", "baseline", "all_rules",
    "rule_ids", "RULE_CLASSES", "main", "find_repo_root",
    "DEFAULT_TARGETS",
]
