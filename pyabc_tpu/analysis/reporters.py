"""abc-lint output: text for humans, JSON for CI and tooling."""
from __future__ import annotations

import json

from .engine import AnalysisResult


def format_text(result: AnalysisResult, verbose: bool = False) -> str:
    lines: list[str] = []
    for f in sorted(result.open, key=lambda f: (f.path, f.line, f.rule)):
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
        if f.hint:
            lines.append(f"    hint: {f.hint}")
    for e in result.stale_baseline:
        lines.append(
            f"{e['path']}: STALE baseline entry for {e['rule']} "
            f"({e['code'][:60]!r}) no longer fires — delete it (the "
            "baseline only shrinks)")
    if verbose:
        for f in sorted(result.suppressed + result.baselined,
                        key=lambda f: (f.path, f.line)):
            lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} "
                         f"[{f.status}: {f.reason}] {f.message}")
    counts = result.by_rule("open")
    summary = ", ".join(f"{k}={v}" for k, v in sorted(counts.items())) \
        or "none"
    lines.append(
        f"abc-lint: {result.files_scanned} files, "
        f"{len(result.open)} unbaselined finding(s) [{summary}], "
        f"{len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined, "
        f"{len(result.stale_baseline)} stale baseline entr(y/ies)")
    return "\n".join(lines)


def format_json(result: AnalysisResult) -> str:
    return json.dumps({
        "files_scanned": result.files_scanned,
        "open": [f.to_dict() for f in result.open],
        "suppressed": [f.to_dict() for f in result.suppressed],
        "baselined": [f.to_dict() for f in result.baselined],
        "stale_baseline": result.stale_baseline,
        "counts": {
            "open_by_rule": result.by_rule("open"),
            "suppressed_by_rule": result.by_rule("suppressed"),
            "baselined_by_rule": result.by_rule("baselined"),
        },
        "ok": result.ok,
    }, indent=1)
