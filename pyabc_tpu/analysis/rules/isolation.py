"""ISO001 — runs exist only inside the scheduler's leased path.

The serving layer's containment story (round 14) hangs on ONE
structural fact: every ABC-SMC run in ``pyabc_tpu/serving/`` is
constructed, leased, supervised and torn down by the
:class:`RunScheduler` — that is where fault scopes are entered, run
leases granted, per-tenant namespaces bound and device slots counted.
An ``ABCSMC(...)`` (or a raw ``DispatchEngine(...)`` / ``DeviceContext
(...)``, or a device-context acquisition via ``_build_device_ctx`` /
``adopt_device_context``) anywhere else in the serving package is an
UNLEASED run: invisible to admission control, unkillable by lease
expiry, uncounted against device slots — exactly the bypass that turns
"multi-tenant with hard fault isolation" back into "several runs in one
process". This rule makes the bypass a finding.

Scope: ``pyabc_tpu/serving/`` only (the inference/bench/test layers
construct ABCSMC legitimately), with ``scheduler.py`` — the leased
path itself — exempt.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule

#: constructing any of these IS starting (or arming) a run
RUN_CONSTRUCTORS = {"ABCSMC", "DispatchEngine", "DeviceContext"}

#: calling any of these acquires a compiled device context
CONTEXT_ACQUIRERS = {"_build_device_ctx", "adopt_device_context",
                     "_adopt_device_context_inner"}

#: the scheduler's leased path — the one legitimate construction site
ALLOWED = {"pyabc_tpu/serving/scheduler.py"}


class Iso001(Rule):
    name = "ISO001"
    summary = ("run construction / device-context acquisition in the "
               "serving layer outside the scheduler's leased path")
    hint = ("only pyabc_tpu/serving/scheduler.py may construct "
            "ABCSMC/DispatchEngine/DeviceContext or acquire a device "
            "context — an unleased run bypasses admission control, run "
            "leases, fault scoping and slot accounting; route it "
            "through RunScheduler.submit()")

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("pyabc_tpu/serving/") and rel not in ALLOWED

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (func.id if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute)
                    else None)
            if name in RUN_CONSTRUCTORS:
                findings.append(self.finding(
                    ctx, node,
                    f"`{name}(...)` constructs a run outside the "
                    f"scheduler's leased path — serving-layer runs must "
                    f"be admitted, leased and supervised by RunScheduler",
                ))
            elif name in CONTEXT_ACQUIRERS:
                findings.append(self.finding(
                    ctx, node,
                    f"`{name}(...)` acquires a device context outside "
                    f"the scheduler's leased path — device slots are "
                    f"leased resources in the serving layer",
                ))
        return findings
