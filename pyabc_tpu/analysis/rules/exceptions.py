"""EXC001 — broad exception handlers must not swallow silently.

Round 10's regex lint banned single-line ``except Exception: pass``;
this AST generalization also catches the multi-line equivalents the
regex missed: a broad handler (bare ``except:``, ``except Exception``,
``except BaseException``, or a tuple containing either) whose entire
body is pass-equivalent — ``pass``, ``...``, a docstring/constant, a
bare ``return`` (or ``return None``), or ``continue``. A broad handler
must log, count, re-raise, or otherwise leave a trace; narrow handlers
(``except FileNotFoundError: pass``) stay legal because suppressing a
SPECIFIC expected condition is a statement, suppressing everything is a
hole.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node: ast.expr | None) -> bool:
    if type_node is None:
        return True
    elts = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    return any(isinstance(e, ast.Name) and e.id in _BROAD for e in elts)


def _swallows(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Return) and (
                stmt.value is None
                or (isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is None)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue        # docstring / Ellipsis
        return False
    return True


class Exc001(Rule):
    name = "EXC001"
    summary = "broad exception handler with a pass-equivalent body"
    hint = ("log, count (metrics.counter(...).inc()), re-raise, or narrow "
            "the exception type to the specific expected condition")

    def applies_to(self, rel: str) -> bool:
        return not rel.startswith("pyabc_tpu/analysis/")

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node.type) and _swallows(node.body):
                caught = ("bare except" if node.type is None
                          else ast.unparse(node.type))
                out.append(self.finding(
                    ctx, node,
                    f"broad handler ({caught}) swallows silently — its "
                    "whole body is pass-equivalent",
                ))
        return out
