"""LOCK001 — guarded-by lock discipline for the threaded seams.

The broker trio, History's async writer, the MetricsRegistry and the
resilience lease machinery are the repo's race surface (PRs 5/6). This
rule turns their locking convention into a checked contract: a field
declared

    self._results = []   # abc-lint: guarded-by=_lock

may only be touched (read OR written) inside a ``with self._lock:``
block in that class. Exemptions, matching the repo's idiom:

- the declaring method (normally ``__init__`` — construction happens
  before the object is shared);
- methods whose name ends in ``_locked`` and methods decorated with
  ``@_locked`` — the established callers-hold-the-lock conventions
  (History's decorator, the broker's suffix);
- methods carrying an explicit ``# abc-lint: holds=<lock>`` directive on
  their ``def`` line.

Conversely, CALLING a ``self.<...>_locked(...)`` helper outside the lock
is itself a finding in any class that declares guarded fields — the
suffix is a contract, not a naming accident. The check is class-internal
and lexical (aliasing the lock or the object defeats it); it is a lint,
not a proof, but it catches the realistic regression: a new method
touching shared state without taking the lock.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule


class Lock001(Rule):
    name = "LOCK001"
    summary = "guarded field touched outside its declared lock"
    hint = ("wrap the access in `with self.<lock>:`, rename the method "
            "`*_locked` / mark it `# abc-lint: holds=<lock>` if every "
            "caller already holds it")

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(ctx, node, out)
        return out

    # ------------------------------------------------------------ internals
    def _check_class(self, ctx: FileContext, cls: ast.ClassDef,
                     out: list[Finding]) -> None:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        # guarded declarations: self-attribute assignments whose line
        # carries a guarded-by directive
        guarded: dict[str, str] = {}
        declared_in: dict[str, str] = {}
        for meth in methods:
            for sub in ast.walk(meth):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                lock = ctx.guarded.get(sub.lineno)
                if lock is None:
                    continue
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        guarded[t.attr] = lock
                        declared_in[t.attr] = meth.name
        if not guarded:
            return
        locks = set(guarded.values())
        for meth in methods:
            held: set[str] = set()
            if meth.name.endswith("_locked"):
                held |= locks
            for dec in meth.decorator_list:
                if isinstance(dec, ast.Name) and dec.id.endswith("_locked"):
                    held |= locks
            holds = ctx.holds.get(meth.lineno)
            if holds:
                held.add(holds)
            self._walk(ctx, meth.body, meth, guarded, declared_in, locks,
                       held, out)

    def _walk(self, ctx: FileContext, stmts: list[ast.stmt],
              meth: ast.AST, guarded: dict[str, str],
              declared_in: dict[str, str], locks: set[str],
              held: set[str], out: list[Finding]) -> None:
        for stmt in stmts:
            self._walk_node(ctx, stmt, meth, guarded, declared_in, locks,
                            held, out)

    def _walk_node(self, ctx: FileContext, node: ast.AST, meth,
                   guarded, declared_in, locks, held: set[str],
                   out: list[Finding]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in node.items:
                try:
                    text = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover
                    text = ""
                for lock in locks:
                    if text == f"self.{lock}" or text == lock \
                            or text.endswith(f".{lock}"):
                        acquired.add(lock)
            inner = held | acquired
            for item in node.items:
                self._walk_node(ctx, item.context_expr, meth, guarded,
                                declared_in, locks, held, out)
            self._walk(ctx, node.body, meth, guarded, declared_in, locks,
                       inner, out)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not meth:
            # nested function: inherits the lexical lock context
            self._walk(ctx, node.body, meth, guarded, declared_in, locks,
                       held, out)
            return
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            attr = node.attr
            lock = guarded.get(attr)
            if lock is not None and lock not in held \
                    and meth.name != declared_in.get(attr):
                out.append(self.finding(
                    ctx, node,
                    f"`self.{attr}` is declared guarded-by={lock} but is "
                    f"touched in `{meth.name}` outside `with self.{lock}:`",
                ))
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)\
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self" \
                and node.func.attr.endswith("_locked") \
                and not (locks & held) \
                and meth.name != "__init__":
            out.append(self.finding(
                ctx, node,
                f"`self.{node.func.attr}(...)` called from `{meth.name}` "
                "without the lock its `_locked` suffix promises",
            ))
        for child in ast.iter_child_nodes(node):
            self._walk_node(ctx, child, meth, guarded, declared_in, locks,
                            held, out)
