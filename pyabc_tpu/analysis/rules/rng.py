"""RNG001 — a JAX PRNG key must not be consumed by two sampling calls.

JAX keys are pure values: passing the same key to two sampling calls
yields CORRELATED draws (often identical), the bug class the typed-key
work in ``DeviceContext._shard_lane_keys`` brushed against. The
discipline: derive (``jax.random.split`` / ``fold_in``) before every
additional consumption.

The rule runs a small order-aware dataflow over each function body:

- a *consuming* call is ``jax.random.<sampler>(key, ...)`` whose first
  positional argument is a plain name, for any sampler other than the
  derivation/constructor set (``split``, ``fold_in``, ``key``,
  ``PRNGKey``, ``key_data``, ``wrap_key_data``, ``clone``);
- any assignment to the name (``key = jax.random.fold_in(key, i)``,
  tuple unpacking from ``split``, a loop target...) resets it;
- a second consumption of the same (name, version) is a finding at the
  second site.

Control flow is handled conservatively: ``if``/``try`` branches are
walked on state copies and merged keeping the *most-consumed* state
(a consume on either path arms the check), except that a branch which
always leaves the scope (guard ``return``/``raise``) contributes
nothing to the fall-through; loop and comprehension
bodies are walked twice so a loop that consumes a key it never re-derives
is caught as cross-iteration reuse. Nested functions are fresh scopes.
Keys threaded through subscripts/attributes (``keys[i]``,
``self.key``) are out of scope — the convention is local names.
"""
from __future__ import annotations

import ast
import copy

from ..engine import FileContext, Finding, Rule

#: jax.random members that derive/construct rather than consume
NONCONSUMING = {"split", "fold_in", "key", "PRNGKey", "key_data",
                "wrap_key_data", "key_impl", "clone"}

#: state: name -> list of ast.Call nodes that consumed the current
#: "version" of the name (reset on every assignment)
_State = dict


class Rng001(Rule):
    name = "RNG001"
    summary = "PRNG key consumed twice without an intervening split/fold_in"
    hint = ("derive per-use subkeys: `k1, k2 = jax.random.split(key)` or "
            "`key = jax.random.fold_in(key, i)` before reusing")

    def applies_to(self, rel: str) -> bool:
        return not rel.startswith("pyabc_tpu/analysis/")

    def check(self, ctx: FileContext) -> list[Finding]:
        self._ctx = ctx
        self._findings: list[Finding] = []
        self._seen: set[tuple[int, int, str]] = set()
        state: _State = {}
        self._walk_stmts(ctx.tree.body, state)
        return self._findings

    # --------------------------------------------------------- statements
    def _walk_stmts(self, stmts: list[ast.stmt], state: _State) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, state)

    def _walk_stmt(self, stmt: ast.stmt, state: _State) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in stmt.decorator_list:
                self._walk_expr(d, state)
            self._walk_stmts(stmt.body, {})     # fresh scope
            return
        if isinstance(stmt, ast.ClassDef):
            self._walk_stmts(stmt.body, {})
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if getattr(stmt, "value", None) is not None:
                self._walk_expr(stmt.value, state)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                self._reset_target(t, state)
            return
        if isinstance(stmt, ast.If):
            self._walk_expr(stmt.test, state)
            a = copy.deepcopy(state)
            self._walk_stmts(stmt.body, a)
            b = copy.deepcopy(state)
            self._walk_stmts(stmt.orelse, b)
            # a branch that always leaves the scope (guard return/raise)
            # contributes nothing to the fall-through state
            branches = []
            if not self._terminates(stmt.body):
                branches.append(a)
            if not self._terminates(stmt.orelse):
                branches.append(b)
            merged = (branches[0] if len(branches) == 1
                      else self._merge(*branches) if branches
                      else copy.deepcopy(state))
            state.clear()
            state.update(merged)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._walk_expr(stmt.iter, state)
            for _pass in range(2):       # second pass = next iteration
                self._reset_target(stmt.target, state)
                self._walk_stmts(stmt.body, state)
            self._walk_stmts(stmt.orelse, state)
            return
        if isinstance(stmt, ast.While):
            for _pass in range(2):
                self._walk_expr(stmt.test, state)
                self._walk_stmts(stmt.body, state)
            self._walk_stmts(stmt.orelse, state)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._walk_expr(item.context_expr, state)
                if item.optional_vars is not None:
                    self._reset_target(item.optional_vars, state)
            self._walk_stmts(stmt.body, state)
            return
        if isinstance(stmt, ast.Try):
            self._walk_stmts(stmt.body, state)
            merged = copy.deepcopy(state)
            for handler in stmt.handlers:
                h = copy.deepcopy(state)
                if handler.name:
                    h[handler.name] = []
                self._walk_stmts(handler.body, h)
                merged = self._merge(merged, h)
            state.clear()
            state.update(merged)
            self._walk_stmts(stmt.orelse, state)
            self._walk_stmts(stmt.finalbody, state)
            return
        # default: evaluate child expressions, then apply any stores
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._walk_expr(child, state)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                state[node.id] = []

    # -------------------------------------------------------- expressions
    def _walk_expr(self, expr: ast.expr, state: _State) -> None:
        if isinstance(expr, ast.Lambda):
            return                        # deferred execution, fresh scope
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in expr.generators:
                self._walk_expr(gen.iter, state)
                self._reset_target(gen.target, state)
            for _pass in range(2):        # body may run once per element
                for gen in expr.generators:
                    for cond in gen.ifs:
                        self._walk_expr(cond, state)
                if isinstance(expr, ast.DictComp):
                    self._walk_expr(expr.key, state)
                    self._walk_expr(expr.value, state)
                else:
                    self._walk_expr(expr.elt, state)
            return
        if isinstance(expr, ast.NamedExpr):
            self._walk_expr(expr.value, state)
            self._reset_target(expr.target, state)
            return
        if isinstance(expr, ast.Call):
            for child in ast.iter_child_nodes(expr):
                if isinstance(child, ast.expr):
                    self._walk_expr(child, state)
            self._maybe_consume(expr, state)
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._walk_expr(child, state)

    def _maybe_consume(self, call: ast.Call, state: _State) -> None:
        dotted = self._ctx.dotted_name(call.func)
        if not dotted or not dotted.startswith("jax.random."):
            return
        if dotted.rsplit(".", 1)[-1] in NONCONSUMING:
            return
        if not call.args or not isinstance(call.args[0], ast.Name):
            return
        name = call.args[0].id
        uses = state.setdefault(name, [])
        if uses:
            first = uses[0]
            key = (call.lineno, call.col_offset, name)
            if key not in self._seen:
                self._seen.add(key)
                self._findings.append(self.finding(
                    self._ctx, call,
                    f"PRNG key `{name}` already consumed by a sampling "
                    f"call at line {first.lineno} — reusing it yields "
                    "correlated draws",
                ))
        uses.append(call)

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _terminates(stmts: list[ast.stmt]) -> bool:
        """True when the block always exits the enclosing scope/flow."""
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))

    def _reset_target(self, target: ast.expr, state: _State) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                state[node.id] = []

    @staticmethod
    def _merge(a: _State, b: _State) -> _State:
        out: _State = {}
        for name in set(a) | set(b):
            ua, ub = a.get(name, []), b.get(name, [])
            out[name] = ua if len(ua) >= len(ub) else ub
        return out
