"""TELEM001 — no ad-hoc telemetry containers outside observability/.

Scatter-shot timing dicts (``phase_timings`` and friends) are how a
codebase grows three clocks and four span schemas; all measurement goes
through the named span/metric instruments of
:mod:`pyabc_tpu.observability` so every datum has one schema, one clock,
one exporter. Ported from the round-1 regex lint verbatim — this rule
intentionally scans comment-stripped SOURCE LINES rather than the AST,
because generated code in string literals (the bench's subprocess
snippets) runs too and is held to the same bar.
"""
from __future__ import annotations

import re

from ..engine import FileContext, Finding, Rule

_AD_HOC = re.compile(
    r"\b(?:phase|stage|step)_timings?\b|\bspan_math\b|\btelemetry_clock\b"
)


class Telem001(Rule):
    name = "TELEM001"
    summary = "ad-hoc telemetry container outside pyabc_tpu/observability/"
    hint = ("add a named span (tracer.span(...)) or metric instrument "
            "(metrics.counter/gauge/histogram) instead of a timing dict")

    def applies_to(self, rel: str) -> bool:
        if rel.startswith(("pyabc_tpu/observability/", "pyabc_tpu/analysis/")):
            return False
        return rel.startswith("pyabc_tpu/") or rel in ("bench.py",
                                                       "profile_gen.py")

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for lineno in range(1, len(ctx.code_lines) + 1):
            if _AD_HOC.search(ctx.code_line(lineno)):
                out.append(self.finding(
                    ctx, lineno,
                    "ad-hoc telemetry container — measurement belongs to "
                    "the observability subsystem",
                ))
        return out
