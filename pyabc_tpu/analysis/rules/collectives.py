"""MESH001 — cross-device collectives live in the kernel layer only.

The sharded fused path (ISSUE 9) holds one discipline: cross-device
traffic is a property of the KERNELS, budgeted and placed deliberately —
per-generation scalar-column gathers and the chunk-boundary row merge
inside ``DeviceContext``'s programs (``pyabc_tpu/inference/util.py``)
plus the shard math in ``pyabc_tpu/ops/``. A collective anywhere else
(``psum`` in an orchestrator, a stray ``all_gather`` in a sampler, a
``shard_map`` wrapping host code) is an unbudgeted sync path: it bypasses
the SyncLedger accounting, the ``syncs_per_run <= chunks + O(1)``
invariant, and the chunk-boundary-only contract the bench ``mesh`` lane
regression-guards. This rule makes the placement structural, the same
way DISP001 pins dispatch/fetch to the engine.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule

#: the cross-device surface: invoking any of these moves data (or
#: partitions execution) across mesh devices
COLLECTIVE_CALLS = {
    "all_gather", "psum", "psum_scatter", "pmean", "pmin", "pmax",
    "ppermute", "pshuffle", "all_to_all", "axis_index", "shard_map",
}

#: where collectives are legitimate: the kernel/composition layer
#: (DeviceContext's programs) and the device-op modules under ops/
ALLOWED_PREFIXES = ("pyabc_tpu/ops/",)
ALLOWED_FILES = {"pyabc_tpu/inference/util.py"}


class Mesh001(Rule):
    name = "MESH001"
    summary = ("cross-device collective outside the kernel layer "
               "(inference/util.py + ops/)")
    hint = ("place collectives inside DeviceContext's jitted programs "
            "(pyabc_tpu/inference/util.py) or pyabc_tpu/ops/ — the "
            "sharded path's contract is scalar-column gathers per "
            "generation and ONE row merge per chunk riding the packed "
            "fetch; a collective elsewhere is an unbudgeted sync path")

    def applies_to(self, rel: str) -> bool:
        if not rel.startswith("pyabc_tpu/"):
            return False
        if rel.startswith("pyabc_tpu/analysis/"):
            return False
        if rel in ALLOWED_FILES:
            return False
        return not any(rel.startswith(p) for p in ALLOWED_PREFIXES)

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Attribute) \
                    and func.attr in COLLECTIVE_CALLS:
                name = func.attr
            elif isinstance(func, ast.Name) and func.id in COLLECTIVE_CALLS:
                name = func.id
            if name is None:
                continue
            findings.append(self.finding(
                ctx, node,
                f"`{name}(...)` is a cross-device collective outside "
                f"the kernel layer — mesh traffic belongs in "
                f"pyabc_tpu/inference/util.py or pyabc_tpu/ops/, where "
                f"the chunk-boundary-only contract is enforced",
            ))
        return findings
