"""DISP001 — chunk dispatch/fetch flows only through the dispatch engine.

Round 12 collapsed ``inference/smc.py``'s three overlapping loops
(pipelined, fused-chunk + threaded-fetch, async-drain) into the single
event-driven engine in ``pyabc_tpu/inference/dispatch.py``. The engine's
invariants — double-buffered speculation, in-order processing with stop
rollback, and the ``syncs_per_run <= chunks + O(1)`` budget — only hold
if EVERY device kernel dispatch and packed fetch goes through it. This
rule makes that structural: a direct call to one of the chunk
dispatch/fetch kernels (``multigen_kernel`` — the fused G-generation
program, ``fetch_pack_kernel`` — the compacted device->host fetch,
``round_kernel`` — a raw proposal round) anywhere in ``pyabc_tpu/``
outside the engine module (or ``inference/util.py``, where the kernels
are defined and composed) is a finding, so the three-loop pattern cannot
silently grow back.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule

#: the chunk dispatch/fetch surface: invoking any of these IS a device
#: dispatch (or the paired packed fetch) — the engine's whole job
KERNEL_CALLS = {"multigen_kernel", "fetch_pack_kernel", "round_kernel"}

#: the engine itself, and the DeviceContext module that defines/composes
#: the kernels (its internal uses are the kernels' own implementation)
ALLOWED = {
    "pyabc_tpu/inference/dispatch.py",
    "pyabc_tpu/inference/util.py",
}


class Disp001(Rule):
    name = "DISP001"
    summary = ("direct chunk-dispatch/fetch kernel call outside the "
               "dispatch engine")
    hint = ("route device dispatch/fetch through pyabc_tpu/inference/"
            "dispatch.py (DispatchEngine / dispatch_speculative_round); "
            "the engine owns speculation, stop rollback and the sync "
            "budget — a bypass re-grows the three-loop pattern")

    def applies_to(self, rel: str) -> bool:
        return (rel.startswith("pyabc_tpu/")
                and not rel.startswith("pyabc_tpu/analysis/")
                and rel not in ALLOWED)

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in KERNEL_CALLS:
                findings.append(self.finding(
                    ctx, node,
                    f"`.{func.attr}(...)` dispatches/fetches a device "
                    f"chunk outside the dispatch engine — every chunk "
                    f"round trip must flow through "
                    f"pyabc_tpu/inference/dispatch.py",
                ))
            elif isinstance(func, ast.Name) and func.id in KERNEL_CALLS:
                findings.append(self.finding(
                    ctx, node,
                    f"`{func.id}(...)` dispatches/fetches a device "
                    f"chunk outside the dispatch engine — every chunk "
                    f"round trip must flow through "
                    f"pyabc_tpu/inference/dispatch.py",
                ))
        return findings
