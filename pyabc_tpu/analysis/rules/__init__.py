"""abc-lint rule registry.

Each rule is a plugin over the engine's visitor framework; ``all_rules``
returns one fresh instance per rule class, in stable id order. To add a
rule: subclass :class:`pyabc_tpu.analysis.engine.Rule` in a module here,
give it a unique ``NAMEnnn`` id, and append it to :data:`RULE_CLASSES`
(README "Static analysis" documents the workflow).
"""
from __future__ import annotations

from .clock import Clock001
from .collectives import Mesh001
from .dispatch import Disp001
from .distributed import Dist001
from .exceptions import Exc001
from .isolation import Iso001
from .locks import Lock001
from .placement_rule import Place001
from .recorder_rule import Rec001
from .rng import Rng001
from .sync import Sync001
from .telemetry import Telem001

RULE_CLASSES = [Sync001, Clock001, Rng001, Exc001, Lock001, Telem001,
                Disp001, Mesh001, Iso001, Place001, Dist001, Rec001]


def all_rules():
    return [cls() for cls in RULE_CLASSES]


def rule_ids():
    return [cls.name for cls in RULE_CLASSES]
