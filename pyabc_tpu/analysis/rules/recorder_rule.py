"""REC001 — telemetry persistence lives in recorder.py / export.py only.

The observability package is IN-MEMORY by design: bounded rings,
instruments, span lists. Exactly two modules are allowed to turn that
state into bytes — ``observability/recorder.py`` (the crash-safe
flight-recorder file: tmp + fsync + rename, CRC-framed) and
``observability/export.py`` (Prometheus exposition text). A stray
``open(...)`` in a metrics helper, or a hand-rolled ``write_flight``
call from the serving layer, bypasses the recorder's atomicity and
bounded-ring semantics: a torn half-file on crash is exactly the
postmortem artifact the flight recorder exists to make impossible.
Mirrors DIST001 (one sanctioned module for the process runtime) for
the telemetry-persistence dimension.

Two firing modes:

- filesystem-write machinery (``open``, ``os.replace``, ``os.rename``,
  ``os.fsync``) inside ``pyabc_tpu/observability/`` but outside the
  two sanctioned files;
- a direct ``write_flight(...)`` call ANYWHERE in ``pyabc_tpu/``
  outside ``recorder.py`` — persistence goes through
  ``FlightRecorder.dump()``, which owns the payload schema.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule

#: the two sanctioned telemetry-persistence modules
ALLOWED_FILES = {
    "pyabc_tpu/observability/recorder.py",
    "pyabc_tpu/observability/export.py",
}

#: filesystem-write machinery banned inside observability/
_FS_WRITE = {"open", "io.open", "os.replace", "os.rename", "os.fsync"}


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class Rec001(Rule):
    name = "REC001"
    summary = ("telemetry file write outside observability/recorder.py "
               "and observability/export.py")
    hint = ("persist telemetry through FlightRecorder.dump() (crash-"
            "safe tmp+fsync+rename, CRC-framed) or export it through "
            "prometheus_text() — a hand-rolled open()/write_flight() "
            "elsewhere can leave a torn half-file on crash, exactly "
            "the artifact the flight recorder exists to prevent")

    def applies_to(self, rel: str) -> bool:
        if not rel.startswith("pyabc_tpu/"):
            return False
        if rel.startswith("pyabc_tpu/analysis/"):
            return False
        return rel not in ALLOWED_FILES

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        in_obs = ctx.rel.startswith("pyabc_tpu/observability/")
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            if dotted == "write_flight" or dotted.endswith(".write_flight"):
                findings.append(self.finding(
                    ctx, node,
                    f"`{dotted}(...)` persists a flight payload outside "
                    f"pyabc_tpu/observability/recorder.py — telemetry "
                    f"persistence goes through FlightRecorder.dump(), "
                    f"which owns the payload schema and the crash-safe "
                    f"write path",
                ))
            elif in_obs and dotted in _FS_WRITE:
                findings.append(self.finding(
                    ctx, node,
                    f"`{dotted}(...)` writes files inside the in-memory "
                    f"observability package — only recorder.py (flight "
                    f"files) and export.py (exposition text) may turn "
                    f"telemetry state into bytes",
                ))
        return findings
