"""PLACE001 — topology decisions live only in the placement module.

Mesh-aware serving (round 15) hangs on ONE structural fact: every
device-topology decision in ``pyabc_tpu/serving/`` — which devices
exist, which are healthy, which contiguous range a tenant runs on, the
``jax.sharding.Mesh`` a lease maps to — flows through
``serving/placement.py``'s :class:`SubMeshAllocator` and its sanctioned
``build_mesh`` / ``platform_device_count`` wrappers. A ``Mesh(...)``
construction or a ``jax.devices()`` enumeration anywhere else in the
serving package is an UNTRACKED placement: devices used without a
lease, invisible to the buddy allocator's books, immune to device-loss
quarantine and degraded cordons — exactly the bypass that turns "zero
leaked/overlapping device ranges" back into hope. This rule makes the
bypass a finding (the placement twin of ISO001's unleased-run rule).

Scope: ``pyabc_tpu/serving/`` only (inference/ops/bench/test layers
construct meshes legitimately), with ``placement.py`` — the sanctioned
topology site — exempt.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule

#: constructing any of these IS building a device mesh
MESH_CONSTRUCTORS = {"Mesh", "local_mesh", "global_mesh", "make_mesh"}

#: calling any of these enumerates the device topology
DEVICE_ENUMERATORS = {"devices", "local_devices", "device_count",
                      "local_device_count"}

#: the sanctioned topology module — the one legitimate site
ALLOWED = {"pyabc_tpu/serving/placement.py"}


class Place001(Rule):
    name = "PLACE001"
    summary = ("Mesh construction / device enumeration in the serving "
               "layer outside the placement module")
    hint = ("only pyabc_tpu/serving/placement.py may construct a Mesh "
            "or enumerate devices — device ranges are LEASED resources "
            "tracked by the SubMeshAllocator (loss quarantine, degraded "
            "cordons, coalescing); route topology through "
            "placement.build_mesh()/platform_device_count() or an "
            "allocator lease")

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("pyabc_tpu/serving/") and rel not in ALLOWED

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (func.id if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute)
                    else None)
            if name in MESH_CONSTRUCTORS:
                findings.append(self.finding(
                    ctx, node,
                    f"`{name}(...)` constructs a device mesh outside "
                    f"the placement module — sub-meshes are leased "
                    f"resources; an untracked Mesh bypasses the "
                    f"allocator's books, device-loss quarantine and "
                    f"degraded cordons",
                ))
            elif name in DEVICE_ENUMERATORS:
                findings.append(self.finding(
                    ctx, node,
                    f"`{name}(...)` enumerates devices outside the "
                    f"placement module — topology is placement.py's "
                    f"job; ad-hoc enumeration drifts from the "
                    f"allocator's healthy/lost/degraded view",
                ))
        return findings
