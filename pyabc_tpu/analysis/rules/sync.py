"""SYNC001 — blocking device transfers must record into a SyncLedger.

The round-5 measurement that justifies the whole dual-basis accounting
is ``residual gap ~= n_syncs x ~102 ms tunnel floor`` — which is only an
*attribution* (not an assumption) if ``syncs_per_run`` is COMPLETE.
PR 2 wired :class:`~pyabc_tpu.observability.sync.SyncLedger` recording
into every known blocking call site by hand; this rule makes the
completeness static: a blocking transfer in a scope with no ledger
recording is a finding.

Detection:

- always-blocking APIs: ``jax.device_get(...)``,
  ``jax.block_until_ready(...)``, ``<x>.block_until_ready()``,
  ``jax.debug.callback(...)`` (host callback = device round trip);
- host materialization of device-marked values: ``np.asarray(x)`` /
  ``np.array(x)`` / ``float(x)`` / ``x.item()`` where the argument's
  source text names a device value (contains ``device`` or a ``_dev``
  suffix — the repo's naming convention for device-resident handles).
  Materializing host arrays stays legal.

Ledger evidence is scoped to the nearest enclosing function: some call
whose form is ``<...ledger...>.record(...)`` (``self.sync_ledger.record``,
``ledger.record``, ...). Evidence in an OUTER function does not excuse a
nested closure — thread targets and executor callables fetch on their
own and must record on their own. Passing ``jax.device_get`` uncalled
(e.g. ``executor.submit(jax.device_get, tree)``) is not flagged; the
submitting scope is expected to record, and the fetch-thread sites in
``inference/smc.py`` do.
"""
from __future__ import annotations

import ast
import re

from ..engine import FileContext, Finding, Rule

#: canonical dotted calls that always block on the device
BLOCKING_CALLS = {"jax.device_get", "jax.block_until_ready",
                  "jax.debug.callback"}
#: materializers that block only when fed a device value
MATERIALIZERS = {"numpy.asarray", "numpy.array"}

_DEV_MARK = re.compile(r"_dev\b|device", re.IGNORECASE)


def _device_marked(node: ast.AST) -> bool:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse of exotic nodes
        return False
    return bool(_DEV_MARK.search(text))


class Sync001(Rule):
    name = "SYNC001"
    summary = "blocking device transfer with no SyncLedger recording in scope"
    hint = ("record the round trip (`<...>.sync_ledger.record(kind, "
            "nbytes)`) in the same function, or suppress with a reason if "
            "the site is outside run orchestration")

    def applies_to(self, rel: str) -> bool:
        return not rel.startswith("pyabc_tpu/analysis/")

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        self._scan_scope(ctx, ctx.tree.body, findings, scope_name="<module>")
        return findings

    # ------------------------------------------------------------ internals
    def _scan_scope(self, ctx: FileContext, body: list[ast.stmt],
                    findings: list[Finding], scope_name: str) -> None:
        """One function (or module) scope: collect this scope's blocking
        calls and ledger evidence, recursing into nested scopes."""
        blocking: list[tuple[ast.AST, str]] = []
        has_ledger = False

        def visit(node: ast.AST) -> None:
            nonlocal has_ledger
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_scope(ctx, node.body, findings, node.name)
                for d in node.decorator_list:
                    visit(d)
                return
            if isinstance(node, ast.ClassDef):
                self._scan_scope(ctx, node.body, findings, node.name)
                return
            if isinstance(node, ast.Call):
                kind = self._blocking_kind(ctx, node)
                if kind:
                    blocking.append((node, kind))
                if self._is_ledger_record(node):
                    has_ledger = True
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in body:
            visit(stmt)

        if blocking and not has_ledger:
            for node, kind in blocking:
                findings.append(self.finding(
                    ctx, node,
                    f"{kind} blocks on the device inside `{scope_name}` "
                    "which never records into a SyncLedger — the sync "
                    "accounting (syncs_per_run) is incomplete here",
                ))

    @staticmethod
    def _is_ledger_record(call: ast.Call) -> bool:
        func = call.func
        if not (isinstance(func, ast.Attribute) and func.attr == "record"):
            return False
        try:
            receiver = ast.unparse(func.value)
        except Exception:  # pragma: no cover
            return False
        return "ledger" in receiver.lower()

    def _blocking_kind(self, ctx: FileContext, call: ast.Call) -> str | None:
        dotted = ctx.dotted_name(call.func)
        if dotted in BLOCKING_CALLS:
            return f"`{dotted}(...)`"
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr == "block_until_ready":
                return "`.block_until_ready()`"
            if (func.attr == "item" and not call.args
                    and _device_marked(func.value)):
                return "`.item()` on a device-marked value"
        if dotted in MATERIALIZERS and call.args \
                and _device_marked(call.args[0]):
            return f"`{dotted}()` on a device-marked value"
        if (isinstance(func, ast.Name) and func.id == "float"
                and len(call.args) == 1 and _device_marked(call.args[0])):
            return "`float()` on a device-marked value"
        return None
