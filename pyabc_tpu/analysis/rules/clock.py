"""CLOCK001 — raw wall-clock reads are banned; use the injected clock.

Every host-side timestamp in the package rides ONE injected clock
(:mod:`pyabc_tpu.observability.clock`): spans and deadlines survive
wall-clock steps, worker clock-offset calibration stays meaningful, and
tests can drive a VirtualClock. Until round 11 this held only for a
pinned allowlist of instrumented modules; the allowlist now INVERTS —
the ban is repo-wide and the legal raw reads (the SystemClock
implementation itself) carry explicit per-site suppressions.

``time.sleep`` stays legal (a delay, not a measurement), as do
``datetime`` *constructors* and parsing — only reads of "now" are
clock sources.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule

#: canonical dotted call paths that read a clock
BANNED = {
    "time.time", "time.time_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.monotonic", "time.monotonic_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}


class Clock001(Rule):
    name = "CLOCK001"
    summary = "raw wall-clock read outside the injected-clock discipline"
    hint = ("route through pyabc_tpu.observability (SYSTEM_CLOCK or the "
            "component's injected clock): .now() for durations/deadlines, "
            ".wall() for civil timestamps")

    def applies_to(self, rel: str) -> bool:
        # repo-wide over the package + the bench harness; profile_gen.py
        # (offline single-process profiling of its own wall clock) and
        # the analysis engine itself (names the banned calls as data)
        # are out of scope
        if rel.startswith("pyabc_tpu/analysis/"):
            return False
        return rel.startswith("pyabc_tpu/") or rel == "bench.py"

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = ctx.dotted_name(node.func)
            if dotted in BANNED:
                out.append(self.finding(
                    ctx, node,
                    f"raw clock read `{dotted}()` — host time must come "
                    "from the injected clock",
                ))
        return out
