"""DIST001 — the multi-process runtime is touched in ONE module only.

The multi-host contract (round 18) is that every process runs the SAME
program and stays in lock-step through the kernels' collectives; the
only host-side API that may observe or change the process topology is
``pyabc_tpu/parallel/distributed.py`` (``initialize``, ``is_primary``,
``primary_db``/``resume_db``, ``barrier``). A ``jax.process_index()``
probe in the SMC loop, a stray ``jax.distributed.initialize`` in a
test helper, or a ``multihost_utils`` barrier in the serving layer is
a divergence hazard: it forks host-side control flow per process (the
exact class of bug the replicated-deterministic adaptation contract
exists to prevent) and bypasses the one place where topology config is
validated (idempotence + partial-env guards). Mirrors MESH001 (mesh
traffic lives in the kernel layer) and PLACE001 (device enumeration
lives in placement) for the process dimension.

Note: ``Device.process_index`` ATTRIBUTE reads (the mesh gate in
``smc.py``/``util.py``) are fine — they inspect a mesh object, not the
runtime; this rule fires on CALLS into the distributed runtime.
"""
from __future__ import annotations

import ast

from ..engine import FileContext, Finding, Rule

#: the one sanctioned module
ALLOWED_FILES = {"pyabc_tpu/parallel/distributed.py"}


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class Dist001(Rule):
    name = "DIST001"
    summary = ("multi-process runtime call outside "
               "pyabc_tpu/parallel/distributed.py")
    hint = ("route process-topology access through "
            "pyabc_tpu/parallel/distributed.py (initialize/is_primary/"
            "process_count/primary_db/resume_db/barrier) — a direct "
            "jax.distributed / jax.process_index / multihost_utils call "
            "elsewhere forks host-side control flow per process and "
            "bypasses the module's config validation")

    def applies_to(self, rel: str) -> bool:
        if not rel.startswith("pyabc_tpu/"):
            return False
        if rel.startswith("pyabc_tpu/analysis/"):
            return False
        return rel not in ALLOWED_FILES

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None or not (
                    "jax.distributed" in dotted
                    or "multihost_utils" in dotted
                    or dotted.endswith("jax.process_index")
                    or dotted.endswith("jax.process_count")):
                continue
            name = dotted
            findings.append(self.finding(
                ctx, node,
                f"`{name}(...)` touches the multi-process runtime "
                f"outside pyabc_tpu/parallel/distributed.py — topology "
                f"access routes through that module's validated helpers "
                f"(is_primary/process_count/primary_db/resume_db/"
                f"barrier)",
            ))
        return findings
