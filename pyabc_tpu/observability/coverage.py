"""The coverage accountant: how much wall clock do the spans explain?

Round 5's verdict found ~60% of the steady benchmark span sat outside
every measured span — "dark time" nobody could attribute to fetches,
broker round trips or the DB writer. This module turns that gap into a
reported, regression-tested number: given a trace, it computes the
fraction of a wall-clock window covered by at least one span, overall
and per thread.

Also home to the window-throughput math ``bench.py`` used to hand-roll
(:func:`window_throughput`): the strict global-completion-clock basis —
cut a span into fixed wall windows, count events per window — now lives
here with unit tests, and the bench calls it instead of reimplementing
it. Semantics are identical to the round-5 bench.
"""
from __future__ import annotations


def _as_interval(sp) -> tuple[float, float, str] | None:
    """(start, end, thread) from a Span or a span dict; None if open."""
    if isinstance(sp, dict):
        start, end = sp.get("start"), sp.get("end")
        thread = sp.get("thread", "")
    else:
        start, end = sp.start, sp.end
        thread = sp.thread
    if start is None or end is None or end < start:
        return None
    return (float(start), float(end), str(thread))


def interval_union(intervals) -> float:
    """Total length of the union of (start, end) intervals."""
    ivs = sorted((float(a), float(b)) for a, b in intervals if b > a)
    total = 0.0
    cur_a = cur_b = None
    for a, b in ivs:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        elif b > cur_b:
            cur_b = b
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def coverage_report(spans, t0: float | None = None,
                    t1: float | None = None,
                    exclude_names=()) -> dict:
    """Attributed-wall-clock accounting over ``[t0, t1]``.

    ``spans``: finished :class:`~pyabc_tpu.observability.tracer.Span`
    objects or their ``to_dict()`` forms (e.g. parsed back from a JSONL
    trace). The window defaults to the trace's own extent. Span parts
    outside the window are clipped.

    ``exclude_names``: span names to IGNORE — pass enclosing root spans
    (e.g. ``("run",)``) when asking "how much wall clock do the WORK
    spans explain?": a root that blankets the whole window would
    otherwise report 100% attribution and hide every gap.

    Returns::

        {"t0", "t1", "window_s",
         "attributed_s",        # union over ALL spans, any thread
         "attributed_frac",     # attributed_s / window_s
         "dark_s",              # window_s - attributed_s (the gap)
         "per_thread": {thread: {"attributed_s", "attributed_frac"}},
         "n_spans"}
    """
    if exclude_names:
        excl = set(exclude_names)
        spans = [sp for sp in spans
                 if (sp.get("name") if isinstance(sp, dict)
                     else sp.name) not in excl]
    ivs = [iv for iv in (_as_interval(sp) for sp in spans) if iv is not None]
    if not ivs:
        return {"t0": t0, "t1": t1, "window_s": 0.0, "attributed_s": 0.0,
                "attributed_frac": 0.0, "dark_s": 0.0, "per_thread": {},
                "n_spans": 0}
    lo = min(a for a, _b, _t in ivs) if t0 is None else float(t0)
    hi = max(b for _a, b, _t in ivs) if t1 is None else float(t1)
    window = max(hi - lo, 0.0)
    clipped = [(max(a, lo), min(b, hi), t) for a, b, t in ivs
               if min(b, hi) > max(a, lo)]
    attributed = interval_union((a, b) for a, b, _t in clipped)
    by_thread: dict[str, list] = {}
    for a, b, t in clipped:
        by_thread.setdefault(t, []).append((a, b))
    per_thread = {
        t: {
            "attributed_s": round(interval_union(iv), 6),
            "attributed_frac": round(
                interval_union(iv) / window, 6) if window > 0 else 0.0,
        }
        for t, iv in sorted(by_thread.items())
    }
    return {
        "t0": lo, "t1": hi, "window_s": round(window, 6),
        "attributed_s": round(attributed, 6),
        "attributed_frac": round(attributed / window, 6)
        if window > 0 else 0.0,
        "dark_s": round(window - attributed, 6),
        "per_thread": per_thread,
        "n_spans": len(ivs),
    }


def interval_intersection(ivs_a, ivs_b) -> float:
    """Total length of the intersection of two interval sets (each an
    iterable of (start, end)). Used to decompose a span set against the
    device-busy pseudo-thread: e.g. fetch-wait seconds that overlap
    device compute vs the exposed tunnel wait."""
    a = sorted((float(x), float(y)) for x, y in ivs_a if y > x)
    b = sorted((float(x), float(y)) for x, y in ivs_b if y > x)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def device_busy_spans(probe_events, thread: str = "device") -> list[dict]:
    """Derive a measured DEVICE-BUSY span stream from consecutive
    ``compute_probe`` completions (ROADMAP "device-busy correlation"
    item): ``probe_events`` is the orchestrator's list of
    ``(dispatch_return_ts, device_done_ts)`` pairs, one per fused chunk,
    on the tracer's clock. The device executes chunks in dispatch order,
    so chunk k's compute occupies ``[max(done_{k-1}, dispatch_k),
    done_k]`` — an UPPER bound (each probe itself pays one pipelined
    tunnel round trip, so short chunks read as floor-length).

    Returns span DICTS (the ``Span.to_dict`` shape) on a pseudo-thread,
    ready to append to a trace before :func:`coverage_report` — the
    accountant then separates "device computing" from "host waiting on
    tunnel" inside chunk-fetch waits via ``per_thread`` and
    :func:`interval_intersection`.
    """
    spans = []
    prev_done = None
    for disp, done in sorted(probe_events, key=lambda p: p[1]):
        start = disp if prev_done is None else max(prev_done, disp)
        if done > start:
            spans.append({
                "name": "device.busy", "span_id": None, "parent_id": None,
                "thread": thread, "start": float(start),
                "end": float(done), "attrs": {"derived": "compute_probe"},
            })
        prev_done = done
    return spans


#: worker/orchestrator span names -> elastic dark-time category. The
#: categories mirror the fused path's gap_attribution: where the fused
#: decomposition splits dark time into device-busy vs tunnel floor, the
#: elastic one splits it into what the WORKERS were doing (their spans
#: arrive offset-mapped onto the orchestrator timeline) plus the
#: orchestrator's own polling exposure.
ELASTIC_CATEGORIES = {
    "worker.simulate": "worker_compute",
    "worker.deserialize": "serialization",
    "worker.serialize": "serialization",
    "worker.slots": "broker_rtt",
    "worker.ship": "broker_rtt",
    "worker.connect": "queue_wait",
    "worker.wait": "queue_wait",
    "broker.poll_latency": "orchestrator_poll",
    # round 9 (resilience): time work sat orphaned between a dead
    # worker's lease expiring and a live worker picking it back up, plus
    # the other recovery actions — the recovery-time slice of dark time
    "recovery.redispatch": "recovery",
    "recovery.timeout_extended": "recovery",
    "recovery.persist_retry": "recovery",
    "recovery.device_reset": "recovery",
    # round 10 (numerical health): detection->redispatch windows of the
    # RunSupervisor's recovery actions, recorded on the `health`
    # pseudo-thread by resilience/health.py
    "health.rollback": "recovery",
    "health.refit": "recovery",
    "health.widen": "recovery",
}


def elastic_gap_attribution(spans, t0: float | None = None,
                            t1: float | None = None) -> dict:
    """Decompose an elastic-path window into worker compute /
    serialization / broker RTT / queue wait / orchestrator poll.

    ``spans``: a merged trace — orchestrator spans plus worker spans
    already offset-mapped onto the orchestrator clock (``Span`` objects
    or dicts). Category seconds are interval UNIONS within the category
    clipped to ``[t0, t1]``: two workers simulating concurrently count
    the covered wall clock once, like the coverage accountant's
    per-thread math. Categories overlap each other (worker A can
    simulate while worker B waits), so the fractions need not sum to 1;
    ``attributed_frac`` is the union over every span (the elastic
    analog of ``steady_attributed_frac``).
    """
    ivs_by_cat: dict[str, list] = {}
    all_ivs: list[tuple[float, float]] = []
    named = []
    for sp in spans:
        name = sp.get("name") if isinstance(sp, dict) else sp.name
        iv = _as_interval(sp)
        if iv is None:
            continue
        named.append((name, iv))
        all_ivs.append((iv[0], iv[1]))
    if not all_ivs:
        return {"window_s": 0.0, "attributed_frac": 0.0, "dark_s": 0.0,
                "categories": {}, "n_spans": 0}
    lo = min(a for a, _b in all_ivs) if t0 is None else float(t0)
    hi = max(b for _a, b in all_ivs) if t1 is None else float(t1)
    window = max(hi - lo, 0.0)
    for name, (a, b, _thread) in named:
        cat = ELASTIC_CATEGORIES.get(name)
        if cat is None:
            continue
        a2, b2 = max(a, lo), min(b, hi)
        if b2 > a2:
            ivs_by_cat.setdefault(cat, []).append((a2, b2))
    clipped_all = [(max(a, lo), min(b, hi)) for a, b in all_ivs
                   if min(b, hi) > max(a, lo)]
    attributed = interval_union(clipped_all)
    categories = {}
    for cat in ("worker_compute", "serialization", "broker_rtt",
                "queue_wait", "orchestrator_poll", "recovery"):
        sec = interval_union(ivs_by_cat.get(cat, []))
        categories[cat] = {
            "s": round(sec, 6),
            "frac": round(sec / window, 6) if window > 0 else 0.0,
        }
    return {
        "t0": lo, "t1": hi, "window_s": round(window, 6),
        "attributed_s": round(attributed, 6),
        "attributed_frac": round(attributed / window, 6)
        if window > 0 else 0.0,
        "dark_s": round(window - attributed, 6),
        "categories": categories,
        "n_spans": len(named),
    }


def window_throughput(events, t0: float, t_end: float,
                      window_s: float) -> dict:
    """Strict global-completion-clock throughput over ``[t0, t_end]``.

    ``events``: iterables of ``(ts, count)`` — completion timestamp and
    the number of items (accepted particles) completing then. The span
    is cut into fixed ``window_s`` wall windows; every second of the
    span lands in exactly one window, so setup, fills, stalls and
    drains all average into the windows they actually occupied — the
    round-5 bench's dual-basis "wall_clock" semantics, verbatim.

    Returns ``{"per_window": [counts/s], "aggregate_per_s", "n_windows",
    "window_s", "span_s", "n_items"}`` (empty per_window when the span
    is shorter than one window would require; n_windows is always >= 1).
    """
    n_win = max(1, int((t_end - t0) // window_s))
    span = n_win * window_s
    counts = [0] * n_win
    n_items = 0
    for ts, cnt in events:
        if t0 < ts <= t0 + span:
            k = min(int((ts - t0) / window_s), n_win - 1)
            counts[k] += cnt
            n_items += cnt
    return {
        "per_window": [c / window_s for c in counts],
        "aggregate_per_s": n_items / max(span, 1e-9),
        "n_windows": n_win,
        "window_s": window_s,
        "span_s": span,
        "n_items": n_items,
    }
