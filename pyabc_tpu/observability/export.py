"""Exporters: JSONL trace files and Prometheus text exposition.

JSONL is the durable trace format (one span per line, written at span
end): it survives crashes mid-run, streams without buffering a whole
trace in memory, and round-trips through :func:`read_trace` into the
coverage accountant. The Prometheus dump is the scrape-friendly view of
a :class:`~pyabc_tpu.observability.metrics.MetricsRegistry`.
"""
from __future__ import annotations

import json
import threading

from .metrics import Counter, Gauge, Histogram


class JsonlTraceExporter:
    """Append spans to ``path`` as JSON lines; thread-safe.

    Opened lazily on the first span so merely CONSTRUCTING a tracer
    config never creates files. ``close()`` is optional (the handle
    flushes per line; an abandoned exporter leaks one fd at worst).
    """

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self._fh = None

    def export(self, span) -> None:
        line = json.dumps(span.to_dict() if hasattr(span, "to_dict")
                          else dict(span))
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "a")
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_trace(path: str) -> list[dict]:
    """Parse a JSONL trace back into span dicts (coverage-accountant
    ready). Tolerates a truncated final line (crash mid-write)."""
    spans: list[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                spans.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return spans


def worker_trace_spans(spans) -> list[dict]:
    """The worker-side subset of a merged trace: spans accounted on a
    ``worker:<id>`` pseudo-thread (the broker's offset-mapped merge) or
    named ``worker.*``/``broker.poll_latency``. Accepts Span objects or
    dicts; returns dicts, coverage-accountant ready."""
    out = []
    for sp in spans:
        d = sp.to_dict() if hasattr(sp, "to_dict") else dict(sp)
        if (str(d.get("thread", "")).startswith("worker:")
                or str(d.get("name", "")).startswith("worker.")
                or d.get("name") == "broker.poll_latency"):
            out.append(d)
    return out


def write_trace(path: str, spans) -> int:
    """Bulk-dump spans (objects or dicts) to a JSONL trace file; returns
    the span count. Complements the streaming :class:`JsonlTraceExporter`
    for after-the-fact exports (e.g. the bench's per-run worker trace)."""
    n = 0
    with open(path, "a") as fh:
        for sp in spans:
            fh.write(json.dumps(
                sp.to_dict() if hasattr(sp, "to_dict") else dict(sp)
            ) + "\n")
            n += 1
    return n


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


def _prom_labels(labels: dict | None, extra: str = "") -> str:
    """Render a label set (plus pre-formatted ``extra`` pairs like the
    histogram ``le``) as ``{k="v",...}`` — empty string for none."""
    parts = []
    for k, v in (labels or {}).items():
        val = str(v).replace("\\", "\\\\").replace('"', '\\"')
        parts.append(_prom_name(str(k)) + '="' + val + '"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry, labels: dict | None = None) -> str:
    """Prometheus text-format dump of every instrument in ``registry``.

    Histograms render cumulative ``_bucket`` series plus ``_count`` /
    ``_sum``, counters get a ``_total`` suffix, gauges render as-is.

    ``labels`` attaches a constant label set to every rendered series —
    the multi-tenant serving layer renders each tenant's private
    registry with ``labels={"tenant": name}`` so one scrape carries
    every run's series WITHOUT collisions (pre-round-14 the exporter
    assumed one run per process and concurrent runs overwrote each
    other's gauges).
    """
    lines: list[str] = []
    lab = _prom_labels(labels)
    for inst in registry.instruments():
        name = _prom_name(inst.name)
        if isinstance(inst, Counter):
            # the Prometheus counter convention is ONE trailing `_total`:
            # instruments already named `*_total` (the resilience/health
            # families) must not render doubled as `*_total_total`
            if not name.endswith("_total"):
                name = f"{name}_total"
            if inst.help:
                lines.append(f"# HELP {name} {inst.help}")
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name}{lab} {inst.value:g}")
        elif isinstance(inst, Gauge):
            if inst.help:
                lines.append(f"# HELP {name} {inst.help}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{lab} {inst.value:g}")
        elif isinstance(inst, Histogram):
            if inst.help:
                lines.append(f"# HELP {name} {inst.help}")
            lines.append(f"# TYPE {name} histogram")
            snap = inst.snapshot()
            cum = 0
            buckets = snap["buckets"]
            count, total = snap["count"], snap["sum"]
            for edge, n in zip(inst.bucket_bounds(), buckets[:-1]):
                cum += n
                le = 'le="%g"' % edge
                lines.append(
                    f"{name}_bucket{_prom_labels(labels, le)} {cum}")
            le_inf = 'le="+Inf"'
            lines.append(
                f"{name}_bucket{_prom_labels(labels, le_inf)} {count}")
            lines.append(f"{name}_count{lab} {count}")
            lines.append(f"{name}_sum{lab} {total:g}")
    return "\n".join(lines) + ("\n" if lines else "")
