"""Nested, thread-safe tracing spans over one injected clock.

The unit is the :class:`Span`: a named ``[start, end]`` interval on the
tracer's clock, carrying key-value attributes (generation index, chunk
size, n_accepted, ...), an explicit parent link, and the name of the
thread that ran it. Spans nest per thread via a contextmanager API::

    with tracer.span("generation", t=3, n=1000) as sp:
        with tracer.span("sample"):
            ...
        sp.set(n_evaluations=n_eval)

Design rules (the whole subsystem follows them):

- **dependency-free**: stdlib only — importable from worker processes,
  the bench, and tests without dragging jax/pandas along;
- **host-side only**: spans wrap host boundaries (dispatch, fetch,
  persist, adapt); nothing here may touch traced/compiled device code,
  so fused kernels stay byte-identical with tracing on or off;
- **no-op-cheap when disabled**: :data:`NULL_TRACER` (the default
  everywhere) allocates nothing per span — instrumentation can stay in
  hot paths unconditionally.

Thread safety: the parent stack is thread-local; finished spans append
to one lock-guarded list (and stream to an exporter if configured), so
concurrent fetch threads, the async DB writer and the drain thread can
all record spans into the same tracer.
"""
from __future__ import annotations

import itertools
import threading

from .clock import Clock, SYSTEM_CLOCK


class Span:
    """One named interval on the tracer's clock; ``attrs`` is open."""

    __slots__ = ("name", "span_id", "parent_id", "thread", "start", "end",
                 "attrs")

    def __init__(self, name: str, span_id: int, parent_id: int | None,
                 thread: str, start: float, attrs: dict):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.thread = thread
        self.start = start
        self.end: float | None = None
        self.attrs = attrs

    def set(self, **attrs) -> "Span":
        """Attach/overwrite attributes while the span is open."""
        self.attrs.update(attrs)
        return self

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else self.start) - self.start

    def to_dict(self) -> dict:
        return {
            "name": self.name, "span_id": self.span_id,
            "parent_id": self.parent_id, "thread": self.thread,
            "start": self.start, "end": self.end, "attrs": dict(self.attrs),
        }


class _SpanContext:
    """The contextmanager handed out by :meth:`Tracer.span`.

    A dedicated class instead of ``@contextmanager``: entering a
    generator-based contextmanager costs ~3x more, and span() sits on
    per-chunk/per-generation paths.
    """

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self._span.attrs.setdefault("error", repr(exc)[:200])
        self._tracer._finish(self._span)
        return False


class Tracer:
    """Collects finished spans; bounded memory; optional streaming export.

    ``exporter``: an object with ``export(span)`` (e.g.
    :class:`~pyabc_tpu.observability.export.JsonlTraceExporter`) called
    at each span end, on the ending thread. ``max_spans`` bounds the
    in-memory buffer — beyond it the OLDEST spans are dropped (counted
    in ``n_dropped``; a streaming exporter still saw them all).
    """

    enabled = True

    def __init__(self, clock: Clock | None = None, exporter=None,
                 max_spans: int = 200_000):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self._exporter = exporter
        self._max_spans = int(max_spans)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._finished: list[Span] = []
        self._ids = itertools.count(1)
        self.n_dropped = 0

    # ------------------------------------------------------------------ api
    def span(self, name: str, **attrs) -> _SpanContext:
        """Open a span as a context manager; nests under the thread's
        current open span."""
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent_id = stack[-1].span_id if stack else None
        sp = Span(name, next(self._ids), parent_id,
                  threading.current_thread().name, self.clock.now(), attrs)
        stack.append(sp)
        return _SpanContext(self, sp)

    def current_span(self) -> Span | None:
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    def record_span(self, name: str, start: float, end: float, *,
                    thread: str | None = None, **attrs) -> Span:
        """Record an externally-timed, already-finished span.

        The merge seam for measurements taken OUTSIDE this process on a
        foreign clock (elastic worker phases, device-busy streams): the
        caller maps the interval onto this tracer's timebase first (e.g.
        via the broker's per-worker clock-offset estimate) and hands over
        plain ``[start, end]`` floats. ``thread`` names the pseudo-thread
        the span is accounted under in :func:`~pyabc_tpu.observability.
        coverage.coverage_report` (e.g. ``worker:<id>``); it defaults to
        the calling thread. The span does not touch the per-thread
        nesting stack — it never becomes anyone's parent."""
        sp = Span(name, next(self._ids), None,
                  thread if thread is not None
                  else threading.current_thread().name,
                  float(start), attrs)
        sp.end = float(end)
        self._store(sp)
        return sp

    def spans(self) -> list[Span]:
        """Snapshot of finished spans (chronological by end time)."""
        with self._lock:
            return list(self._finished)

    def snapshot(self) -> dict:
        """In-process summary the dashboard / bench read without touching
        span objects: per-name counts and total seconds."""
        with self._lock:
            per_name: dict[str, dict] = {}
            for sp in self._finished:
                agg = per_name.setdefault(
                    sp.name, {"count": 0, "total_s": 0.0})
                agg["count"] += 1
                agg["total_s"] += sp.duration
            for agg in per_name.values():
                agg["total_s"] = round(agg["total_s"], 6)
            return {
                "n_spans": len(self._finished),
                "n_dropped": self.n_dropped,
                "spans_by_name": per_name,
            }

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            self.n_dropped = 0

    # ------------------------------------------------------------ internals
    def _finish(self, sp: Span) -> None:
        sp.end = self.clock.now()
        stack = getattr(self._local, "stack", None)
        # unwind to (and including) sp — tolerant of a caller leaking an
        # inner contextmanager across threads or exiting out of order
        if stack:
            while stack:
                top = stack.pop()
                if top is sp:
                    break
        self._store(sp)

    def _store(self, sp: Span) -> None:
        with self._lock:
            self._finished.append(sp)
            if len(self._finished) > self._max_spans:
                drop = len(self._finished) - self._max_spans
                del self._finished[:drop]
                self.n_dropped += drop
        if self._exporter is not None:
            try:
                self._exporter.export(sp)
            except Exception:
                # tracing must never kill work — but a dying exporter
                # must not die SILENTLY either (the round-10 lint bans
                # swallowed errors): count the drop so snapshot() shows it
                self.n_dropped += 1


class _NullSpan:
    """Shared inert span: ``set()`` no-ops, fields read as empty."""

    __slots__ = ()
    name = ""
    span_id = 0
    parent_id = None
    thread = ""
    start = 0.0
    end = 0.0
    attrs: dict = {}
    duration = 0.0

    def set(self, **attrs) -> "_NullSpan":
        return self


class _NullSpanContext:
    __slots__ = ()

    def __enter__(self) -> _NullSpan:
        return _NULL_SPAN

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SPAN = _NullSpan()
_NULL_SPAN_CONTEXT = _NullSpanContext()


class NullTracer:
    """The default tracer: every call returns a shared inert object.

    ``span()`` allocates nothing (the kwargs dict an instrumented call
    site builds is the entire cost), so instrumentation is safe to
    leave on hot paths unconditionally — guarded by the overhead test
    in ``tests/test_observability.py``.
    """

    enabled = False

    def __init__(self, clock: Clock | None = None):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.n_dropped = 0

    def span(self, name: str, **attrs) -> _NullSpanContext:
        return _NULL_SPAN_CONTEXT

    def record_span(self, name: str, start: float, end: float, *,
                    thread: str | None = None, **attrs) -> _NullSpan:
        return _NULL_SPAN

    def current_span(self) -> None:
        return None

    def spans(self) -> list:
        return []

    def snapshot(self) -> dict:
        return {"n_spans": 0, "n_dropped": 0, "spans_by_name": {}}

    def clear(self) -> None:
        pass


#: process-wide default null tracer (shares the system clock)
NULL_TRACER = NullTracer()
