"""Device-sync accounting: how many host<->device round trips did a run pay?

The round-5 bench established that the strict wall-clock basis is
latency-bound, not compute-bound: every synchronous device round trip over
the TPU tunnel costs a measured ~102 ms FLOOR regardless of payload, so
the residual gap between the pipeline-full and wall-clock bases is, to
first order, ``n_syncs x sync_floor``. This module turns that model into
bookkeeping: every call site that blocks on the device (``device_get`` of
a chunk, ``block_until_ready`` probes, per-generation collects) records
one event into a :class:`SyncLedger`, and the bench multiplies the count
by the measured floor to ATTRIBUTE the residual wall-clock gap instead of
assuming it (VERDICT r5 Next #1c).

Design rules follow the subsystem's: stdlib-only, injected clock,
thread-safe (fetch threads, the probe thread and the drain thread all
record into one ledger), and cheap enough to leave on unconditionally —
recording is one lock + tuple append.
"""
from __future__ import annotations

import threading

from .clock import Clock, SYSTEM_CLOCK

#: the measured tiny-fetch sync latency floor over the axon TPU tunnel
#: (BASELINE.md, round-5 session measurement). A co-located host runs
#: ~1 ms; benches may override via PYABC_TPU_SYNC_FLOOR_S.
DEFAULT_SYNC_FLOOR_S = 0.102


class SyncLedger:
    """Counts synchronous host<->device round trips and their payloads.

    ``record(kind, nbytes)`` is called AT the blocking call site (chunk
    fetch, compute probe, generation collect, ...). ``summary()`` returns
    the per-kind counts/bytes plus the floor-model attribution the bench
    reports as ``syncs_per_run`` / ``tunnel_floor_s``.
    """

    def __init__(self, clock: Clock | None = None):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self._lock = threading.Lock()
        #: (ts, kind, nbytes) per sync, in record order
        self.events: list[tuple[float, str, int]] = []  # abc-lint: guarded-by=_lock

    def record(self, kind: str, nbytes: int = 0) -> None:
        with self._lock:
            self.events.append((self.clock.now(), str(kind), int(nbytes)))

    @property
    def count(self) -> int:
        with self._lock:
            return len(self.events)

    def by_kind(self) -> dict[str, int]:
        with self._lock:
            out: dict[str, int] = {}
            for _ts, kind, _b in self.events:
                out[kind] = out.get(kind, 0) + 1
            return out

    def total_bytes(self) -> int:
        with self._lock:
            return sum(b for _ts, _k, b in self.events)

    def floor_s(self, sync_floor_s: float = DEFAULT_SYNC_FLOOR_S) -> float:
        """Wall clock the floor model attributes to this ledger's syncs."""
        return self.count * float(sync_floor_s)

    def summary(self, sync_floor_s: float = DEFAULT_SYNC_FLOOR_S) -> dict:
        with self._lock:
            n = len(self.events)
            by_kind: dict[str, int] = {}
            bytes_by_kind: dict[str, int] = {}
            for _ts, kind, b in self.events:
                by_kind[kind] = by_kind.get(kind, 0) + 1
                bytes_by_kind[kind] = bytes_by_kind.get(kind, 0) + b
        return {
            "syncs": n,
            "by_kind": by_kind,
            "bytes_by_kind": bytes_by_kind,
            "total_bytes": sum(bytes_by_kind.values()),
            "sync_floor_s": float(sync_floor_s),
            "tunnel_floor_s": round(n * float(sync_floor_s), 6),
        }

    def budget_report(self, chunks: int, allowed: int) -> dict:
        """Assert the dispatch engine's per-run sync budget
        (``syncs_per_run <= chunks + O(1)``) against this ledger.

        The engine computes ``allowed`` from its declared per-chunk
        round trips (one packed fetch per processed chunk, plus opt-in
        compute probes / checkpoint fetches) and an O(1) per-run
        allowance; the LEDGER is the authority on what was actually
        paid. ``ok=False`` means a blocking round trip crept into the
        per-chunk path — the bench ``dispatch`` lane regression-guards
        it and the engine raises under PYABC_TPU_SYNC_BUDGET_STRICT."""
        n = self.count
        return {
            "syncs": int(n),
            "chunks": int(chunks),
            "allowed": int(allowed),
            "ok": bool(n <= int(allowed)),
        }

    def clear(self) -> None:
        with self._lock:
            self.events.clear()


class NullSyncLedger:
    """Inert ledger for components run without an orchestrator."""

    events: list = []
    count = 0

    def record(self, kind: str, nbytes: int = 0) -> None:
        pass

    def by_kind(self) -> dict:
        return {}

    def total_bytes(self) -> int:
        return 0

    def floor_s(self, sync_floor_s: float = DEFAULT_SYNC_FLOOR_S) -> float:
        return 0.0

    def summary(self, sync_floor_s: float = DEFAULT_SYNC_FLOOR_S) -> dict:
        return {"syncs": 0, "by_kind": {}, "bytes_by_kind": {},
                "total_bytes": 0, "sync_floor_s": float(sync_floor_s),
                "tunnel_floor_s": 0.0}

    def budget_report(self, chunks: int, allowed: int) -> dict:
        return {"syncs": 0, "chunks": int(chunks),
                "allowed": int(allowed), "ok": True}

    def clear(self) -> None:
        pass


#: shared inert ledger (the default on samplers outside a run)
NULL_SYNC_LEDGER = NullSyncLedger()
