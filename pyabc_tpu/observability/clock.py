"""The single injected clock behind every host-side measurement.

Every instrumented module (orchestrator loops, broker, async DB writer,
bench) reads time through ONE of these objects instead of calling
``time.time()`` ad hoc:

- spans and deadlines become immune to wall-clock steps (NTP slews,
  suspended VMs) because the default timebase is ``time.monotonic()``;
- tests drive a :class:`VirtualClock` to make timing logic deterministic
  (the bench spend loop and broker deadlines are tested this way).

``now()`` is the measurement timebase (monotonic seconds; arbitrary
epoch — only differences are meaningful). ``wall()`` is the civil
timestamp for DATA that leaves the process (log lines, db rows); never
subtract two ``wall()`` readings to measure a duration.

A repo lint (``tests/test_observability_lint.py``) fails when an
instrumented module calls ``time.time()`` directly.
"""
from __future__ import annotations

import time as _time


class Clock:
    """Interface: ``now()`` (monotonic) + ``wall()`` (civil)."""

    def now(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError

    def wall(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError


class SystemClock(Clock):
    """The production clock: monotonic timebase, wall timestamps."""

    def now(self) -> float:
        return _time.monotonic()

    def wall(self) -> float:
        return _time.time()


class VirtualClock(Clock):
    """A test clock advanced explicitly; ``wall()`` tracks ``now()``."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def wall(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        self._t += float(dt)
        return self._t


#: process-wide default — share ONE instance so timestamps from
#: different subsystems (tracer spans, bench events, broker deadlines)
#: live on the same timebase and can be compared directly
SYSTEM_CLOCK = SystemClock()
