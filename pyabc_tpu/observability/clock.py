"""The single injected clock behind every host-side measurement.

Every instrumented module (orchestrator loops, broker, async DB writer,
bench) reads time through ONE of these objects instead of calling
``time.time()`` ad hoc:

- spans and deadlines become immune to wall-clock steps (NTP slews,
  suspended VMs) because the default timebase is ``time.monotonic()``;
- tests drive a :class:`VirtualClock` to make timing logic deterministic
  (the bench spend loop and broker deadlines are tested this way).

``now()`` is the measurement timebase (monotonic seconds; arbitrary
epoch — only differences are meaningful). ``wall()`` is the civil
timestamp for DATA that leaves the process (log lines, db rows); never
subtract two ``wall()`` readings to measure a duration.

A repo lint (``tests/test_observability_lint.py``) fails when an
instrumented module calls ``time.time()`` directly.
"""
from __future__ import annotations

import time as _time


class Clock:
    """Interface: ``now()`` (monotonic) + ``wall()`` (civil)."""

    def now(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError

    def wall(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError


class SystemClock(Clock):
    """The production clock: monotonic timebase, wall timestamps."""

    def now(self) -> float:
        # abc-lint: disable=CLOCK001 SystemClock IS the injected clock's timebase — the one legal raw monotonic read
        return _time.monotonic()

    def wall(self) -> float:
        # abc-lint: disable=CLOCK001 SystemClock IS the injected clock's civil source — the one legal raw wall read
        return _time.time()


class VirtualClock(Clock):
    """A test clock advanced explicitly; ``wall()`` tracks ``now()``."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def wall(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        self._t += float(dt)
        return self._t


class ClockOffsetEstimator:
    """NTP-style offset of a REMOTE clock against a local one.

    Elastic workers are separate processes (possibly separate machines),
    so their monotonic clocks share no epoch with the orchestrator's.
    Every request/response exchange yields one offset sample: the local
    peer sends at ``t1``, the remote stamps its clock at ``t2`` while
    handling, and the reply lands locally at ``t4``. Assuming the
    transport is symmetric, the remote handled the request at the RTT
    midpoint, so

        offset = t2 - (t1 + t4) / 2      (remote = local + offset)

    with worst-case error bounded by half the round trip,

        uncertainty = (t4 - t1) / 2

    (the classic NTP bound: the true offset lies in
    ``[t2 - t4, t2 - t1]`` whatever the asymmetry). ``add_sample`` keeps
    the MINIMUM-RTT sample of a sliding window — the exchange least
    delayed by queueing is the one whose midpoint assumption is
    tightest — so a single congested round trip can't poison the
    estimate. Pure float bookkeeping, no locks: each worker thread owns
    its estimator.
    """

    __slots__ = ("window", "_samples", "offset", "uncertainty_s", "rtt_s",
                 "n_samples")

    def __init__(self, window: int = 64):
        self.window = int(window)
        self._samples: list[tuple[float, float]] = []  # (rtt, offset)
        self.offset: float | None = None
        self.uncertainty_s: float | None = None
        self.rtt_s: float | None = None
        self.n_samples = 0

    def add_sample(self, t1: float, t2_remote: float, t4: float) -> None:
        rtt = float(t4) - float(t1)
        if rtt < 0:  # a stepped/broken local clock; drop the sample
            return
        self.n_samples += 1
        self._samples.append(
            (rtt, float(t2_remote) - (float(t1) + float(t4)) / 2.0)
        )
        if len(self._samples) > self.window:
            del self._samples[: len(self._samples) - self.window]
        best_rtt, best_off = min(self._samples)
        self.offset = best_off
        self.rtt_s = best_rtt
        self.uncertainty_s = best_rtt / 2.0

    def to_remote(self, t_local: float) -> float:
        """Map a local-clock instant onto the remote timebase."""
        return float(t_local) + (self.offset or 0.0)

    def to_local(self, t_remote: float) -> float:
        """Map a remote-clock instant onto the local timebase."""
        return float(t_remote) - (self.offset or 0.0)

    def summary(self) -> dict:
        return {
            "offset_s": self.offset,
            "uncertainty_s": self.uncertainty_s,
            "rtt_s": self.rtt_s,
            "n_samples": self.n_samples,
        }


#: process-wide default — share ONE instance so timestamps from
#: different subsystems (tracer spans, bench events, broker deadlines)
#: live on the same timebase and can be compared directly
SYSTEM_CLOCK = SystemClock()
