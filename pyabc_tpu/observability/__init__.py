"""pyabc_tpu.observability — tracing spans, metrics, wall-clock attribution.

One dependency-free subsystem for every host-side measurement in the
ABC-SMC pipeline (SURVEY.md §5.1 tracing/profiling row, grown into a
first-class layer):

- :class:`Tracer` / :class:`NullTracer` — nested, thread-safe spans on
  a single injected clock (``tracer.span("generation", t=3)``);
- :class:`MetricsRegistry` — counters, gauges, histogram timers
  (broker queue depth, chunk latency, DB backlog);
- exporters — :class:`JsonlTraceExporter` (streamed trace file),
  :func:`prometheus_text` (metrics dump), plus in-process
  ``snapshot()`` APIs the visserver dashboard and bench read;
- :func:`coverage_report` — the coverage accountant: the fraction of a
  wall-clock window attributed to at least one span, overall and per
  thread (the round-5 "60% dark time" gap as a number);
- :class:`SyncLedger` — device-sync accounting: every blocking
  host<->device round trip is recorded, so the bench can attribute the
  residual wall-clock gap to the measured tunnel latency floor
  (``syncs x ~102 ms``) instead of assuming it.

Enablement: everything defaults to the no-op :data:`NULL_TRACER` /
:data:`NULL_METRICS`. Turn tracing on per run via
``ABCSMC(..., tracer=Tracer(...))`` or process-wide via the env var
``PYABC_TPU_TRACE=/path/to/trace.jsonl`` (read by
:func:`default_tracer`). Instrumentation wraps host boundaries only —
compiled device code is never touched, so fused kernels are
byte-identical with observability on or off.
"""
from .clock import (
    Clock,
    ClockOffsetEstimator,
    SystemClock,
    VirtualClock,
    SYSTEM_CLOCK,
)
from .coverage import (
    coverage_report,
    device_busy_spans,
    elastic_gap_attribution,
    interval_intersection,
    interval_union,
    window_throughput,
)
from .export import (
    JsonlTraceExporter,
    prometheus_text,
    read_trace,
    worker_trace_spans,
    write_trace,
)
from .metrics import (
    Counter,
    FEDERATED_SPAN_BATCHES_TOTAL,
    FEDERATED_SPANS_TOTAL,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullMetrics,
    NULL_METRICS,
)
from .recorder import (
    FlightCorruptError,
    FlightRecorder,
    read_flight,
    render_timeline,
    write_flight,
)
from .slo import SLO, SloEngine, default_slos
from .sync import (
    DEFAULT_SYNC_FLOOR_S,
    NullSyncLedger,
    NULL_SYNC_LEDGER,
    SyncLedger,
)
from .tracer import NullTracer, NULL_TRACER, Span, Tracer

import os as _os
import threading as _threading

__all__ = [
    "Clock", "ClockOffsetEstimator", "SystemClock", "VirtualClock",
    "SYSTEM_CLOCK",
    "Span", "Tracer", "NullTracer", "NULL_TRACER",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NullMetrics",
    "NULL_METRICS",
    "JsonlTraceExporter", "prometheus_text", "read_trace",
    "worker_trace_spans", "write_trace",
    "coverage_report", "device_busy_spans", "elastic_gap_attribution",
    "interval_intersection", "interval_union", "window_throughput",
    "SyncLedger", "NullSyncLedger", "NULL_SYNC_LEDGER",
    "DEFAULT_SYNC_FLOOR_S",
    "default_tracer", "global_metrics", "global_tracer",
    "set_global_tracer", "observability_snapshot",
    "register_worker_source", "unregister_worker_source",
    "register_dispatch_source", "unregister_dispatch_source",
    "dispatch_sources_snapshot",
    "register_tenant_source", "unregister_tenant_source",
    "tenant_sources_snapshot",
    "record_host_clock_offset", "host_clocks_snapshot",
    "FlightRecorder", "FlightCorruptError", "read_flight", "write_flight",
    "render_timeline",
    "SLO", "SloEngine", "default_slos",
    "register_slo_source", "unregister_slo_source", "slo_sources_snapshot",
    "ingest_remote_spans", "federated_spans_snapshot",
    "clear_federated_spans",
    "install_span_ship_hook", "uninstall_span_ship_hook",
    "fire_span_ship_hooks",
]

_lock = _threading.Lock()
_global_tracer = None
_global_metrics: MetricsRegistry | None = None


def default_tracer():
    """The tracer a fresh ABCSMC uses when none is passed: a JSONL-
    exporting tracer if ``PYABC_TPU_TRACE`` names a path (shared
    process-wide so back-to-back runs append to one trace), else
    :data:`NULL_TRACER`."""
    path = _os.environ.get("PYABC_TPU_TRACE")
    if not path:
        return NULL_TRACER
    global _global_tracer
    with _lock:
        if _global_tracer is None or getattr(
                getattr(_global_tracer, "_exporter", None), "path", None
        ) != path:
            _global_tracer = Tracer(exporter=JsonlTraceExporter(path))
        return _global_tracer


def global_tracer():
    """The process-wide tracer, if any was installed (via
    ``PYABC_TPU_TRACE`` or :func:`set_global_tracer`); else the null
    tracer. The visserver's ``/api/observability`` endpoint reads it."""
    with _lock:
        return _global_tracer if _global_tracer is not None else NULL_TRACER


def set_global_tracer(tracer) -> None:
    global _global_tracer
    with _lock:
        _global_tracer = tracer


def global_metrics() -> MetricsRegistry:
    """Process-wide metrics registry (created on first use). Real (not
    null): bare counters/gauges are cheap enough to always collect, and
    a dashboard scraping a process that never configured observability
    should still see the broker/writer instruments."""
    global _global_metrics
    with _lock:
        if _global_metrics is None:
            _global_metrics = MetricsRegistry()
        return _global_metrics


#: weakly-referenced providers of elastic-worker state: each entry is a
#: weakref to an object with ``worker_snapshot() -> dict`` (the
#: EvalBroker registers itself on construction). Dead refs are pruned on
#: read, so a broker that was garbage-collected silently drops out.
_worker_sources: list = []


def register_worker_source(source) -> None:
    """Register an object exposing ``worker_snapshot()`` (per-worker
    liveness / clock offsets / last errors) with the process-wide
    snapshot, via weakref — the dashboard's ``/api/observability`` then
    shows the elastic pool without the broker leaking through module
    state."""
    import weakref

    with _lock:
        _worker_sources.append(weakref.ref(source))


def unregister_worker_source(source) -> None:
    with _lock:
        _worker_sources[:] = [
            r for r in _worker_sources
            if r() is not None and r() is not source
        ]


def _workers_snapshot() -> dict:
    out: dict = {}
    with _lock:
        refs = list(_worker_sources)
    for r in refs:
        src = r()
        if src is None:
            continue
        try:
            out.update(src.worker_snapshot())
        except Exception as exc:  # snapshotting must never kill the
            # dashboard — but the broken source is named, not swallowed
            out.setdefault("__errors__", []).append(repr(exc)[:200])
    with _lock:
        _worker_sources[:] = [r for r in _worker_sources if r() is not None]
    return out


#: weakly-referenced providers of dispatch-engine state: each entry is a
#: weakref to an object with ``snapshot() -> dict`` (the DispatchEngine
#: registers itself on construction). Same lifecycle rules as the
#: worker sources: dead refs prune on read.
_dispatch_sources: list = []


def register_dispatch_source(source) -> None:
    """Register an object exposing ``snapshot()`` (dispatch-engine state:
    in-flight chunks, speculative rollbacks, the per-run sync budget)
    with the process-wide snapshot, via weakref — the dashboard's
    ``/api/observability`` and the broker status then show the fused
    run's dispatch health next to the elastic pool's."""
    import weakref

    with _lock:
        _dispatch_sources.append(weakref.ref(source))


def unregister_dispatch_source(source) -> None:
    with _lock:
        _dispatch_sources[:] = [
            r for r in _dispatch_sources
            if r() is not None and r() is not source
        ]


def dispatch_sources_snapshot() -> list:
    """Snapshots of every live dispatch engine in this process."""
    out: list = []
    with _lock:
        refs = list(_dispatch_sources)
    for r in refs:
        src = r()
        if src is None:
            continue
        try:
            out.append(src.snapshot())
        except Exception as exc:  # snapshotting must never kill the
            # dashboard — but the broken source is named, not swallowed
            out.append({"__error__": repr(exc)[:200]})
    with _lock:
        _dispatch_sources[:] = [
            r for r in _dispatch_sources if r() is not None
        ]
    return out


#: weakly-referenced multi-tenant namespaces: tenant name -> weakref to
#: an object with ``namespace_snapshot() -> dict`` (the serving layer's
#: Tenant registers itself while it lives). Pre-round-14 the snapshot
#: assumed ONE run per process — concurrent runs interleaved their spans
#: in the global tracer and overwrote each other's gauges; namespacing
#: gives every run its own tracer/metrics pair and aggregates them here
#: side by side instead.
_tenant_sources: dict = {}


def register_tenant_source(name: str, source) -> None:
    """Register a tenant namespace (an object with
    ``namespace_snapshot()``) under ``name`` with the process-wide
    snapshot, via weakref. A later registration under the same name
    replaces the earlier one (tenant ids are unique per scheduler)."""
    import weakref

    with _lock:
        _tenant_sources[str(name)] = weakref.ref(source)


def unregister_tenant_source(name: str) -> None:
    with _lock:
        _tenant_sources.pop(str(name), None)


def tenant_sources_snapshot() -> dict:
    """{tenant name: namespace snapshot} for every live tenant.

    Race-free by construction: the registry is copied under the module
    lock, each namespace snapshots its OWN tracer/metrics (which lock
    internally), and a tenant garbage-collected mid-iteration simply
    drops out — two concurrent callers each get a consistent view."""
    with _lock:
        refs = dict(_tenant_sources)
    out: dict = {}
    for name, ref in refs.items():
        src = ref()
        if src is None:
            continue
        try:
            out[name] = src.namespace_snapshot()
        except Exception as exc:  # snapshotting must never kill the
            # dashboard — but the broken source is named, not swallowed
            out[name] = {"__error__": repr(exc)[:200]}
    with _lock:
        for name in list(_tenant_sources):
            if _tenant_sources[name]() is None:
                del _tenant_sources[name]
    return out


#: measured clock offsets of OTHER hosts against this process's clock:
#: host name -> the ClockOffsetEstimator summary dict recorded by
#: ``parallel.distributed.measure_clock_offset``. Multi-host span merges
#: read this table to map a secondary host's monotonic timestamps onto
#: the coordinator's timeline (offset ± RTT/2).
_host_clocks: dict = {}


def record_host_clock_offset(host: str, summary: dict) -> None:
    """Record a remote host's measured clock offset (a
    :meth:`~pyabc_tpu.observability.ClockOffsetEstimator.summary` dict)
    under ``host`` in the process-wide snapshot. Re-measurement
    replaces the earlier record."""
    with _lock:
        _host_clocks[str(host)] = dict(summary)


def host_clocks_snapshot() -> dict:
    """{host: offset summary} for every measured remote host."""
    with _lock:
        return {h: dict(s) for h, s in _host_clocks.items()}


#: spans shipped from OTHER processes of a multi-host run, already
#: offset-corrected onto this process's timebase and accounted on
#: ``host:<p>`` pseudo-threads (the round-8 ``worker:<id>`` pattern,
#: extended to whole pod members). Bounded: oldest dropped beyond the
#: cap, so a long fleet run cannot grow the primary without bound.
_federated_spans: list = []
_FEDERATED_MAX_SPANS = 4096
_federated_batches = 0
_federated_dropped = 0


def ingest_remote_spans(host: str, process_id: int, spans,
                        *, tracer=None) -> int:
    """Merge span summaries shipped by a remote process.

    Each span dict's ``start``/``end`` are monotonic timestamps on the
    REMOTE host's clock; they are mapped onto this process's timebase
    with the measured offset from :func:`host_clocks_snapshot`
    (``local = remote - offset_s`` — the estimator's offset convention
    is remote-minus-local), then accounted under a ``host:<p>``
    pseudo-thread in the bounded federated buffer (and mirrored into
    ``tracer`` — default the process-global tracer — via
    ``record_span``, so the coverage accountant and the flight recorder
    see the whole pod). Spans from a host with NO measured offset merge
    uncorrected and are flagged ``offset_corrected=False``. Returns the
    number of spans merged."""
    global _federated_batches, _federated_dropped
    with _lock:
        summ = _host_clocks.get(str(host))
    offset = float(summ.get("offset_s") or 0.0) if summ else 0.0
    corrected = summ is not None and summ.get("offset_s") is not None
    if tracer is None:
        tracer = global_tracer()
    thread = f"host:{int(process_id)}"
    merged: list = []
    for sp in spans:
        d = sp.to_dict() if hasattr(sp, "to_dict") else dict(sp)
        start = float(d.get("start") or 0.0) - offset
        end_raw = d.get("end")
        end = (float(end_raw) - offset) if end_raw is not None else start
        attrs = dict(d.get("attrs") or {})
        attrs["origin_host"] = str(host)
        attrs["origin_thread"] = d.get("thread", "")
        if not corrected:
            attrs["offset_corrected"] = False
        merged.append({
            "name": d.get("name", ""), "thread": thread,
            "start": start, "end": end, "attrs": attrs,
        })
        if getattr(tracer, "enabled", False):
            tracer.record_span(d.get("name", ""), start, end,
                               thread=thread, **attrs)
    with _lock:
        _federated_spans.extend(merged)
        _federated_batches += 1
        if len(_federated_spans) > _FEDERATED_MAX_SPANS:
            drop = len(_federated_spans) - _FEDERATED_MAX_SPANS
            del _federated_spans[:drop]
            _federated_dropped += drop
    reg = global_metrics()
    reg.counter(FEDERATED_SPAN_BATCHES_TOTAL).inc()
    reg.counter(FEDERATED_SPANS_TOTAL).inc(len(merged))
    return len(merged)


def federated_spans_snapshot() -> list:
    """Offset-corrected remote spans merged so far (dicts, oldest
    first; bounded — see :func:`ingest_remote_spans`)."""
    with _lock:
        return [dict(d) for d in _federated_spans]


def clear_federated_spans() -> None:
    """Drop the federated buffer (test/bench hygiene between runs)."""
    global _federated_batches, _federated_dropped
    with _lock:
        _federated_spans.clear()
        _federated_batches = 0
        _federated_dropped = 0


#: span-ship hooks the dispatch engine fires once per processed chunk
#: (the per-generation coordination cadence): zero-argument callables —
#: a SpanShipper's ``ship``. Plain host-side I/O only: a hook must
#: never touch a device or the SyncLedger, and a raising hook is
#: dropped (best-effort observability must not fail the run).
_span_ship_hooks: list = []


def install_span_ship_hook(fn) -> None:
    """Register ``fn`` to fire on the per-chunk federation cadence."""
    with _lock:
        if fn not in _span_ship_hooks:
            _span_ship_hooks.append(fn)


def uninstall_span_ship_hook(fn) -> None:
    with _lock:
        _span_ship_hooks[:] = [f for f in _span_ship_hooks if f is not fn]


def fire_span_ship_hooks() -> None:
    """Fire every installed ship hook; raising hooks uninstall
    themselves (counted nowhere — the shipper side already marks itself
    dead and logs through its own channel)."""
    with _lock:
        hooks = list(_span_ship_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:
            uninstall_span_ship_hook(fn)


#: weakly-referenced SLO engines: each entry is a weakref to an object
#: with ``snapshot() -> dict`` (the RunScheduler's SloEngine registers
#: itself on construction). Same lifecycle rules as the dispatch
#: sources: dead refs prune on read.
_slo_sources: list = []


def register_slo_source(source) -> None:
    """Register an SLO engine (an object with ``snapshot()``) with the
    process-wide snapshot, via weakref — ``/api/observability`` then
    carries the live burn-rate state next to the tenant namespaces."""
    import weakref

    with _lock:
        _slo_sources.append(weakref.ref(source))


def unregister_slo_source(source) -> None:
    with _lock:
        _slo_sources[:] = [
            r for r in _slo_sources
            if r() is not None and r() is not source
        ]


def slo_sources_snapshot() -> list:
    """Snapshots of every live SLO engine in this process."""
    out: list = []
    with _lock:
        refs = list(_slo_sources)
    for r in refs:
        src = r()
        if src is None:
            continue
        try:
            out.append(src.snapshot())
        except Exception as exc:  # snapshotting must never kill the
            # dashboard — but the broken source is named, not swallowed
            out.append({"__error__": repr(exc)[:200]})
    with _lock:
        _slo_sources[:] = [r for r in _slo_sources if r() is not None]
    return out


def observability_snapshot() -> dict:
    """One JSON-ready dict of the process's tracer + metrics state —
    the in-process snapshot API (dashboard endpoint, bench block).
    ``workers`` carries the elastic pool's per-worker liveness, clock
    offsets and last errors when a broker is live in this process;
    ``dispatch`` carries each live dispatch engine's state (in-flight
    chunks, speculative rollbacks, sync budget); ``tenants`` carries
    each live serving-layer tenant's PRIVATE tracer/metrics namespace —
    concurrent runs aggregate side by side instead of interleaving
    through the process globals; ``hosts`` carries the measured clock
    offset (± RTT/2) of every remote host probed from this process."""
    with _lock:
        fed = {"n_spans": len(_federated_spans),
               "n_batches": _federated_batches,
               "n_dropped": _federated_dropped}
    return {
        "tracer": global_tracer().snapshot(),
        "metrics": global_metrics().snapshot(),
        "workers": _workers_snapshot(),
        "dispatch": dispatch_sources_snapshot(),
        "tenants": tenant_sources_snapshot(),
        "hosts": host_clocks_snapshot(),
        "federation": fed,
        "slo": slo_sources_snapshot(),
    }
