"""pyabc_tpu.observability — tracing spans, metrics, wall-clock attribution.

One dependency-free subsystem for every host-side measurement in the
ABC-SMC pipeline (SURVEY.md §5.1 tracing/profiling row, grown into a
first-class layer):

- :class:`Tracer` / :class:`NullTracer` — nested, thread-safe spans on
  a single injected clock (``tracer.span("generation", t=3)``);
- :class:`MetricsRegistry` — counters, gauges, histogram timers
  (broker queue depth, chunk latency, DB backlog);
- exporters — :class:`JsonlTraceExporter` (streamed trace file),
  :func:`prometheus_text` (metrics dump), plus in-process
  ``snapshot()`` APIs the visserver dashboard and bench read;
- :func:`coverage_report` — the coverage accountant: the fraction of a
  wall-clock window attributed to at least one span, overall and per
  thread (the round-5 "60% dark time" gap as a number);
- :class:`SyncLedger` — device-sync accounting: every blocking
  host<->device round trip is recorded, so the bench can attribute the
  residual wall-clock gap to the measured tunnel latency floor
  (``syncs x ~102 ms``) instead of assuming it.

Enablement: everything defaults to the no-op :data:`NULL_TRACER` /
:data:`NULL_METRICS`. Turn tracing on per run via
``ABCSMC(..., tracer=Tracer(...))`` or process-wide via the env var
``PYABC_TPU_TRACE=/path/to/trace.jsonl`` (read by
:func:`default_tracer`). Instrumentation wraps host boundaries only —
compiled device code is never touched, so fused kernels are
byte-identical with observability on or off.
"""
from .clock import (
    Clock,
    ClockOffsetEstimator,
    SystemClock,
    VirtualClock,
    SYSTEM_CLOCK,
)
from .coverage import (
    coverage_report,
    device_busy_spans,
    elastic_gap_attribution,
    interval_intersection,
    interval_union,
    window_throughput,
)
from .export import (
    JsonlTraceExporter,
    prometheus_text,
    read_trace,
    worker_trace_spans,
    write_trace,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullMetrics,
    NULL_METRICS,
)
from .sync import (
    DEFAULT_SYNC_FLOOR_S,
    NullSyncLedger,
    NULL_SYNC_LEDGER,
    SyncLedger,
)
from .tracer import NullTracer, NULL_TRACER, Span, Tracer

import os as _os
import threading as _threading

__all__ = [
    "Clock", "ClockOffsetEstimator", "SystemClock", "VirtualClock",
    "SYSTEM_CLOCK",
    "Span", "Tracer", "NullTracer", "NULL_TRACER",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NullMetrics",
    "NULL_METRICS",
    "JsonlTraceExporter", "prometheus_text", "read_trace",
    "worker_trace_spans", "write_trace",
    "coverage_report", "device_busy_spans", "elastic_gap_attribution",
    "interval_intersection", "interval_union", "window_throughput",
    "SyncLedger", "NullSyncLedger", "NULL_SYNC_LEDGER",
    "DEFAULT_SYNC_FLOOR_S",
    "default_tracer", "global_metrics", "global_tracer",
    "set_global_tracer", "observability_snapshot",
    "register_worker_source", "unregister_worker_source",
    "register_dispatch_source", "unregister_dispatch_source",
    "dispatch_sources_snapshot",
    "register_tenant_source", "unregister_tenant_source",
    "tenant_sources_snapshot",
    "record_host_clock_offset", "host_clocks_snapshot",
]

_lock = _threading.Lock()
_global_tracer = None
_global_metrics: MetricsRegistry | None = None


def default_tracer():
    """The tracer a fresh ABCSMC uses when none is passed: a JSONL-
    exporting tracer if ``PYABC_TPU_TRACE`` names a path (shared
    process-wide so back-to-back runs append to one trace), else
    :data:`NULL_TRACER`."""
    path = _os.environ.get("PYABC_TPU_TRACE")
    if not path:
        return NULL_TRACER
    global _global_tracer
    with _lock:
        if _global_tracer is None or getattr(
                getattr(_global_tracer, "_exporter", None), "path", None
        ) != path:
            _global_tracer = Tracer(exporter=JsonlTraceExporter(path))
        return _global_tracer


def global_tracer():
    """The process-wide tracer, if any was installed (via
    ``PYABC_TPU_TRACE`` or :func:`set_global_tracer`); else the null
    tracer. The visserver's ``/api/observability`` endpoint reads it."""
    with _lock:
        return _global_tracer if _global_tracer is not None else NULL_TRACER


def set_global_tracer(tracer) -> None:
    global _global_tracer
    with _lock:
        _global_tracer = tracer


def global_metrics() -> MetricsRegistry:
    """Process-wide metrics registry (created on first use). Real (not
    null): bare counters/gauges are cheap enough to always collect, and
    a dashboard scraping a process that never configured observability
    should still see the broker/writer instruments."""
    global _global_metrics
    with _lock:
        if _global_metrics is None:
            _global_metrics = MetricsRegistry()
        return _global_metrics


#: weakly-referenced providers of elastic-worker state: each entry is a
#: weakref to an object with ``worker_snapshot() -> dict`` (the
#: EvalBroker registers itself on construction). Dead refs are pruned on
#: read, so a broker that was garbage-collected silently drops out.
_worker_sources: list = []


def register_worker_source(source) -> None:
    """Register an object exposing ``worker_snapshot()`` (per-worker
    liveness / clock offsets / last errors) with the process-wide
    snapshot, via weakref — the dashboard's ``/api/observability`` then
    shows the elastic pool without the broker leaking through module
    state."""
    import weakref

    with _lock:
        _worker_sources.append(weakref.ref(source))


def unregister_worker_source(source) -> None:
    with _lock:
        _worker_sources[:] = [
            r for r in _worker_sources
            if r() is not None and r() is not source
        ]


def _workers_snapshot() -> dict:
    out: dict = {}
    with _lock:
        refs = list(_worker_sources)
    for r in refs:
        src = r()
        if src is None:
            continue
        try:
            out.update(src.worker_snapshot())
        except Exception as exc:  # snapshotting must never kill the
            # dashboard — but the broken source is named, not swallowed
            out.setdefault("__errors__", []).append(repr(exc)[:200])
    with _lock:
        _worker_sources[:] = [r for r in _worker_sources if r() is not None]
    return out


#: weakly-referenced providers of dispatch-engine state: each entry is a
#: weakref to an object with ``snapshot() -> dict`` (the DispatchEngine
#: registers itself on construction). Same lifecycle rules as the
#: worker sources: dead refs prune on read.
_dispatch_sources: list = []


def register_dispatch_source(source) -> None:
    """Register an object exposing ``snapshot()`` (dispatch-engine state:
    in-flight chunks, speculative rollbacks, the per-run sync budget)
    with the process-wide snapshot, via weakref — the dashboard's
    ``/api/observability`` and the broker status then show the fused
    run's dispatch health next to the elastic pool's."""
    import weakref

    with _lock:
        _dispatch_sources.append(weakref.ref(source))


def unregister_dispatch_source(source) -> None:
    with _lock:
        _dispatch_sources[:] = [
            r for r in _dispatch_sources
            if r() is not None and r() is not source
        ]


def dispatch_sources_snapshot() -> list:
    """Snapshots of every live dispatch engine in this process."""
    out: list = []
    with _lock:
        refs = list(_dispatch_sources)
    for r in refs:
        src = r()
        if src is None:
            continue
        try:
            out.append(src.snapshot())
        except Exception as exc:  # snapshotting must never kill the
            # dashboard — but the broken source is named, not swallowed
            out.append({"__error__": repr(exc)[:200]})
    with _lock:
        _dispatch_sources[:] = [
            r for r in _dispatch_sources if r() is not None
        ]
    return out


#: weakly-referenced multi-tenant namespaces: tenant name -> weakref to
#: an object with ``namespace_snapshot() -> dict`` (the serving layer's
#: Tenant registers itself while it lives). Pre-round-14 the snapshot
#: assumed ONE run per process — concurrent runs interleaved their spans
#: in the global tracer and overwrote each other's gauges; namespacing
#: gives every run its own tracer/metrics pair and aggregates them here
#: side by side instead.
_tenant_sources: dict = {}


def register_tenant_source(name: str, source) -> None:
    """Register a tenant namespace (an object with
    ``namespace_snapshot()``) under ``name`` with the process-wide
    snapshot, via weakref. A later registration under the same name
    replaces the earlier one (tenant ids are unique per scheduler)."""
    import weakref

    with _lock:
        _tenant_sources[str(name)] = weakref.ref(source)


def unregister_tenant_source(name: str) -> None:
    with _lock:
        _tenant_sources.pop(str(name), None)


def tenant_sources_snapshot() -> dict:
    """{tenant name: namespace snapshot} for every live tenant.

    Race-free by construction: the registry is copied under the module
    lock, each namespace snapshots its OWN tracer/metrics (which lock
    internally), and a tenant garbage-collected mid-iteration simply
    drops out — two concurrent callers each get a consistent view."""
    with _lock:
        refs = dict(_tenant_sources)
    out: dict = {}
    for name, ref in refs.items():
        src = ref()
        if src is None:
            continue
        try:
            out[name] = src.namespace_snapshot()
        except Exception as exc:  # snapshotting must never kill the
            # dashboard — but the broken source is named, not swallowed
            out[name] = {"__error__": repr(exc)[:200]}
    with _lock:
        for name in list(_tenant_sources):
            if _tenant_sources[name]() is None:
                del _tenant_sources[name]
    return out


#: measured clock offsets of OTHER hosts against this process's clock:
#: host name -> the ClockOffsetEstimator summary dict recorded by
#: ``parallel.distributed.measure_clock_offset``. Multi-host span merges
#: read this table to map a secondary host's monotonic timestamps onto
#: the coordinator's timeline (offset ± RTT/2).
_host_clocks: dict = {}


def record_host_clock_offset(host: str, summary: dict) -> None:
    """Record a remote host's measured clock offset (a
    :meth:`~pyabc_tpu.observability.ClockOffsetEstimator.summary` dict)
    under ``host`` in the process-wide snapshot. Re-measurement
    replaces the earlier record."""
    with _lock:
        _host_clocks[str(host)] = dict(summary)


def host_clocks_snapshot() -> dict:
    """{host: offset summary} for every measured remote host."""
    with _lock:
        return {h: dict(s) for h, s in _host_clocks.items()}


def observability_snapshot() -> dict:
    """One JSON-ready dict of the process's tracer + metrics state —
    the in-process snapshot API (dashboard endpoint, bench block).
    ``workers`` carries the elastic pool's per-worker liveness, clock
    offsets and last errors when a broker is live in this process;
    ``dispatch`` carries each live dispatch engine's state (in-flight
    chunks, speculative rollbacks, sync budget); ``tenants`` carries
    each live serving-layer tenant's PRIVATE tracer/metrics namespace —
    concurrent runs aggregate side by side instead of interleaving
    through the process globals; ``hosts`` carries the measured clock
    offset (± RTT/2) of every remote host probed from this process."""
    return {
        "tracer": global_tracer().snapshot(),
        "metrics": global_metrics().snapshot(),
        "workers": _workers_snapshot(),
        "dispatch": dispatch_sources_snapshot(),
        "tenants": tenant_sources_snapshot(),
        "hosts": host_clocks_snapshot(),
    }
