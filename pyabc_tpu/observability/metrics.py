"""Counters, gauges and histogram timers over the injected clock.

The registry is the numeric side of the observability subsystem (spans
are the temporal side): broker queue depth, device-chunk latency, DB
writer backlog, accepted-particles/s all live here as named instruments.

Same design rules as the tracer: stdlib-only, host-side, and no-op
cheap when disabled (:data:`NULL_METRICS` is the default everywhere).
Exports: :meth:`MetricsRegistry.snapshot` (in-process dict for the
dashboard / bench) and :func:`~pyabc_tpu.observability.export.
prometheus_text` (Prometheus text exposition).
"""
from __future__ import annotations

import threading

from .clock import Clock, SYSTEM_CLOCK


class Counter:
    """Monotonically increasing count (events, particles, bytes)."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """A value that goes up and down (queue depth, backlog, in-flight)."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class _HistogramTimer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: "Histogram"):
        self._hist = hist
        self._t0 = 0.0

    def __enter__(self) -> "_HistogramTimer":
        self._t0 = self._hist._clock.now()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._hist.observe(self._hist._clock.now() - self._t0)
        return False


class Histogram:
    """Fixed log2-bucket histogram + running count/sum/min/max.

    Buckets are powers of two over ``[base, base * 2**n_buckets)`` —
    latency-shaped without configuration. ``time()`` returns a
    contextmanager observing elapsed seconds on the registry's clock.
    """

    __slots__ = ("name", "help", "_clock", "_lock", "_base", "_buckets",
                 "count", "sum", "min", "max")

    def __init__(self, name: str, help: str = "",
                 clock: Clock | None = None,
                 base: float = 1e-4, n_buckets: int = 28):
        self.name = name
        self.help = help
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        self._lock = threading.Lock()
        self._base = float(base)
        self._buckets = [0] * (int(n_buckets) + 1)  # +1: overflow bucket
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, value: float) -> None:
        v = float(value)
        i = 0
        edge = self._base
        while v >= edge and i < len(self._buckets) - 1:
            edge *= 2.0
            i += 1
        with self._lock:
            self._buckets[i] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def time(self) -> _HistogramTimer:
        return _HistogramTimer(self)

    def bucket_bounds(self) -> list[float]:
        out, edge = [], self._base
        for _ in range(len(self._buckets) - 1):
            out.append(edge)
            edge *= 2.0
        return out

    def snapshot(self) -> dict:
        """One consistent point-in-time read: bucket counts, count, sum,
        min, max captured under the lock TOGETHER. Every reader that
        needs more than a single field (the Prometheus exposition, the
        SLO engine, ``summary``/``quantile``) goes through this — a
        field-by-field read can interleave with a concurrent ``observe``
        and yield a ``count`` inconsistent with the cumulative bucket
        series."""
        with self._lock:
            return {
                "buckets": list(self._buckets),
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
            }

    def quantile(self, q: float, snap: dict | None = None) -> float:
        """Upper-bound quantile estimate from the log2 buckets: the
        upper edge of the smallest bucket whose cumulative count reaches
        ``q * count`` (the overflow bucket reports the observed max, the
        below-base bucket the base edge). NaN when empty. ``q`` is a
        fraction in [0, 1]. Conservative by construction — the true
        quantile is never above the estimate within a bucket."""
        s = snap if snap is not None else self.snapshot()
        count = s["count"]
        if count <= 0:
            return float("nan")
        target = max(1, -(-count * min(max(float(q), 0.0), 1.0) // 1))
        cum = 0
        edge = self._base
        buckets = s["buckets"]
        for n in buckets[:-1]:
            cum += n
            if cum >= target:
                return min(edge, s["max"])
            edge *= 2.0
        return s["max"]

    def summary(self) -> dict:
        s = self.snapshot()
        count = s["count"]
        return {
            "count": count, "sum": round(s["sum"], 9),
            "min": s["min"] if count else None,
            "max": s["max"] if count else None,
            "mean": (s["sum"] / count) if count else None,
            "p50": self.quantile(0.50, s) if count else None,
            "p90": self.quantile(0.90, s) if count else None,
            "p99": self.quantile(0.99, s) if count else None,
        }


# -- elastic-worker instrument names (the pyabc_tpu_worker_* family) ---------
#
# One canonical place for the Prometheus names the broker path exports so
# the sampler, bench and dashboard agree (ElasticSampler sets them, the
# text exposition renders them):
#:  number of workers the broker currently knows (heard from at all)
WORKER_KNOWN_GAUGE = "pyabc_tpu_worker_known"
#:  workers heard from within the liveness window (default 5 s)
WORKER_ALIVE_GAUGE = "pyabc_tpu_worker_alive"
#:  handed-out evaluation slots not yet delivered (broker queue depth)
WORKER_QUEUE_DEPTH_GAUGE = "pyabc_tpu_worker_queue_depth"
#:  worker evaluations reported via trace summaries (all workers)
WORKER_EVALS_COUNTER = "pyabc_tpu_worker_evals"
#:  per-worker delivered-results throughput; suffixed per worker id
WORKER_THROUGHPUT_GAUGE = "pyabc_tpu_worker_results_per_s"
#:  largest |clock offset| / offset uncertainty over reporting workers
WORKER_CLOCK_OFFSET_GAUGE = "pyabc_tpu_worker_clock_offset_max_abs_s"
WORKER_CLOCK_UNC_GAUGE = "pyabc_tpu_worker_clock_uncertainty_max_s"

# -- resilience instrument names (round 9) -----------------------------------
#
# The fault-tolerance subsystem's counters; one canonical place so the
# broker, worker, writer, fused loop, bench lane and dashboard agree:
#:  faults fired by the active FaultPlan (tests/bench assert injection)
FAULTS_INJECTED_TOTAL = "pyabc_tpu_faults_injected_total"
#:  expired / presumed-dead batch leases requeued and handed to a live
#:  worker (the self-healing redispatch the acceptance criteria guard)
BATCHES_REDISPATCHED_TOTAL = "pyabc_tpu_batches_redispatched_total"
#:  late duplicate deliveries dropped by slot-level dedup (exactly-once)
DUPLICATES_DROPPED_TOTAL = "pyabc_tpu_duplicate_results_dropped_total"
#:  batch leases reaped (expired or owner presumed dead) and requeued
LEASES_EXPIRED_TOTAL = "pyabc_tpu_leases_expired_total"
#:  broker round trips retried by the shared RetryPolicy (all callers)
REQUEST_RETRIES_TOTAL = "pyabc_tpu_request_retries_total"
#:  transient History persist failures retried before sticky latching
PERSIST_RETRIES_TOTAL = "pyabc_tpu_persist_retries_total"
#:  fused-loop carry checkpoints written (mid-chunk restore points)
CHECKPOINTS_WRITTEN_TOTAL = "pyabc_tpu_checkpoints_written_total"
#:  generation deadlines extended because live workers remain (the
#:  graceful-degradation path that replaces TimeoutError)
TIMEOUT_EXTENSIONS_TOTAL = "pyabc_tpu_generation_timeout_extensions_total"
#:  device contexts dropped + rebuilt after a (simulated) reset
DEVICE_RESETS_TOTAL = "pyabc_tpu_device_context_resets_total"

# -- numerical/statistical health instrument names (round 10) -----------------
#
# The in-kernel health word's host-side counters (resilience/health.py
# RunSupervisor emits them; per-kind series via health_event_metric):
#:  nonzero in-kernel health words the supervisor acted on (all kinds)
HEALTH_EVENTS_TOTAL = "pyabc_tpu_health_events_total"
#:  fused chunks aborted + rolled back (checkpoint / last-good carry /
#:  host rebuild) by the health supervisor
CHUNK_ROLLBACKS_TOTAL = "pyabc_tpu_health_chunk_rollbacks_total"
#:  proposal-bandwidth widenings applied on ESS/acceptance collapse
PROPOSAL_WIDENINGS_TOTAL = "pyabc_tpu_health_proposal_widenings_total"
#:  runs terminated with a typed DegenerateRunError (health trail attached)
DEGENERATE_RUNS_TOTAL = "pyabc_tpu_degenerate_runs_total"

# -- dispatch-engine instrument names (round 12) ------------------------------
#
# The single async dispatch engine (inference/dispatch.py) owns every
# device round trip of a fused run and exports its two invariants:
#:  blocking device round trips of the last completed run — the engine's
#:  budget is `chunks + O(1)`, regression-guarded by the bench
#:  `dispatch` lane
SYNCS_PER_RUN_GAUGE = "pyabc_tpu_syncs_per_run"
#:  speculative chunks rolled back unpersisted (dispatched past a
#:  stopping-rule hit or discarded with a health-degraded carry)
SPECULATIVE_ROLLBACKS_TOTAL = "pyabc_tpu_speculative_rollbacks_total"

# Sharded fused sampling (ISSUE 9): the dispatch engine's mesh gauges.
#:  devices of the mesh the sharded multigen kernel runs on (1 when the
#:  run is unsharded)
MESH_DEVICES_GAUGE = "pyabc_tpu_mesh_devices"
#:  per-shard work imbalance of the last processed chunk: max over
#:  shards of proposal rounds worked, divided by the mean — 1.0 is a
#:  perfectly balanced mesh; the bench `mesh` lane records it
MESH_IMBALANCE_GAUGE = "pyabc_tpu_mesh_shard_imbalance"
#:  busiest-shard share of total mesh rounds in the last processed
#:  chunk (1/n_devices when perfectly balanced)
MESH_BUSY_MAX_GAUGE = "pyabc_tpu_mesh_shard_busy_max_frac"
#:  cross-shard ROW collectives of sharded runs (per-chunk packed-fetch
#:  merge gathers + in-kernel cadence-refit theta all-gathers) — the gap
#:  accounting's view of what actually crosses the mesh beyond the
#:  per-generation scalar columns (round 16: adaptive sharded configs)
MESH_ROW_COLLECTIVES_TOTAL = "pyabc_tpu_mesh_row_collectives_total"
#:  per-generation cross-shard payload of the adaptive scale reduction +
#:  stochastic record-column gathers (bytes; 0 for non-adaptive configs)
MESH_SCALE_BYTES_GAUGE = "pyabc_tpu_mesh_scale_reduction_bytes_per_gen"

# Segmented early-reject execution (ISSUE 15): both instruments ride
# the packed fetch (four int32 per generation — zero extra syncs,
# SyncLedger-asserted under the strict budget).
#:  vector lanes retired between segments because the distance's
#:  monotone prefix bound already exceeded the generation threshold —
#:  each retirement is a provably-rejected trajectory whose remaining
#:  segments were never paid for
SIM_LANES_RETIRED_TOTAL = "pyabc_tpu_sim_lanes_retired_early_total"
#:  productive segment-step share of the last chunk's lane sweeps
#:  (seg_steps / (B * sweeps)); the shortfall is drain/refill idle time
SIM_SEGMENT_OCCUPANCY_GAUGE = "pyabc_tpu_sim_segment_occupancy"
#:  per-shard early-reject imbalance of the last processed chunk
#:  (max over shards of lanes retired / mean; 1.0 = evenly spread
#:  rejection) — sits next to pyabc_tpu_mesh_shard_imbalance so a
#:  bound that fires on one shard's lane block is visible (ISSUE 17:
#:  the composed sharded+segmented kernel)
SIM_RETIRE_IMBALANCE_GAUGE = "pyabc_tpu_sim_retire_shard_imbalance"

# -- device-native learned summary statistics (ISSUE 20) ----------------------
#
# Fearnhead-Prangle transforms fit IN-KERNEL at chunk boundaries under
# a device-fit plan; the instruments make the fit cadence and the
# raw-S -> learned-C' fetch compression observable per run:
#:  in-kernel boundary refits of the learned-sumstat predictor (the
#:  host mirror bumps this when the kernel's fit predicate fired)
SUMSTAT_REFITS_TOTAL = "pyabc_tpu_sumstat_refits_total"
#:  raw summary-statistic dimension S of the learned-sumstat run
SUMSTAT_DIM_GAUGE = "pyabc_tpu_sumstat_dim"
#:  learned feature dimension C' the packed fetch ships per particle
#:  (the S -> C' ratio IS the fetch-bytes reduction of the transform)
SUMSTAT_DIM_REDUCED_GAUGE = "pyabc_tpu_sumstat_dim_reduced"

# -- capability-gate fallback accounting (ISSUE 17) ---------------------------
#
# When early_reject="auto" or an implicit mesh-width shard resolution
# falls back to a slower serving path, the fallback used to be a log
# line only. Operators watching a fleet need it as a counter:
#:  total silent capability-gate fallbacks (all gates); the per-gate
#:  breakdown rides name suffixes (capability_fallback_metric), the
#:  full reason strings land in History telemetry and
#:  /api/observability
CAPABILITY_FALLBACKS_TOTAL = "pyabc_tpu_capability_fallbacks_total"


def capability_fallback_metric(gate: str) -> str:
    """Per-gate fallback counter name — the registry's stand-in for
    ``pyabc_tpu_capability_fallbacks_total{reason=...}`` (the text
    exposition has no label support; cardinality is bounded by the
    fixed gate set: early_reject, sharded)."""
    g = "".join(c if c.isalnum() or c == "_" else "_" for c in str(gate))
    return f"{CAPABILITY_FALLBACKS_TOTAL}_{g}"


# -- multi-tenant serving instrument names (round 14) -------------------------
#
# The RunScheduler/AdmissionController gauges and counters; one
# canonical place so the scheduler, serve API, bench `serve` lane and
# dashboard agree:
#:  tenants currently holding a device slot (running)
TENANTS_LIVE_GAUGE = "pyabc_tpu_tenant_live"
#:  tenants admitted and waiting for a device slot
TENANTS_QUEUED_GAUGE = "pyabc_tpu_tenant_queued"
#:  submissions admitted (queued or started)
TENANT_ADMISSIONS_TOTAL = "pyabc_tpu_tenant_admissions_total"
#:  submissions rejected with typed backpressure (AdmissionRejectedError
#:  + Retry-After) instead of unbounded queueing
TENANT_REJECTIONS_TOTAL = "pyabc_tpu_tenant_admission_rejected_total"
#:  run leases reaped (orchestrator thread dead or hung past the lease
#:  timeout) with the tenant requeued from its checkpoint
TENANT_REQUEUES_TOTAL = "pyabc_tpu_tenant_requeues_total"
#:  tenants that finished with a posterior (the happy path)
TENANT_COMPLETED_TOTAL = "pyabc_tpu_tenant_completed_total"
#:  tenants that failed terminally (requeue budget exhausted, degenerate
#:  run, unhandled orchestrator error)
TENANT_FAILURES_TOTAL = "pyabc_tpu_tenant_failures_total"
#:  tenants drained gracefully (flush + final checkpoint) on SIGTERM
TENANT_DRAINS_TOTAL = "pyabc_tpu_tenant_drains_total"
#:  shape-keyed kernel-cache hits (tenant paid zero compile) / misses
TENANT_KERNEL_CACHE_HITS_TOTAL = "pyabc_tpu_tenant_kernel_cache_hits_total"
TENANT_KERNEL_CACHE_MISSES_TOTAL = \
    "pyabc_tpu_tenant_kernel_cache_misses_total"

# -- mesh-aware serving instrument names (round 15) ---------------------------
#
# Sub-mesh placement, checkpoint-preemption and device-loss survival:
#:  healthy devices in the serving pool (shrinks on device_lost)
SUBMESH_DEVICES_HEALTHY_GAUGE = "pyabc_tpu_submesh_devices_healthy"
#:  devices currently in free blocks (allocatable capacity)
SUBMESH_DEVICES_FREE_GAUGE = "pyabc_tpu_submesh_devices_free"
#:  widest contiguous sub-mesh allocatable right now (fragmentation
#:  signal: healthy-free high but widest low = drain candidates exist)
SUBMESH_WIDEST_FREE_GAUGE = "pyabc_tpu_submesh_widest_free"
#:  tenants checkpoint-preempted at a chunk boundary and requeued (to
#:  drain fragmentation or admit latency-sensitive small tenants)
TENANT_PREEMPTIONS_TOTAL = "pyabc_tpu_tenant_preemptions_total"
#:  devices marked lost (hard mesh loss — capacity shrunk, leases reaped)
DEVICES_LOST_TOTAL = "pyabc_tpu_devices_lost_total"
#:  whole hosts marked lost (round 18 fleets — the host's entire
#:  allocator segment quarantined, every lease on it reaped, admission
#:  repriced on the surviving fleet)
HOSTS_LOST_TOTAL = "pyabc_tpu_hosts_lost_total"
#:  tenants requeued because their sub-mesh lost a device (infrastructure
#:  fault: does NOT consume the tenant's own requeue budget)
TENANT_DEVICE_LOSS_REQUEUES_TOTAL = \
    "pyabc_tpu_tenant_device_loss_requeues_total"

# -- History storage instrument names (round 17) ------------------------------
#
# The columnar generation-batch backend's ingest accounting; one
# canonical place so History, the serve API and the bench `storage`
# lane agree:
#:  accepted particles persisted per second by the LAST append (row or
#:  columnar store; measured on the thread that executed the write, so
#:  with an async writer it reflects true ingest, not queue time)
HISTORY_INGEST_ROWS_PER_SEC_GAUGE = "pyabc_tpu_history_ingest_rows_per_sec"
#:  bytes on disk attributable to this History's current run after the
#:  last append (columnar: sum of the run's generation files; rows:
#:  sqlite main db + WAL)
HISTORY_BYTES_ON_DISK_GAUGE = "pyabc_tpu_history_bytes_on_disk"

# -- traffic / lifecycle instrument names (round 19) --------------------------
#
# The fleet-scale traffic subsystem (open-loop generator) and tenant
# lifecycle layer (retention/GC/quotas); one canonical place so the
# scheduler, lifecycle manager, traffic generator, serve API and the
# bench `traffic` lane agree:
#:  bytes on disk attributable to one tenant's History (sqlite db + WAL
#:  + columnar generation files + archive); set in the tenant's PRIVATE
#:  registry, so /metrics renders it with a {tenant="<id>"} label
TENANT_BYTES_ON_DISK_GAUGE = "pyabc_tpu_tenant_bytes_on_disk"
#:  generations deleted by lifecycle retention sweeps (keep-last-k /
#:  TTL / eviction GC), SQL rows and columnar Parquet files both
TENANT_GENERATIONS_GCED_TOTAL = "pyabc_tpu_tenant_generations_gced_total"
#:  terminal tenants whose History was packed into a tar.gz archive
TENANT_ARCHIVES_TOTAL = "pyabc_tpu_tenant_archives_total"
#:  submissions refused because the TENANT QUOTA (chip-seconds, bytes
#:  on disk, generations) was exhausted — distinct from queue-full 429s
TENANT_QUOTA_REJECTIONS_TOTAL = "pyabc_tpu_tenant_quota_rejected_total"
#:  open-loop arrivals the traffic generator submitted (admitted or not)
TRAFFIC_ARRIVALS_TOTAL = "pyabc_tpu_traffic_arrivals_total"
#:  arrivals refused with typed backpressure (429 + Retry-After)
TRAFFIC_REJECTIONS_TOTAL = "pyabc_tpu_traffic_rejections_total"
#:  submit -> posterior-complete latency of finished tenants (the
#:  histogram's summary() carries the p50/p99 the bench lane guards)
TIME_TO_POSTERIOR_HISTOGRAM = "pyabc_tpu_time_to_posterior_seconds"

# -- SLO / flight-recorder instrument names (round 22) ------------------------
#
# The burn-rate engine (observability/slo.py) and the crash-safe flight
# recorder (observability/recorder.py); one canonical place so the
# scheduler, traffic generator, serve API and the bench `slo` leg agree:
#:  wall seconds spent INSIDE RunScheduler.submit() per admitted
#:  arrival, observed scheduler-side (the traffic generator's view adds
#:  client retry waits; this is the fleet's own admission-latency SLI)
ADMISSION_LATENCY_HISTOGRAM = "pyabc_tpu_admission_latency_seconds"
#:  observed_wait / first_hint per 429-rejected-then-admitted arrival —
#:  the Retry-After honesty ratio, observed by the traffic generator
#:  (1.0 = the hint priced the queue exactly)
RETRY_HONESTY_HISTOGRAM = "pyabc_tpu_retry_after_honesty_ratio"
#:  flight files persisted by fault-path dumps (all tenants, all causes)
FLIGHT_DUMPS_TOTAL = "pyabc_tpu_flight_dumps_total"
#:  remote span batches merged by the primary's federation sink
FEDERATED_SPAN_BATCHES_TOTAL = "pyabc_tpu_federated_span_batches_total"
#:  remote spans merged onto host:<p> pseudo-threads (offset-corrected)
FEDERATED_SPANS_TOTAL = "pyabc_tpu_federated_spans_total"


def slo_metric(slo_name: str, which: str) -> str:
    """A per-SLO gauge name: ``pyabc_tpu_slo_<slo>_<which>`` with the
    SLO name sanitized to Prometheus charset — the registry's stand-in
    for ``pyabc_tpu_slo_*{slo=...}`` labels. ``which`` is one of
    ``burn_fast`` / ``burn_slow`` / ``alerting`` / ``bad_fraction``;
    cardinality is bounded by the declared SLO set."""
    s = "".join(c if c.isalnum() or c == "_" else "_" for c in str(slo_name))
    return f"pyabc_tpu_slo_{s}_{which}"


def health_event_metric(kind: str) -> str:
    """Per-kind health-event counter name — the registry's stand-in for
    ``pyabc_tpu_health_events_total{kind=...}`` (the text exposition has
    no label support; cardinality is bounded by the fixed bit set)."""
    k = "".join(c if c.isalnum() or c == "_" else "_" for c in str(kind))
    return f"{HEALTH_EVENTS_TOTAL}_{k}"


def per_worker_metric(base: str, worker_id: str) -> str:
    """A per-worker instrument name: ``base`` suffixed with the worker id
    sanitized to Prometheus charset (worker ids carry hostnames/uuids).
    Cardinality is bounded by the pool size, which is small by design."""
    wid = "".join(c if c.isalnum() or c == "_" else "_"
                  for c in str(worker_id))
    return f"{base}_{wid}"


class MetricsRegistry:
    """Named instruments; get-or-create semantics, thread-safe."""

    enabled = True

    def __init__(self, clock: Clock | None = None):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self._lock = threading.Lock()
        self._instruments: dict[str, Counter | Gauge | Histogram] = {}  # abc-lint: guarded-by=_lock

    def _get(self, name: str, cls, help: str, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, **kwargs)
                self._instruments[name] = inst
            elif type(inst) is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(name, Histogram, help, clock=self.clock)

    def instruments(self) -> list:
        with self._lock:
            return list(self._instruments.values())

    def snapshot(self) -> dict:
        """{name: value-or-summary} — the in-process read API."""
        out = {}
        for inst in self.instruments():
            if isinstance(inst, Histogram):
                out[inst.name] = inst.summary()
            else:
                out[inst.name] = inst.value
        return out


class _NullInstrument:
    """Shared inert counter/gauge/histogram-timer hybrid."""

    __slots__ = ()
    name = ""
    help = ""
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def time(self) -> "_NullInstrument":
        return self

    def __enter__(self) -> "_NullInstrument":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def summary(self) -> dict:
        return {"count": 0, "sum": 0.0, "min": None, "max": None,
                "mean": None, "p50": None, "p90": None, "p99": None}

    def snapshot(self) -> dict:
        return {"buckets": [], "count": 0, "sum": 0.0,
                "min": float("inf"), "max": float("-inf")}

    def quantile(self, q: float, snap: dict | None = None) -> float:
        return float("nan")


_NULL_INSTRUMENT = _NullInstrument()


class NullMetrics:
    """Disabled registry: every instrument is the shared inert object."""

    enabled = False

    def __init__(self, clock: Clock | None = None):
        self.clock = clock if clock is not None else SYSTEM_CLOCK

    def counter(self, name: str, help: str = "") -> _NullInstrument:
        return _NULL_INSTRUMENT

    def gauge(self, name: str, help: str = "") -> _NullInstrument:
        return _NULL_INSTRUMENT

    def histogram(self, name: str, help: str = "") -> _NullInstrument:
        return _NULL_INSTRUMENT

    def instruments(self) -> list:
        return []

    def snapshot(self) -> dict:
        return {}


#: process-wide default disabled registry
NULL_METRICS = NullMetrics()
