"""Crash-safe flight recorder: the per-tenant black box.

When a tenant dies hard — ``DegenerateRunError``, lease reap, device or
host loss, a fatal writer-pool error, a SIGTERM drain — its in-memory
trace used to die with it. The :class:`FlightRecorder` keeps a bounded
ring of recent context (recorder notes, the span tail, metric deltas
against an armed baseline, tenant lifecycle events, the measured
cross-host clock table and the federated span tail) on the injected
clock, and persists it ATOMICALLY on every fault path: serialized as
JSON, CRC-framed exactly like the PR-5 checkpoint header (magic |
version | crc32 | length, little-endian), written tmp + flush + fsync +
rename so a crash mid-dump leaves either the previous flight file or a
complete new one — never a torn read for the postmortem.

JSON (not pickle) on purpose: a flight file must be parseable by
``abc-manager --postmortem`` and by humans under incident pressure,
with no import of the writing process's class graph.

Same design rules as the rest of the subsystem: stdlib-only, host-side,
injected clock only (CLOCK001), and dump-never-raises — a recorder
failure on a fault path must not mask the fault being recorded.
"""
from __future__ import annotations

import json
import logging
import os
import struct
import threading
import zlib

from .clock import Clock, SYSTEM_CLOCK

logger = logging.getLogger("pyabc_tpu.observability.recorder")

#: flight-file magic — distinct from the checkpoint's ``PTCK`` so a
#: mixed-up path fails loudly with a typed error, not a bad unpickle
FLIGHT_MAGIC = b"PTFR"
FLIGHT_VERSION = 1

# same frame as resilience/checkpoint.py: magic | schema version |
# payload crc32 | payload length, little-endian, 20 bytes
_HEADER = struct.Struct("<4sIIQ")


class FlightCorruptError(RuntimeError):
    """A flight file failed validation (bad magic/version/length/CRC)."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt flight file {path!r}: {reason}")
        self.path = str(path)
        self.reason = reason


def write_flight(path: str, payload: dict) -> int:
    """Atomically persist ``payload`` as a CRC-framed flight file.

    tmp + flush + fsync + rename: the destination is never observable
    half-written. Returns the total bytes written."""
    blob = json.dumps(payload, default=str).encode("utf-8")
    header = _HEADER.pack(FLIGHT_MAGIC, FLIGHT_VERSION,
                          zlib.crc32(blob) & 0xFFFFFFFF, len(blob))
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        fh.write(header)
        fh.write(blob)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return _HEADER.size + len(blob)


def read_flight(path: str) -> dict:
    """Load + validate a flight file; raises :class:`FlightCorruptError`
    with the FIRST failing check (magic -> version -> length -> CRC ->
    decode), mirroring the checkpoint loader's order."""
    with open(path, "rb") as fh:
        data = fh.read()
    if len(data) < _HEADER.size:
        raise FlightCorruptError(
            path, f"truncated header ({len(data)} < {_HEADER.size} bytes)")
    magic, version, crc, length = _HEADER.unpack_from(data)
    if magic != FLIGHT_MAGIC:
        raise FlightCorruptError(path, f"bad magic {magic!r}")
    if version != FLIGHT_VERSION:
        raise FlightCorruptError(
            path, f"unsupported flight version {version}")
    blob = data[_HEADER.size:]
    if len(blob) != length:
        raise FlightCorruptError(
            path, f"payload length {len(blob)} != header {length}")
    if zlib.crc32(blob) & 0xFFFFFFFF != crc:
        raise FlightCorruptError(path, "payload CRC mismatch")
    try:
        payload = json.loads(blob)
    except json.JSONDecodeError as err:
        raise FlightCorruptError(path, f"JSON decode failed: {err}") from err
    if not isinstance(payload, dict):
        raise FlightCorruptError(
            path, f"payload is {type(payload).__name__}, not an object")
    return payload


class FlightRecorder:
    """Bounded black box for one tenant/run.

    ``note(kind, **attrs)`` appends a timestamped entry to the ring
    (oldest dropped beyond ``max_entries``); ``arm`` attaches the live
    sources a snapshot gathers from — the tenant's tracer (span tail),
    metrics registry (baseline captured at arm time so the snapshot
    carries DELTAS, not lifetime totals), and a lifecycle-events
    callable. ``dump`` persists the snapshot via :func:`write_flight`
    and NEVER raises: it is called from fault paths where a secondary
    failure must not mask the primary one.
    """

    def __init__(self, run_id: str, *, clock: Clock | None = None,
                 path: str | None = None, max_entries: int = 512,
                 max_spans: int = 256):
        self.run_id = str(run_id)
        self.path = path
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        self._lock = threading.Lock()
        self._max_entries = int(max_entries)
        self._max_spans = int(max_spans)
        self._entries: list[dict] = []  # abc-lint: guarded-by=_lock
        self._n_dropped = 0  # abc-lint: guarded-by=_lock
        self._tracer = None
        self._metrics = None
        self._events_fn = None
        self._baseline: dict = {}
        self.n_dumps = 0

    # ------------------------------------------------------------------ arm
    def arm(self, *, tracer=None, metrics=None, events_fn=None) -> None:
        """Attach live sources; captures the metrics baseline so later
        snapshots report deltas over the recorder's lifetime."""
        with self._lock:
            if tracer is not None:
                self._tracer = tracer
            if metrics is not None:
                self._metrics = metrics
                self._baseline = _numeric_view(metrics.snapshot())
            if events_fn is not None:
                self._events_fn = events_fn

    # ----------------------------------------------------------------- ring
    def note(self, kind: str, **attrs) -> None:
        """Append one timestamped entry (lease/chunk events, health
        words, capability fallbacks, fault-path breadcrumbs)."""
        entry = {"ts": self._clock.now(), "wall": self._clock.wall(),
                 "kind": str(kind)}
        entry.update(attrs)
        with self._lock:
            self._entries.append(entry)
            if len(self._entries) > self._max_entries:
                drop = len(self._entries) - self._max_entries
                del self._entries[:drop]
                self._n_dropped += drop

    # ------------------------------------------------------------- snapshot
    def snapshot(self, *, reason: str = "on_demand") -> dict:
        """The full black-box payload, JSON-ready."""
        # lazy import: the package __init__ imports this module
        from . import federated_spans_snapshot, host_clocks_snapshot
        with self._lock:
            entries = list(self._entries)
            n_dropped = self._n_dropped
            tracer, metrics, events_fn = (
                self._tracer, self._metrics, self._events_fn)
            baseline = dict(self._baseline)
        spans: list[dict] = []
        if tracer is not None:
            # host:<p> pseudo-thread spans ride the federated block —
            # skipping them here keeps the timeline free of duplicates
            # when the tracer also mirrors the federation merge
            tail = [sp for sp in tracer.spans()
                    if not str(sp.thread).startswith("host:")]
            spans = [sp.to_dict() for sp in tail[-self._max_spans:]]
        current: dict = {}
        deltas: dict = {}
        if metrics is not None:
            current = _numeric_view(metrics.snapshot())
            deltas = {k: round(v - baseline.get(k, 0.0), 9)
                      for k, v in current.items()
                      if v != baseline.get(k, 0.0)}
        events: list = []
        if events_fn is not None:
            try:
                events = list(events_fn())
            except Exception as err:
                events = [{"kind": "flight.events_source_error",
                           "error": repr(err)[:200]}]
        return {
            "flight_version": FLIGHT_VERSION,
            "run_id": self.run_id,
            "reason": reason,
            "ts": self._clock.now(),
            "wall": self._clock.wall(),
            "entries": entries,
            "entries_dropped": n_dropped,
            "spans": spans,
            "metrics": {"baseline": baseline, "current": current,
                        "deltas": deltas},
            "events": events,
            "hosts": host_clocks_snapshot(),
            "federated_spans": federated_spans_snapshot()[-self._max_spans:],
        }

    # ----------------------------------------------------------------- dump
    def dump(self, path: str | None = None, *,
             reason: str = "fault") -> str | None:
        """Persist the snapshot; returns the path, or None on failure.

        Never raises — a broken disk during a host-loss dump must not
        turn the fault path into a crash loop. Failures are logged and
        visible as ``flight.dump_error`` notes on the next snapshot."""
        target = path or self.path
        if target is None:
            return None
        try:
            write_flight(target, self.snapshot(reason=reason))
            self.n_dumps += 1
            return target
        except Exception as err:
            logger.warning("flight dump to %s failed: %r", target, err)
            self.note("flight.dump_error", path=str(target),
                      error=repr(err)[:200])
            return None


def _numeric_view(snapshot: dict) -> dict:
    """Flatten a MetricsRegistry.snapshot() to {name: float} — histogram
    summaries contribute their count/sum so deltas stay meaningful."""
    out: dict[str, float] = {}
    for name, val in snapshot.items():
        if isinstance(val, dict):
            out[f"{name}_count"] = float(val.get("count") or 0)
            out[f"{name}_sum"] = float(val.get("sum") or 0.0)
        else:
            try:
                out[name] = float(val)
            except (TypeError, ValueError):
                continue
    return out


# -------------------------------------------------------------- postmortem
def render_timeline(payload: dict) -> str:
    """Render a flight payload into the offset-corrected postmortem
    timeline ``abc-manager --postmortem`` prints.

    Spans (local and ``host:<p>`` federated — the latter were mapped
    onto the primary's timebase at ingest via the measured clock
    offsets), recorder entries and tenant lifecycle events merge into
    one chronological listing with times relative to the earliest
    timestamp; the host-clock table prints the offset ± uncertainty
    each remote span was corrected with."""
    rows: list[tuple[float, str]] = []

    def _fmt_attrs(attrs: dict) -> str:
        if not attrs:
            return ""
        body = " ".join(f"{k}={v}" for k, v in list(attrs.items())[:8])
        return body if len(body) <= 100 else body[:97] + "..."

    for sp in list(payload.get("spans") or []) + \
            list(payload.get("federated_spans") or []):
        start = float(sp.get("start") or 0.0)
        end = sp.get("end")
        dur = (float(end) - start) if end is not None else 0.0
        rows.append((start, "span  %-14s %-28s %8.3fs  %s" % (
            f"[{sp.get('thread', '')}]", sp.get("name", ""), dur,
            _fmt_attrs(sp.get("attrs") or {}))))
    for ent in payload.get("entries") or []:
        ts = float(ent.get("ts") or 0.0)
        attrs = {k: v for k, v in ent.items()
                 if k not in ("ts", "wall", "kind")}
        rows.append((ts, "note  %-14s %-28s           %s" % (
            "[recorder]", ent.get("kind", ""), _fmt_attrs(attrs))))
    for ev in payload.get("events") or []:
        if not isinstance(ev, dict):
            continue
        ts = float(ev.get("ts") or 0.0)
        attrs = {k: v for k, v in ev.items()
                 if k not in ("ts", "wall", "kind", "seq")}
        rows.append((ts, "event %-14s %-28s           %s" % (
            "[tenant]", ev.get("kind", ""), _fmt_attrs(attrs))))
    rows.sort(key=lambda r: r[0])
    t0 = rows[0][0] if rows else 0.0

    lines = [
        "flight recorder · run %s · reason=%s · dumped %s" % (
            payload.get("run_id", "?"), payload.get("reason", "?"),
            payload.get("wall", "?")),
    ]
    hosts = payload.get("hosts") or {}
    for host, summ in sorted(hosts.items()):
        if isinstance(summ, dict):
            lines.append(
                "host clock %-18s offset=%+.6fs ±%.6fs" % (
                    str(host),
                    float(summ.get("offset_s") or 0.0),
                    float(summ.get("uncertainty_s") or 0.0)))
    deltas = (payload.get("metrics") or {}).get("deltas") or {}
    if deltas:
        lines.append("metric deltas since arm: " + ", ".join(
            f"{k}={v:g}" for k, v in sorted(deltas.items())[:12]))
    dropped = payload.get("entries_dropped") or 0
    if dropped:
        lines.append(f"({dropped} oldest recorder entries dropped)")
    lines.append("")
    for ts, body in rows:
        lines.append("%+10.3fs  %s" % (ts - t0, body.rstrip()))
    return "\n".join(lines) + "\n"
