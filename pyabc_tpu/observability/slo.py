"""Declarative SLOs with multi-window burn-rate alerting.

The traffic lane's one-shot bench guards (admission p99, Retry-After
honesty, time-to-posterior) become LIVE fleet signals: an
:class:`SLO` declares a per-traffic-class objective over instruments
that already exist in a :class:`~pyabc_tpu.observability.metrics.
MetricsRegistry`, and the :class:`SloEngine` samples the cumulative
good/total counts on the injected clock, computes windowed bad-event
fractions, and alerts on the classic multi-window burn-rate rule: the
error budget must be burning FAST on both a short and a long window
(fast pair 5m/1h at 14.4x budget, slow pair 6h/3d at 6x) before the
alert fires — transient spikes on the short window alone don't page,
and a sustained slow leak still does.

Three SLI shapes, all read from cumulative instruments (no per-request
bookkeeping):

- **histogram threshold** — good = observations at or under
  ``threshold`` (the cumulative log2-bucket count at the last edge not
  above it, via ``Histogram.snapshot()`` — conservative: a straddling
  bucket counts bad);
- **good/total counters** — e.g. availability = completed / admitted;
- **good/bad counters** — total = good + bad, e.g. admission
  availability = admitted / (admitted + rejected).

Everything is host-side, stdlib-only, and injected-clock-disciplined
like the rest of the subsystem; state is exported as ``pyabc_tpu_slo_*``
gauges plus the ``slo`` block of ``/api/observability``.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from .clock import Clock, SYSTEM_CLOCK
from .metrics import (
    ADMISSION_LATENCY_HISTOGRAM,
    Histogram,
    RETRY_HONESTY_HISTOGRAM,
    TENANT_ADMISSIONS_TOTAL,
    TENANT_COMPLETED_TOTAL,
    TENANT_REJECTIONS_TOTAL,
    TIME_TO_POSTERIOR_HISTOGRAM,
    slo_metric,
)

#: multi-window burn-rate pairs (seconds) + thresholds — the standard
#: fast-page / slow-ticket split: 14.4x burn on 5m AND 1h consumes 2%
#: of a 30-day budget in an hour; 6x on 6h AND 3d is the slow leak
FAST_WINDOWS_S = (300.0, 3600.0)
FAST_BURN_THRESHOLD = 14.4
SLOW_WINDOWS_S = (21600.0, 259200.0)
SLOW_BURN_THRESHOLD = 6.0


@dataclass(frozen=True)
class SLO:
    """One declarative objective over existing instruments.

    Exactly one SLI shape must be configured: ``histogram`` +
    ``threshold``, ``good_counter`` + ``total_counter``, or
    ``good_counter`` + ``bad_counter``. ``objective`` is the target
    good fraction (0.99 = 1% error budget)."""

    name: str
    objective: float
    traffic_class: str = "*"
    histogram: str | None = None
    threshold: float | None = None
    good_counter: str | None = None
    total_counter: str | None = None
    bad_counter: str | None = None
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: objective must be in (0, 1), "
                f"got {self.objective}")
        hist = self.histogram is not None
        ratio = self.good_counter is not None and (
            (self.total_counter is not None)
            != (self.bad_counter is not None))
        if hist == ratio or (hist and self.threshold is None):
            raise ValueError(
                f"SLO {self.name!r}: configure exactly one SLI shape — "
                "histogram+threshold, good_counter+total_counter, or "
                "good_counter+bad_counter")

    @property
    def budget(self) -> float:
        """The error budget: the tolerated bad fraction."""
        return 1.0 - self.objective


def default_slos() -> list[SLO]:
    """The fleet's standing objectives over the serving instruments
    (schedulers may pass their own list to tighten/replace them)."""
    return [
        SLO(name="admission_latency", objective=0.99,
            histogram=ADMISSION_LATENCY_HISTOGRAM, threshold=2.0,
            description="scheduler-side submit() wall under 2s"),
        SLO(name="admission_availability", objective=0.99,
            good_counter=TENANT_ADMISSIONS_TOTAL,
            bad_counter=TENANT_REJECTIONS_TOTAL,
            description="arrivals admitted vs 429-rejected"),
        SLO(name="availability", objective=0.90,
            good_counter=TENANT_COMPLETED_TOTAL,
            total_counter=TENANT_ADMISSIONS_TOTAL,
            description="admitted tenants that reach a posterior"),
        SLO(name="time_to_posterior", objective=0.90,
            histogram=TIME_TO_POSTERIOR_HISTOGRAM, threshold=600.0,
            description="submit -> posterior under 10 minutes"),
        SLO(name="retry_honesty", objective=0.90,
            histogram=RETRY_HONESTY_HISTOGRAM, threshold=10.0,
            description="Retry-After hints within 10x of observed wait"),
    ]


@dataclass
class _Sample:
    ts: float
    good: float
    total: float


@dataclass
class _SloState:
    slo: SLO
    samples: list = field(default_factory=list)


class SloEngine:
    """Samples cumulative SLIs into bounded rings and evaluates the
    multi-window burn-rate rule on the injected clock.

    ``sample()`` is called opportunistically from the scheduler's pump
    tick (throttled to ``sample_interval_s``); ``snapshot()`` serves
    the live state to ``/api/observability`` and the bench; gauges
    (``pyabc_tpu_slo_<name>_{burn_fast,burn_slow,alerting,
    bad_fraction}``) are refreshed on every accepted sample so a plain
    Prometheus scrape sees the burn state without calling the API."""

    def __init__(self, metrics, *, slos: list[SLO] | None = None,
                 clock: Clock | None = None,
                 sample_interval_s: float = 10.0,
                 max_samples: int = 4096, register: bool = True):
        self._metrics = metrics
        self._clock = clock if clock is not None else getattr(
            metrics, "clock", SYSTEM_CLOCK)
        self._interval = float(sample_interval_s)
        self._max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._states = {s.name: _SloState(slo=s)  # abc-lint: guarded-by=_lock
                        for s in (slos if slos is not None
                                  else default_slos())}
        self._last_ts: float | None = None  # abc-lint: guarded-by=_lock
        if register:
            from . import register_slo_source
            register_slo_source(self)

    @property
    def slos(self) -> list[SLO]:
        with self._lock:
            return [st.slo for st in self._states.values()]

    # ------------------------------------------------------------- sampling
    def _measure(self, slo: SLO) -> tuple[float, float]:
        """Cumulative (good, total) for one SLO, right now."""
        if slo.histogram is not None:
            hist = self._metrics.histogram(slo.histogram)
            if not isinstance(hist, Histogram):
                return 0.0, 0.0
            snap = hist.snapshot()
            good = 0
            for edge, n in zip(hist.bucket_bounds(), snap["buckets"][:-1]):
                if edge > slo.threshold:
                    break
                good += n
            return float(good), float(snap["count"])
        good = float(self._metrics.counter(slo.good_counter).value)
        if slo.total_counter is not None:
            total = float(self._metrics.counter(slo.total_counter).value)
        else:
            total = good + float(
                self._metrics.counter(slo.bad_counter).value)
        return good, total

    def sample(self, force: bool = False) -> bool:
        """Take one sample of every SLI if ``sample_interval_s`` has
        elapsed (or ``force``); returns whether a sample was taken."""
        now = self._clock.now()
        with self._lock:
            if (not force and self._last_ts is not None
                    and now - self._last_ts < self._interval):
                return False
            self._last_ts = now
            states = list(self._states.values())
        for st in states:
            good, total = self._measure(st.slo)
            with self._lock:
                st.samples.append(_Sample(ts=now, good=good, total=total))
                if len(st.samples) > self._max_samples:
                    del st.samples[:len(st.samples) - self._max_samples]
            self._export_gauges(st, now)
        return True

    # ----------------------------------------------------------- evaluation
    @staticmethod
    def _window_delta(samples: list, now: float,
                      window_s: float) -> tuple[float, float]:
        """(bad, total) events inside the trailing window: latest sample
        minus the newest sample at or before ``now - window_s`` (the
        oldest available when the ring doesn't reach back that far —
        the standard cold-start approximation)."""
        if not samples:
            return 0.0, 0.0
        latest = samples[-1]
        cutoff = now - window_s
        base = samples[0]
        for s in samples:
            if s.ts <= cutoff:
                base = s
            else:
                break
        d_total = latest.total - base.total
        d_good = latest.good - base.good
        if d_total <= 0.0:
            return 0.0, 0.0
        return max(0.0, d_total - d_good), d_total

    def _burn(self, st: _SloState, now: float, window_s: float) -> float:
        with self._lock:
            samples = list(st.samples)
        bad, total = self._window_delta(samples, now, window_s)
        if total <= 0.0:
            return 0.0
        return (bad / total) / st.slo.budget

    def _evaluate(self, st: _SloState, now: float) -> dict:
        slo = st.slo
        burns = {w: self._burn(st, now, w)
                 for w in (*FAST_WINDOWS_S, *SLOW_WINDOWS_S)}
        burn_fast = min(burns[w] for w in FAST_WINDOWS_S)
        burn_slow = min(burns[w] for w in SLOW_WINDOWS_S)
        alerting_fast = burn_fast > FAST_BURN_THRESHOLD
        alerting_slow = burn_slow > SLOW_BURN_THRESHOLD
        with self._lock:
            latest = st.samples[-1] if st.samples else None
        good = latest.good if latest else 0.0
        total = latest.total if latest else 0.0
        bad_fraction = (1.0 - good / total) if total > 0 else 0.0
        return {
            "name": slo.name,
            "traffic_class": slo.traffic_class,
            "objective": slo.objective,
            "description": slo.description,
            "good": good,
            "total": total,
            "bad_fraction": round(bad_fraction, 9),
            "burn": {f"{int(w)}s": round(burns[w], 6) for w in burns},
            "burn_fast": round(burn_fast, 6),
            "burn_slow": round(burn_slow, 6),
            "alerting_fast": alerting_fast,
            "alerting_slow": alerting_slow,
            "alerting": alerting_fast or alerting_slow,
        }

    def _export_gauges(self, st: _SloState, now: float) -> None:
        ev = self._evaluate(st, now)
        name = st.slo.name
        reg = self._metrics
        reg.gauge(slo_metric(name, "burn_fast")).set(ev["burn_fast"])
        reg.gauge(slo_metric(name, "burn_slow")).set(ev["burn_slow"])
        reg.gauge(slo_metric(name, "alerting")).set(
            1.0 if ev["alerting"] else 0.0)
        reg.gauge(slo_metric(name, "bad_fraction")).set(ev["bad_fraction"])

    def evaluate(self, name: str) -> dict:
        """Burn-rate evaluation of one SLO, at the current clock."""
        with self._lock:
            st = self._states[name]
        return self._evaluate(st, self._clock.now())

    def alerting(self, name: str | None = None) -> bool:
        """Is ``name`` (or, with None, ANY declared SLO) alerting?"""
        now = self._clock.now()
        with self._lock:
            states = ([self._states[name]] if name is not None
                      else list(self._states.values()))
        return any(self._evaluate(st, now)["alerting"] for st in states)

    def snapshot(self) -> dict:
        """JSON-ready live state (the ``/api/observability`` block)."""
        now = self._clock.now()
        with self._lock:
            states = list(self._states.values())
            last_ts = self._last_ts
        return {
            "windows": {
                "fast_s": list(FAST_WINDOWS_S),
                "fast_threshold": FAST_BURN_THRESHOLD,
                "slow_s": list(SLOW_WINDOWS_S),
                "slow_threshold": SLOW_BURN_THRESHOLD,
            },
            "sample_interval_s": self._interval,
            "last_sample_ts": last_ts,
            "slos": [self._evaluate(st, now) for st in states],
        }
