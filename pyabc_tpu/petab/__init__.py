"""PEtab problem importer (reference parity: ``pyabc/petab/base.py``).

Imports a PEtab parameter-estimation problem (YAML + TSV tables,
https://petab.readthedocs.io) as pyabc_tpu priors and observed data. The
reference builds on the ``petab`` + ``amici`` packages; neither is
available here, so the importer parses the PEtab files directly with
pandas/pyyaml (both baked in) — priors come from the parameter table per
the PEtab prior semantics, observations from the measurement table. The
SIMULATOR is supplied by the user (amici is a CPU/C++ code generator; the
TPU-native path is a JaxModel of the same ODEs).
"""
from .problem import PetabProblem

__all__ = ["PetabProblem"]
