"""Dependency-light PEtab problem parsing.

Scope (matches what ``pyabc/petab`` consumes from the petab package):
- parameter table -> :class:`pyabc_tpu.Distribution` prior over the
  ``estimate == 1`` parameters, honoring ``parameterScale`` and the
  ``objectivePriorType`` / ``objectivePriorParameters`` columns
  (uniform / normal / laplace and their parameterScale* variants;
  default: parameterScaleUniform over the bounds);
- measurement table -> observed summary-statistic dict
  ``{observableId: measurements ordered by time}``;
- nominal values of non-estimated parameters.
"""
from __future__ import annotations

import os

import numpy as np
import pandas as pd

from ..core.random_variables import RV, Distribution


def _split_params(val) -> list[float]:
    return [float(x) for x in str(val).split(";")]


def _scale(x: float, scale: str) -> float:
    if scale == "log10":
        return float(np.log10(x))
    if scale == "log":
        return float(np.log(x))
    return float(x)


class PetabProblem:
    """A parsed PEtab problem (YAML + TSV tables)."""

    def __init__(self, parameter_df: pd.DataFrame,
                 measurement_df: pd.DataFrame | None = None,
                 observable_df: pd.DataFrame | None = None,
                 condition_df: pd.DataFrame | None = None):
        self.parameter_df = parameter_df
        self.measurement_df = measurement_df
        self.observable_df = observable_df
        self.condition_df = condition_df

    # ------------------------------------------------------------------ io
    @classmethod
    def from_yaml(cls, path: str) -> "PetabProblem":
        import yaml

        with open(path) as fh:
            spec = yaml.safe_load(fh)
        base = os.path.dirname(os.path.abspath(path))
        problems = spec.get("problems", [spec])
        prob = problems[0]

        def _read(key, required=False):
            files = prob.get(key) or ([spec[key]] if key in spec else [])
            if isinstance(files, str):
                files = [files]
            if not files:
                if required:
                    raise ValueError(f"PEtab yaml lacks {key}")
                return None
            frames = [
                pd.read_csv(os.path.join(base, f), sep="\t") for f in files
            ]
            return pd.concat(frames, ignore_index=True)

        # parameter file may live at the top level or inside the problem
        par_files = spec.get("parameter_file") or prob.get("parameter_file")
        if isinstance(par_files, str):
            par_files = [par_files]
        parameter_df = pd.concat(
            [pd.read_csv(os.path.join(base, f), sep="\t")
             for f in par_files],
            ignore_index=True,
        )
        return cls(
            parameter_df=parameter_df,
            measurement_df=_read("measurement_files"),
            observable_df=_read("observable_files"),
            condition_df=_read("condition_files"),
        )

    # --------------------------------------------------------------- priors
    def prior(self) -> Distribution:
        """Prior over the estimated parameters ON THEIR parameterScale
        (matches the reference: pyabc parameters live on the scale the
        optimizer/estimator sees)."""
        rvs: dict[str, RV] = {}
        df = self.parameter_df
        for row in df.itertuples():
            if int(getattr(row, "estimate", 1)) != 1:
                continue
            pid = row.parameterId
            scale = str(getattr(row, "parameterScale", "lin"))
            ptype = str(getattr(row, "objectivePriorType", "") or "")
            pvals = getattr(row, "objectivePriorParameters", None)
            lb = _scale(float(row.lowerBound), scale)
            ub = _scale(float(row.upperBound), scale)
            if not ptype or ptype == "nan":
                ptype = "parameterScaleUniform"
            if ptype in ("parameterScaleUniform", "uniform"):
                if ptype == "uniform" and scale != "lin":
                    # a LINEAR-scale flat prior transformed to log scale is
                    # NOT flat (density picks up a Jacobian 1/x); silently
                    # building the flat-on-log prior would bias the
                    # posterior — refuse like the normal/laplace cases
                    raise ValueError(
                        f"{pid}: linear-scale uniform prior with "
                        f"parameterScale={scale} is not representable; "
                        "use parameterScaleUniform"
                    )
                if pvals is not None and str(pvals) not in ("nan", "None"):
                    a, b = _split_params(pvals)
                else:
                    a, b = lb, ub
                rvs[pid] = RV("uniform", a, b - a)
            elif ptype in ("parameterScaleNormal", "normal"):
                mean, sd = _split_params(pvals)
                if ptype == "normal":
                    # normal prior on the LINEAR scale; approximate on the
                    # parameter scale only for lin (exact); otherwise keep
                    # linear-scale normal truncated to the bounds via the
                    # uniform fallback is wrong — raise instead
                    if scale != "lin":
                        raise ValueError(
                            f"{pid}: linear-scale normal prior with "
                            f"parameterScale={scale} is not representable; "
                            "use parameterScaleNormal"
                        )
                rvs[pid] = RV("norm", mean, sd)
            elif ptype in ("parameterScaleLaplace", "laplace"):
                loc, b = _split_params(pvals)
                if ptype == "laplace" and scale != "lin":
                    raise ValueError(
                        f"{pid}: linear-scale laplace prior with "
                        f"parameterScale={scale} is not representable; "
                        "use parameterScaleLaplace"
                    )
                rvs[pid] = RV("laplace", loc, b)
            elif ptype == "logNormal":
                if scale != "lin":
                    raise ValueError(
                        f"{pid}: logNormal prior requires parameterScale="
                        "lin (use parameterScaleNormal with log10 scale)"
                    )
                # PEtab (mean, sd) are of log(X); RV('lognorm') follows the
                # scipy convention (s=sd_of_log, loc=0, scale=exp(mean))
                mean, sd = _split_params(pvals)
                rvs[pid] = RV("lognorm", sd, 0.0, float(np.exp(mean)))
            else:
                raise ValueError(
                    f"{pid}: unsupported objectivePriorType {ptype!r}"
                )
        if not rvs:
            raise ValueError("no estimated parameters in the PEtab table")
        return Distribution(**rvs)

    # ----------------------------------------------------------------- data
    def observed_data(self) -> dict[str, np.ndarray]:
        """Measurements grouped per observable, ordered by time (the
        summary-statistic dict an ABCSMC run conditions on)."""
        if self.measurement_df is None:
            raise ValueError("PEtab problem has no measurement table")
        out: dict[str, np.ndarray] = {}
        for oid, grp in self.measurement_df.groupby("observableId"):
            grp = grp.sort_values("time")
            out[str(oid)] = grp["measurement"].to_numpy(np.float64)
        return out

    def observation_times(self) -> dict[str, np.ndarray]:
        if self.measurement_df is None:
            raise ValueError("PEtab problem has no measurement table")
        return {
            str(oid): grp.sort_values("time")["time"].to_numpy(np.float64)
            for oid, grp in self.measurement_df.groupby("observableId")
        }

    def nominal_parameters(self) -> dict[str, float]:
        """Fixed (estimate == 0) parameters at nominal values, on their
        parameterScale."""
        out = {}
        for row in self.parameter_df.itertuples():
            if int(getattr(row, "estimate", 1)) == 0:
                out[row.parameterId] = _scale(
                    float(row.nominalValue),
                    str(getattr(row, "parameterScale", "lin")),
                )
        return out
