from .base import Sample, SampleFactory, Sampler
from .batched import BatchedSampler
from .dask_sampler import DaskDistributedSampler
from .mapping import ConcurrentFutureSampler, MappingSampler
from .multicore import (
    MulticoreEvalParallelSampler,
    MulticoreParticleParallelSampler,
    nr_cores_available,
)
from .singlecore import SingleCoreSampler

__all__ = [
    "Sampler", "Sample", "SampleFactory",
    "SingleCoreSampler", "BatchedSampler",
    "MulticoreEvalParallelSampler", "MulticoreParticleParallelSampler",
    "MappingSampler", "ConcurrentFutureSampler", "nr_cores_available",
    "DaskDistributedSampler",
]
