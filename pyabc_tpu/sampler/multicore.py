"""Multiprocess samplers for host-side (non-traceable) models.

Reference parity: ``pyabc/sampler/multicore.py::MulticoreParticleParallelSampler``,
``pyabc/sampler/multicore_evaluation_parallel.py::MulticoreEvalParallelSampler``
and ``pyabc/sampler/multicorebase.py::{nr_cores_available,
get_if_worker_healthy}``.

These exist for capability parity: arbitrary Python simulators (SimpleModel,
external processes) that cannot enter the XLA path still get single-node
parallelism. The statistical contract is identical to the reference:
evaluation-parallel workers share atomic counters, and the accepted set is
sorted by eval-slot id with deterministic overshoot trim, keeping the
dynamic scheduler unbiased (SURVEY.md §3.4). For traceable models,
`BatchedSampler` supersedes these by orders of magnitude.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import queue as queue_mod
import weakref

import numpy as np

from ..core.population import Particle
from .base import HostRecords, Sample, Sampler, particle_record

DONE = "__done__"


def nr_cores_available() -> int:
    """Reference nr_cores_available: respects sched_getaffinity if present."""
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover - non-Linux
        return mp.cpu_count()


def get_if_worker_healthy(workers, q, timeout: float = 1800.0):
    """Get from q, re-raising child failures (reference get_if_worker_healthy)."""
    while True:
        try:
            return q.get(timeout=5.0)
        except queue_mod.Empty:
            if not any(w.is_alive() for w in workers):
                raise RuntimeError(
                    "all sampler workers died without producing results"
                )


def _eval_loop(simulate_one, n_request, n_eval, n_acc, out_q,
               record_rejected, rej_q):
    """Evaluation-parallel inner loop (shared by the one-shot fork worker
    and the persistent pool worker): claim a global eval slot, simulate,
    count acceptances on the shared counter until n_request is reached."""
    while True:
        with n_acc.get_lock():
            if n_acc.value >= n_request:
                break
        with n_eval.get_lock():
            slot = n_eval.value
            n_eval.value += 1
        particle = simulate_one()
        if record_rejected:
            rej_q.put(particle_record(particle))
        if particle.accepted:
            with n_acc.get_lock():
                n_acc.value += 1
            out_q.put((slot, particle))
    out_q.put(DONE)


def _quota_loop(simulate_one, quota, out_q, record_rejected, rej_q) -> int:
    """Particle-parallel inner loop (shared like _eval_loop): fill a fixed
    acceptance quota; returns the local evaluation count."""
    produced = 0
    n_eval = 0
    while produced < quota:
        particle = simulate_one()
        n_eval += 1
        if record_rejected:
            rej_q.put(particle_record(particle))
        if particle.accepted:
            produced += 1
            out_q.put((None, particle))
    out_q.put((DONE, n_eval))
    return n_eval


def _eval_parallel_worker(simulate_one, n_request, n_eval, n_acc, out_q,
                          seed, record_rejected, rej_q):
    simulate_one = _load_payload(simulate_one)
    np.random.seed(seed)
    _eval_loop(simulate_one, n_request, n_eval, n_acc, out_q,
               record_rejected, rej_q)


def _particle_parallel_worker(simulate_one, quota, out_q, seed,
                              record_rejected, rej_q):
    simulate_one = _load_payload(simulate_one)
    np.random.seed(seed)
    _quota_loop(simulate_one, quota, out_q, record_rejected, rej_q)


def _load_payload(simulate_one):
    """Worker-side inverse of the spawn-context cloudpickle wrapping."""
    if isinstance(simulate_one, bytes):
        import cloudpickle

        return cloudpickle.loads(simulate_one)
    return simulate_one


def _pool_worker(task_q, out_q, rej_q, n_eval, n_acc):
    """Persistent pool worker: serves one generation task at a time until
    the None sentinel. Spawn/forkserver pay the interpreter+import cost
    ONCE per sampler instead of once per generation (the per-generation
    respawn is ~10x a small generation's work)."""
    while True:
        task = task_q.get()
        if task is None:
            break
        kind, payload, arg, seed, record_rejected = task
        simulate_one = _load_payload(payload)
        np.random.seed(seed)
        if kind == "eval":
            _eval_loop(simulate_one, arg, n_eval, n_acc, out_q,
                       record_rejected, rej_q)
        else:  # quota
            _quota_loop(simulate_one, arg, out_q, record_rejected, rej_q)
        if record_rejected:
            # cross-queue delivery order is not guaranteed (separate
            # feeder threads), and pool workers never exit — a DONE per
            # TASK on the record queue is the drain signal
            # (_drain_rejected_pool counts tasks, not workers: a fast
            # worker may serve several tasks of one generation)
            rej_q.put(DONE)


def _shutdown_pool(workers, task_q):
    """Stop sentinels + join; terminate stragglers. Module-level so a
    weakref.finalize can run it at interpreter exit BEFORE multiprocessing
    joins non-daemon children (a daemon=False pool would otherwise hang
    shutdown: workers block forever on task_q.get())."""
    for _ in workers:
        try:
            task_q.put(None)
        except (ValueError, OSError):  # queue already closed
            break
    for w in workers:
        w.join(timeout=5.0)
        if w.is_alive():
            w.terminate()


class _MulticoreBase(Sampler):
    """start_method: 'spawn' (default) / 'forkserver' run a PERSISTENT
    worker pool — robust against forked-backend deadlocks by construction
    (the closure travels via cloudpickle into fresh interpreters), with
    the startup cost amortized over the whole run. 'fork' (opt-in,
    reference behavior) forks per generation — cheap startup and no
    pickling requirement on the closure, guarded by a pre-fork
    jax-reference scan."""

    def __init__(self, n_procs: int | None = None, daemon: bool = True,
                 start_method: str = "spawn", check_fork_safety: bool = True):
        super().__init__()
        self.n_procs = n_procs if n_procs is not None else nr_cores_available()
        self.daemon = daemon
        self.start_method = start_method
        self.check_fork_safety = check_fork_safety
        self._pool = None

    def _resolve(self, simulate_one):
        if hasattr(simulate_one, "host_simulate_one"):
            simulate_one = simulate_one.host_simulate_one
        if self.start_method == "fork" and self.check_fork_safety:
            # fail fast (with the offending access path) instead of
            # deadlocking a forked child on the parent's XLA mutexes
            from ..utils.fork_safety import assert_fork_safe

            assert_fork_safe(simulate_one)
        elif self.start_method != "fork":
            import cloudpickle

            simulate_one = cloudpickle.dumps(simulate_one)
        return simulate_one

    # --------------------------------------------------- persistent pool
    def _ensure_pool(self):
        """Start (or reuse) the persistent worker pool; counters are reset
        by the caller between generations while workers idle on the task
        queue."""
        if self._pool is not None:
            if all(w.is_alive() for w in self._pool[1]):
                return self._pool
            self.stop()
        ctx = mp.get_context(self.start_method)
        task_q, out_q, rej_q = ctx.Queue(), ctx.Queue(), ctx.Queue()
        n_eval, n_acc = ctx.Value("i", 0), ctx.Value("i", 0)
        workers = [
            ctx.Process(target=_pool_worker,
                        args=(task_q, out_q, rej_q, n_eval, n_acc),
                        daemon=self.daemon)
            for _ in range(self.n_procs)
        ]
        for w in workers:
            w.start()
        self._pool = (ctx, workers, task_q, out_q, rej_q, n_eval, n_acc)
        # runs on GC of the sampler AND at interpreter shutdown (before
        # multiprocessing's atexit join of non-daemon children)
        self._pool_finalizer = weakref.finalize(
            self, _shutdown_pool, workers, task_q
        )
        return self._pool

    def stop(self) -> None:
        """Shut the pool down (None sentinel per worker, then join)."""
        if self._pool is None:
            return
        fin = getattr(self, "_pool_finalizer", None)
        if fin is not None:
            fin.detach()
            self._pool_finalizer = None
        _shutdown_pool(self._pool[1], self._pool[2])
        self._pool = None

    def __getstate__(self):
        # the pool (processes/queues/finalizer) never travels; a pickled
        # sampler re-creates it lazily on first use
        state = self.__dict__.copy()
        state["_pool"] = None
        state["_pool_finalizer"] = None
        return state

    def _pool_get(self, workers, q):
        """Get from q; a pool worker that exited ABNORMALLY may have held a
        dequeued task whose DONE will never arrive, so tear down and raise
        (reference ``get_if_worker_healthy`` semantics: any non-zero child
        exitcode is fatal). Idle-and-alive or cleanly-exited workers never
        trip this."""
        while True:
            try:
                return q.get(timeout=5.0)
            except queue_mod.Empty:
                if any(w.exitcode not in (0, None) for w in workers):
                    self.stop()
                    raise RuntimeError(
                        "a sampler pool worker died mid-generation"
                    )

    def _run_pool(self, kind, payload, args, seeds, sample):
        """One generation on the persistent pool: reset shared counters,
        enqueue one task per worker slot, collect until every task's DONE.
        Tasks are pulled greedily, so DONE sentinels are counted per TASK
        (a fast worker may serve two tasks back-to-back).

        Any abort between enqueue and full drain (KeyboardInterrupt, a
        caller-side exception) tears the pool down: a reused pool would
        carry live tasks of the aborted generation whose 'eval' loops
        revive when the next generation resets the shared n_acc counter,
        mixing stale-closure particles and extra DONE sentinels into the
        new generation's queues."""
        _, workers, task_q, out_q, rej_q, n_eval, n_acc = self._ensure_pool()
        n_eval.value = 0
        n_acc.value = 0
        try:
            n_tasks = 0
            for i, arg in enumerate(args):
                if arg <= 0:
                    continue
                task_q.put((kind, payload, arg, int(seeds[i]),
                            sample.record_rejected))
                n_tasks += 1
            collected: list[tuple] = []
            done = 0
            n_evals = 0
            while done < n_tasks:
                item = self._pool_get(workers, out_q)
                if isinstance(item, str) and item == DONE:
                    done += 1
                elif isinstance(item, tuple) and item[0] == DONE:
                    n_evals += item[1]
                    done += 1
                else:
                    collected.append(item)
            if kind == "eval":
                n_evals = n_eval.value
            self._drain_rejected_pool(sample, workers, rej_q, n_tasks)
        except BaseException:
            self.stop()
            raise
        return collected, n_evals

    def _drain_rejected_pool(self, sample: Sample, workers, rej_q,
                             n_tasks) -> None:
        """Collect rejected records until every task's DONE sentinel."""
        if not sample.record_rejected:
            return
        records = []
        done = 0
        while done < n_tasks:
            item = self._pool_get(workers, rej_q)
            if isinstance(item, str) and item == DONE:
                done += 1
            else:
                records.append(item)
        if records:
            sample.host_all_records = HostRecords.from_tuples(records)

    def _drain_rejected(self, sample: Sample, rej_q, workers=()) -> None:
        """Drain the rejected-record queue BEFORE joining workers: a child
        cannot exit while its queue feeder thread still holds undelivered
        records (the pipe is small), so join-before-drain deadlocks.
        (fork path only — pool workers signal with DONE sentinels.)"""
        if not sample.record_rejected:
            return
        records = []
        while True:
            try:
                records.append(rej_q.get_nowait())
            except queue_mod.Empty:
                if not any(w.is_alive() for w in workers):
                    break
                import time

                time.sleep(0.005)
        if records:
            sample.host_all_records = HostRecords.from_tuples(records)


class MulticoreEvalParallelSampler(_MulticoreBase):
    """Evaluation-parallel dynamic multiprocessing sampler (the reference's
    recommended multicore sampler and the BASELINE.json baseline)."""

    def sample_until_n_accepted(self, n, simulate_one, t, *, max_eval=np.inf,
                                all_accepted=False, ana_vars=None) -> Sample:
        simulate_one = self._resolve(simulate_one)
        sample = self.sample_factory()
        seeds = np.random.randint(0, 2**31 - 1, size=self.n_procs)
        if self.start_method != "fork":
            collected, n_evals = self._run_pool(
                "eval", simulate_one, [n] * self.n_procs, seeds, sample
            )
        else:
            ctx = mp.get_context(self.start_method)
            n_eval = ctx.Value("i", 0)
            n_acc = ctx.Value("i", 0)
            out_q = ctx.Queue()
            rej_q = ctx.Queue()
            workers = [
                ctx.Process(
                    target=_eval_parallel_worker,
                    args=(simulate_one, n, n_eval, n_acc, out_q,
                          int(seeds[i]), sample.record_rejected, rej_q),
                    daemon=self.daemon,
                )
                for i in range(self.n_procs)
            ]
            for w in workers:
                w.start()
            collected = []
            done = 0
            while done < self.n_procs:
                item = get_if_worker_healthy(workers, out_q)
                if item == DONE:
                    done += 1
                else:
                    collected.append(item)
            self._drain_rejected(sample, rej_q, workers)
            for w in workers:
                w.join()
            n_evals = n_eval.value
        self.nr_evaluations_ = n_evals
        # deterministic slot ordering + overshoot trim (reference invariant)
        collected.sort(key=lambda x: x[0])
        collected = collected[:n]
        sample.accepted_particles = [p for _, p in collected]
        sample.accepted_proposal_ids = np.asarray([s for s, _ in collected])
        return sample


class MulticoreParticleParallelSampler(_MulticoreBase):
    """Particle-parallel static multiprocessing sampler (reference
    MulticoreParticleParallelSampler): each worker fills a fixed quota."""

    def sample_until_n_accepted(self, n, simulate_one, t, *, max_eval=np.inf,
                                all_accepted=False, ana_vars=None) -> Sample:
        simulate_one = self._resolve(simulate_one)
        sample = self.sample_factory()
        quotas = [n // self.n_procs] * self.n_procs
        for i in range(n % self.n_procs):
            quotas[i] += 1
        seeds = np.random.randint(0, 2**31 - 1, size=self.n_procs)
        if self.start_method != "fork":
            collected, n_eval = self._run_pool(
                "quota", simulate_one, quotas, seeds, sample
            )
            particles = [p for _, p in collected]
        else:
            ctx = mp.get_context(self.start_method)
            out_q = ctx.Queue()
            rej_q = ctx.Queue()
            workers = [
                ctx.Process(
                    target=_particle_parallel_worker,
                    args=(simulate_one, quotas[i], out_q, int(seeds[i]),
                          sample.record_rejected, rej_q),
                    daemon=self.daemon,
                )
                for i in range(self.n_procs)
                if quotas[i] > 0
            ]
            for w in workers:
                w.start()
            particles = []
            n_eval = 0
            done = 0
            while done < len(workers):
                item = get_if_worker_healthy(workers, out_q)
                if isinstance(item, tuple) and item[0] == DONE:
                    n_eval += item[1]
                    done += 1
                else:
                    particles.append(item[1])
            self._drain_rejected(sample, rej_q, workers)
            for w in workers:
                w.join()
        self.nr_evaluations_ = n_eval
        sample.accepted_particles = particles[:n]
        sample.accepted_proposal_ids = np.arange(len(sample.accepted_particles))
        return sample
