"""Multiprocess samplers for host-side (non-traceable) models.

Reference parity: ``pyabc/sampler/multicore.py::MulticoreParticleParallelSampler``,
``pyabc/sampler/multicore_evaluation_parallel.py::MulticoreEvalParallelSampler``
and ``pyabc/sampler/multicorebase.py::{nr_cores_available,
get_if_worker_healthy}``.

These exist for capability parity: arbitrary Python simulators (SimpleModel,
external processes) that cannot enter the XLA path still get single-node
parallelism. The statistical contract is identical to the reference:
evaluation-parallel workers share atomic counters, and the accepted set is
sorted by eval-slot id with deterministic overshoot trim, keeping the
dynamic scheduler unbiased (SURVEY.md §3.4). For traceable models,
`BatchedSampler` supersedes these by orders of magnitude.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import queue as queue_mod

import numpy as np

from ..core.population import Particle
from .base import HostRecords, Sample, Sampler, particle_record

DONE = "__done__"


def nr_cores_available() -> int:
    """Reference nr_cores_available: respects sched_getaffinity if present."""
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover - non-Linux
        return mp.cpu_count()


def get_if_worker_healthy(workers, q, timeout: float = 1800.0):
    """Get from q, re-raising child failures (reference get_if_worker_healthy)."""
    while True:
        try:
            return q.get(timeout=5.0)
        except queue_mod.Empty:
            if not any(w.is_alive() for w in workers):
                raise RuntimeError(
                    "all sampler workers died without producing results"
                )


def _eval_parallel_worker(simulate_one, n_request, n_eval, n_acc, out_q,
                          seed, record_rejected, rej_q):
    simulate_one = _load_payload(simulate_one)
    np.random.seed(seed)
    while True:
        with n_acc.get_lock():
            if n_acc.value >= n_request:
                break
        with n_eval.get_lock():
            slot = n_eval.value
            n_eval.value += 1
        particle = simulate_one()
        if record_rejected:
            rej_q.put(particle_record(particle))
        if particle.accepted:
            with n_acc.get_lock():
                n_acc.value += 1
            out_q.put((slot, particle))
    out_q.put(DONE)


def _particle_parallel_worker(simulate_one, quota, out_q, seed,
                              record_rejected, rej_q):
    simulate_one = _load_payload(simulate_one)
    np.random.seed(seed)
    produced = 0
    n_eval = 0
    while produced < quota:
        particle = simulate_one()
        n_eval += 1
        if record_rejected:
            rej_q.put(particle_record(particle))
        if particle.accepted:
            produced += 1
            out_q.put((None, particle))
    out_q.put((DONE, n_eval))


def _load_payload(simulate_one):
    """Worker-side inverse of the spawn-context cloudpickle wrapping."""
    if isinstance(simulate_one, bytes):
        import cloudpickle

        return cloudpickle.loads(simulate_one)
    return simulate_one


class _MulticoreBase(Sampler):
    """start_method: 'fork' (default, reference behavior — cheap worker
    startup, guarded by a pre-fork jax-reference scan of the closure) or
    'spawn'/'forkserver' (robust against forked-backend deadlocks by
    construction; the closure travels via cloudpickle, workers re-import)."""

    def __init__(self, n_procs: int | None = None, daemon: bool = True,
                 start_method: str = "fork", check_fork_safety: bool = True):
        super().__init__()
        self.n_procs = n_procs if n_procs is not None else nr_cores_available()
        self.daemon = daemon
        self.start_method = start_method
        self.check_fork_safety = check_fork_safety

    def _resolve(self, simulate_one):
        if hasattr(simulate_one, "host_simulate_one"):
            simulate_one = simulate_one.host_simulate_one
        if self.start_method == "fork" and self.check_fork_safety:
            # fail fast (with the offending access path) instead of
            # deadlocking a forked child on the parent's XLA mutexes
            from ..utils.fork_safety import assert_fork_safe

            assert_fork_safe(simulate_one)
        elif self.start_method != "fork":
            import cloudpickle

            simulate_one = cloudpickle.dumps(simulate_one)
        return simulate_one

    def _drain_rejected(self, sample: Sample, rej_q, workers=()) -> None:
        """Drain the rejected-record queue BEFORE joining workers: a child
        cannot exit while its queue feeder thread still holds undelivered
        records (the pipe is small), so join-before-drain deadlocks."""
        if not sample.record_rejected:
            return
        records = []
        while True:
            try:
                records.append(rej_q.get_nowait())
            except queue_mod.Empty:
                if not any(w.is_alive() for w in workers):
                    break
                import time

                time.sleep(0.005)
        if records:
            sample.host_all_records = HostRecords.from_tuples(records)


class MulticoreEvalParallelSampler(_MulticoreBase):
    """Evaluation-parallel dynamic multiprocessing sampler (the reference's
    recommended multicore sampler and the BASELINE.json baseline)."""

    def sample_until_n_accepted(self, n, simulate_one, t, *, max_eval=np.inf,
                                all_accepted=False, ana_vars=None) -> Sample:
        simulate_one = self._resolve(simulate_one)
        sample = self.sample_factory()
        ctx = mp.get_context(self.start_method)
        n_eval = ctx.Value("i", 0)
        n_acc = ctx.Value("i", 0)
        out_q = ctx.Queue()
        rej_q = ctx.Queue()
        seeds = np.random.randint(0, 2**31 - 1, size=self.n_procs)
        workers = [
            ctx.Process(
                target=_eval_parallel_worker,
                args=(simulate_one, n, n_eval, n_acc, out_q, int(seeds[i]),
                      sample.record_rejected, rej_q),
                daemon=self.daemon,
            )
            for i in range(self.n_procs)
        ]
        for w in workers:
            w.start()
        collected: list[tuple[int, Particle]] = []
        done = 0
        while done < self.n_procs:
            item = get_if_worker_healthy(workers, out_q)
            if item == DONE:
                done += 1
            else:
                collected.append(item)
        self._drain_rejected(sample, rej_q, workers)
        for w in workers:
            w.join()
        self.nr_evaluations_ = n_eval.value
        # deterministic slot ordering + overshoot trim (reference invariant)
        collected.sort(key=lambda x: x[0])
        collected = collected[:n]
        sample.accepted_particles = [p for _, p in collected]
        sample.accepted_proposal_ids = np.asarray([s for s, _ in collected])
        return sample


class MulticoreParticleParallelSampler(_MulticoreBase):
    """Particle-parallel static multiprocessing sampler (reference
    MulticoreParticleParallelSampler): each worker fills a fixed quota."""

    def sample_until_n_accepted(self, n, simulate_one, t, *, max_eval=np.inf,
                                all_accepted=False, ana_vars=None) -> Sample:
        simulate_one = self._resolve(simulate_one)
        sample = self.sample_factory()
        ctx = mp.get_context(self.start_method)
        out_q = ctx.Queue()
        rej_q = ctx.Queue()
        quotas = [n // self.n_procs] * self.n_procs
        for i in range(n % self.n_procs):
            quotas[i] += 1
        seeds = np.random.randint(0, 2**31 - 1, size=self.n_procs)
        workers = [
            ctx.Process(
                target=_particle_parallel_worker,
                args=(simulate_one, quotas[i], out_q, int(seeds[i]),
                      sample.record_rejected, rej_q),
                daemon=self.daemon,
            )
            for i in range(self.n_procs)
            if quotas[i] > 0
        ]
        for w in workers:
            w.start()
        particles: list[Particle] = []
        n_eval = 0
        done = 0
        while done < len(workers):
            item = get_if_worker_healthy(workers, out_q)
            if isinstance(item, tuple) and item[0] == DONE:
                n_eval += item[1]
                done += 1
            else:
                particles.append(item[1])
        self._drain_rejected(sample, rej_q, workers)
        for w in workers:
            w.join()
        self.nr_evaluations_ = n_eval
        sample.accepted_particles = particles[:n]
        sample.accepted_proposal_ids = np.arange(len(sample.accepted_particles))
        return sample
