"""Single-core scalar sampler — the reference baseline semantics.

Reference parity: ``pyabc/sampler/singlecore.py::SingleCoreSampler``. Loops
the scalar ``simulate_one`` closure until n acceptances. Serves arbitrary
Python models and acts as the statistical oracle the batched device sampler
is tested against.
"""
from __future__ import annotations

import numpy as np

from ..core.population import Particle
from .base import HostRecords, Sample, Sampler


class SingleCoreSampler(Sampler):
    def __init__(self, check_max_eval: bool = False):
        super().__init__()
        self.check_max_eval = check_max_eval

    def sample_until_n_accepted(self, n, simulate_one, t, *, max_eval=np.inf,
                                all_accepted=False, ana_vars=None) -> Sample:
        if hasattr(simulate_one, "host_simulate_one"):
            simulate_one = simulate_one.host_simulate_one
        sample = self.sample_factory()
        accepted: list[Particle] = []
        accepted_ids: list[int] = []
        records: list[Particle] = []
        nr_eval = 0
        while len(accepted) < n:
            if self.check_max_eval and nr_eval >= max_eval:
                break
            particle = simulate_one()
            slot = nr_eval
            nr_eval += 1
            if sample.record_rejected:
                records.append(particle)
            if particle.accepted or all_accepted:
                accepted.append(particle)
                accepted_ids.append(slot)
        self.nr_evaluations_ = nr_eval
        sample.accepted_particles = accepted  # list view for host consumers
        sample.accepted_proposal_ids = np.asarray(accepted_ids)
        if sample.record_rejected and records:
            sample.host_all_records = HostRecords.from_particles(records)
        return sample
